"""Tiled (out-of-core) execution — the workfile-manager / spill analog.

The reference survives bigger-than-memory queries by spilling operator state
to workfiles (src/backend/utils/workfile_manager/workfile_mgr.c, the batch
discipline of nodeHash.c) under a vmem red zone
(src/backend/utils/mmgr/redzone_handler.c). The XLA translation cannot page
a running program, so the spill boundary moves to PLAN TIME: when the
admission estimator (exec/resource.py) rejects a plan, this module re-plans
it as a STREAM OF FIXED-SHAPE TILES —

- the plan's big probe-side scan becomes the tile stream: host RAM (or
  micro-partition files, for cold tables) holds the table; the device only
  ever sees one tile of ``tile_rows`` rows;
- every spine join's build subtree is computed ONCE by a prelude program
  and its (bounded, estimated-and-admitted) result arrays stay resident;
- one jitted STEP program runs per tile: spine joins/filters/projections,
  a partial aggregation, and a merge into a fixed-capacity accumulator
  (the combine-function discipline of the distributed two-stage agg,
  plan/distribute.py:_split_aggs — partials merge associatively, so any
  tile order and count gives the same answer);
- a finalize program applies the post-aggregation chain (HAVING / ORDER BY /
  LIMIT / avg = sum/count) to the accumulator.

Per-tile capacities keep the engine's checked-overflow discipline: a tile
that overflows its expansion-join or group buffers raises, never truncates.
Peak device memory is the admitted estimate: resident builds + one tile's
working set + the accumulator — independent of the streamed table's size.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from cloudberry_tpu.columnar.batch import ColumnBatch
from cloudberry_tpu.exec import bufferpool as BUF
from cloudberry_tpu.exec import executor as X
from cloudberry_tpu.exec import kernels as K
from cloudberry_tpu.exec import scanpipe as SP
from cloudberry_tpu.exec import tilepipe as TP
from cloudberry_tpu.exec.resource import estimate_plan_memory
from cloudberry_tpu.plan import expr as ex
from cloudberry_tpu.plan import nodes as N
from cloudberry_tpu.utils.faultinject import fault_point
from cloudberry_tpu.plan.distribute import (_all_exprs, _finalize_project,
                                            _split_aggs)

_MAX_TILE = 1 << 22
_MIN_TILE = 1 << 12

# The declared set of tiled executor modes that snapshot carried state
# into the recovery store (_TileShape.mode values whose tick() paths
# checkpoint). exec/recovery.py REPLACEABLE must cover every entry —
# the plan verifier (plan/verify.py recovery-mode-unreplaceable) and
# graftlint's planprops pass pin the two tables together BOTH ways, so
# a new checkpointing mode cannot ship without a degraded-mesh
# re-placement rule.
CHECKPOINT_MODES = ("agg", "topn", "sort", "window")


class _AccLeaf(N.PlanNode):
    """Plan leaf standing for the accumulator in the finalize program."""

    def title(self):
        return "TileAccumulator"


@dataclass
class _TileShape:
    """Everything the rewrite discovered about the plan. Two modes:
    "agg" streams into a partial-aggregation accumulator; "topn" streams
    into a fixed top-N row accumulator (ORDER BY + LIMIT over the spine,
    no aggregation — the tuplesort bounded-heap analog, nodeSort.c
    bounded mode)."""

    agg: Optional[N.PAgg]             # the streamed aggregation (agg mode)
    post: list[N.PlanNode]            # chain above agg/sort, root first
    spine: list[N.PlanNode]           # agg.child .. just above the stream
    stream: N.PScan                   # the tiled scan
    builds: list[N.PlanNode]          # spine joins' build subtrees
    stream_rows: int = 0              # whole-stream rows (floor scaling)
    partial_plan: N.PlanNode = None   # type: ignore[assignment]
    merge_specs: list = field(default_factory=list)
    finalize: dict = field(default_factory=dict)
    root: N.PlanNode = None           # type: ignore[assignment]
    g_cap: int = 0                    # accumulator capacity (groups / rows)
    mode: str = "agg"
    sortnode: Optional[N.PSort] = None  # topn/sort: the (synthetic) sort
    winnode: Optional[N.PWindow] = None  # window mode: BOTTOM of the stack
    wintop: Optional[N.PWindow] = None   # window mode: TOP of the stack
    n_ckeys: int = 0                  # window mode: chunk-key count


def plan_tiled(plan: N.PlanNode, session) -> Optional["TiledExecutable"]:
    """Try to re-plan an admission-rejected statement for tiled execution.
    Returns None when the plan shape or the budget cannot support it."""
    if not session.config.resource.enable_spill:
        return None
    if session.config.n_segments > 1:
        from cloudberry_tpu.exec.tiled_dist import plan_tiled_dist

        return plan_tiled_dist(plan, session)
    if getattr(plan, "_direct_segment", None) is not None:
        return None
    from cloudberry_tpu.plan.pointlookup import unbind_point_lookups

    # the tile stream and resident loads key inputs by TABLE NAME: a
    # point-sliced scan would miss its $pt input — restore full scans
    unbind_point_lookups(plan)
    # join-index inputs are a one-shot-executor feature: tiled prelude/
    # step programs assemble their own input dicts, so drop the
    # annotations — joins then compute their argsort in-program
    # (exec/joinindex.py documents the fallback contract). The strip is
    # speculative: a decline below restores the stash so the one-shot
    # fallback keeps its cached indexes.
    from cloudberry_tpu.exec.joinindex import (restore_join_index,
                                               stash_join_index,
                                               strip_join_index)
    shape = _analyze(plan)
    if shape is None:
        return None
    # whole-run growth marks (session._run_with_growth) are meaningless at
    # tile scale and would poison the per-tile floor — the tiled adaptive
    # loop re-learns spine buffer sizes itself. Build-side joins keep
    # theirs: the prelude still computes whole builds.
    for node in shape.spine:
        if isinstance(node, N.PJoin) and hasattr(node, "_min_out_cap"):
            del node._min_out_cap
    stash = stash_join_index(plan)
    strip_join_index(plan)
    t = _plan_by_mode(shape, session)
    if t is None:
        restore_join_index(stash)
    return t


def _plan_by_mode(shape: "_TileShape", session):
    if shape.mode == "topn":
        t = _plan_topn(shape, session)
        if t is not None:
            return t
        # the LIMIT+OFFSET exceeds any resident accumulator: fall back
        # to the full external sort and apply the limit host-side
        shape.mode = "sort"
        shape.g_cap = 0
    if shape.mode == "sort":
        return _plan_sort(shape, session)
    if shape.mode == "window":
        return _plan_window(shape, session)
    try:
        partial_aggs, final_aggs, finalize = _split_aggs(shape.agg.aggs)
    except ValueError:
        return None  # an aggregate with no partial/merge decomposition
    shape.finalize = finalize
    shape.merge_specs = [K.AggSpec(call.func, name)
                         for name, call in final_aggs]

    # Accumulator capacity: the binder's agg capacity is the worst case
    # (child rows) — useless as a resident buffer. Size from the NDV-based
    # group estimate with 4× headroom; a merge overflow at runtime grows it
    # and retries (the nodeHash.c increase-nbatch discipline) rather than
    # ever returning truncated groups.
    from cloudberry_tpu.plan.cost import estimate_rows

    est_groups = estimate_rows(shape.agg, session.catalog)
    shape.g_cap = int(min(shape.agg.capacity,
                          max(1024, 4 * int(est_groups) + 1)))

    # per-tile partial aggregation over the spine (mode/fields mirror the
    # distributed two-stage construction, plan/distribute.py:532)
    partial = N.PAgg(shape.agg.child, shape.agg.group_keys, partial_aggs,
                     capacity=shape.agg.capacity, mode="partial")
    partial.fields = [
        N.PlanField(n, e.dtype, _expr_dict(shape.agg.child, e))
        for n, e in shape.agg.group_keys
    ] + [N.PlanField(n, c.dtype, None) for n, c in partial_aggs]
    shape.partial_plan = partial

    budget = session.config.resource.query_mem_bytes
    tile_rows = _choose_tile(shape, budget)
    if tile_rows is None:
        return None

    # finalize plan: (acc leaf) -> finalize project -> original post chain
    leaf = _AccLeaf()
    leaf.fields = list(partial.fields)
    fproj = _finalize_project(leaf, shape.agg, finalize)
    if shape.post:
        shape.post[-1].child = fproj
        shape.root = shape.post[0]
    else:
        shape.root = fproj

    return TiledExecutable(shape, session, tile_rows, budget)


def _plan_topn(shape: _TileShape, session) -> Optional["TopNTiledExecutable"]:
    """Top-N streaming: the accumulator holds the best LIMIT+OFFSET rows
    of the sort's child so far; each tile merges through one bounding
    sort (tuplesort bounded-heap role, nodeSort.c). The post chain above
    the sort (LIMIT, projections) finalizes over the sorted accumulator."""
    sort = shape.sortnode
    shape.partial_plan = sort.child
    budget = session.config.resource.query_mem_bytes
    tile_rows = _choose_tile(shape, budget)
    if tile_rows is None:
        return None  # LIMIT too large for a resident accumulator

    # merge program plan: bounding sort over (acc ∪ tile output)
    mleaf = _AccLeaf()
    mleaf.fields = list(sort.child.fields)
    msort = N.PSort(mleaf, list(sort.keys))
    msort.fields = list(mleaf.fields)
    shape.finalize = {"mleaf": mleaf, "msort": msort}

    # finalize plan: (sorted acc leaf) -> original post chain above sort
    fleaf = _AccLeaf()
    fleaf.fields = list(sort.child.fields)
    shape.post[-1].child = fleaf  # post is non-empty: the LIMIT lives there
    shape.root = shape.post[0]
    return TopNTiledExecutable(shape, session, tile_rows, budget)


def host_post_ok(nodes, sort_keys=None) -> bool:
    """True when a chain above a spilled sort can apply HOST-SIDE after
    the merge pass: column-pruning projections, LIMIT/OFFSET, gather
    motions (no-ops — the host already pools every segment's rows) and
    sorts on the same keys (already satisfied by the merge order). One
    predicate shared by the single-node and distributed recognizers so
    they cannot drift from host_apply_post."""
    for nd in nodes:
        if isinstance(nd, N.PLimit):
            continue
        if isinstance(nd, N.PProject) and all(
                isinstance(e, ex.ColumnRef) for _, e in nd.exprs):
            continue
        if isinstance(nd, N.PMotion) and nd.kind == "gather":
            continue
        if sort_keys is not None and isinstance(nd, N.PSort) \
                and repr(nd.keys) == repr(sort_keys):
            continue
        return False
    return True


def host_apply_post(nodes, cols: dict) -> dict:
    """Apply a host_post_ok-validated chain bottom-up over host arrays
    (gathers and merge-order sorts are no-ops here)."""
    for node in reversed(nodes):
        if isinstance(node, N.PLimit):
            total = len(next(iter(cols.values()))) if cols else 0
            lo = min(node.offset, total)
            cols = {nm: a[lo:lo + node.limit] for nm, a in cols.items()}
        elif isinstance(node, N.PProject):
            cols = {out: cols[e.name] for out, e in node.exprs}
    return cols


def merge_sorted_runs(runs: dict, key_runs: list, fields, nkeys: int):
    """The external sort's merge pass, shared by the single-node and
    distributed executables: one stable host key sort over the pooled
    runs (np.lexsort: LAST key is primary — mirror sort_indices).
    Returns (sorted columns, sorted normalized keys)."""
    names = list(runs)
    if not names or not any(len(r) for r in runs[names[0]]):
        cols = {f.name: np.zeros((0,), dtype=f.type.np_dtype)
                for f in fields}
        return cols, [np.zeros((0,), dtype=np.uint64)
                      for _ in range(nkeys)]
    karr = [np.concatenate(kr) for kr in key_runs]
    order = np.lexsort(tuple(reversed(karr)))
    cols = {nm: np.concatenate(runs[nm])[order] for nm in names}
    return cols, [k[order] for k in karr]


def _full_sort_shape(chain: list):
    """Unbounded ORDER BY shape: the lowest sort, with only a
    host-applicable chain above it — the external-sort path
    (tuplesort.c's spill-to-tape mode; here host RAM is the tape: the
    device streams spine tiles and emits rows plus their
    order-normalized u64 keys, the host keeps the runs and one C-speed
    stable key sort is the merge pass). Returns the sort node, or None
    when the chain has a different shape."""
    sort_i = next((i for i in range(len(chain) - 1, -1, -1)
                   if isinstance(chain[i], N.PSort)), None)
    if sort_i is None:
        return None
    if any(not isinstance(n, (N.PProject, N.PFilter))
           for n in chain[sort_i + 1:]):
        return None
    if not host_post_ok(chain[:sort_i], chain[sort_i].keys):
        return None
    return chain[sort_i]


def _plan_sort(shape: _TileShape,
               session) -> Optional["SortTiledExecutable"]:
    """Full external sort: stream the spine, keep every surviving row
    (plus order-normalized keys) in host RAM, one stable key sort as the
    merge pass, then apply the post chain (column pruning + LIMIT)
    host-side. The device budget covers resident builds + one tile's
    working set; the result itself lives host-side — the workfile."""
    # the topn fallback arrives here WITHOUT _full_sort_shape's chain
    # validation: re-check that everything above the sort is
    # host-applicable
    if not host_post_ok(shape.post, shape.sortnode.keys):
        return None
    shape.partial_plan = shape.sortnode.child
    budget = session.config.resource.query_mem_bytes
    tile_rows = _choose_tile(shape, budget)
    if tile_rows is None:
        return None
    shape.root = shape.post[0] if shape.post else shape.sortnode
    return SortTiledExecutable(shape, session, tile_rows, budget)


def _plan_window(shape: _TileShape,
                 session) -> Optional["WindowTiledExecutable"]:
    """Window spill: phase one is the external-sort stream grouped by
    the partition keys COMMON to every spec in the stack; phase two
    windows whole-partition chunks on device (WindowTiledExecutable) —
    each chunk re-sorts per spec, so only the grouping must align. A
    stack with no common partition key is one giant partition — nothing
    bounds its working set, so it cannot stream (the reference buffers
    that case too)."""
    bottom = shape.winnode
    # common partition keys across the stack, matched structurally;
    # expr objects come from the BOTTOM spec (they bind over its child)
    common = {repr(pk): pk for pk in bottom.partition_keys}
    node = shape.wintop
    while isinstance(node, N.PWindow):
        here = {repr(pk) for pk in node.partition_keys}
        common = {k: v for k, v in common.items() if k in here}
        node = node.child
    if not common:
        return None
    ckeys = list(common.values())
    srt = N.PSort(bottom.child, [(ck, True) for ck in ckeys])
    srt.fields = list(bottom.child.fields)
    shape.sortnode = srt
    shape.n_ckeys = len(ckeys)
    shape.partial_plan = bottom.child
    budget = session.config.resource.query_mem_bytes
    tile_rows = _choose_tile(shape, budget)
    if tile_rows is None:
        return None
    shape.root = shape.post[0] if shape.post else shape.wintop
    return WindowTiledExecutable(shape, session, tile_rows, budget)


def _topn_bound(chain: list, skip: tuple = ()):
    """Locate a topn-streamable post chain's bounding sort and LIMIT: the
    LOWEST sort, fed only by projections/filters (part of the stream),
    with a LIMIT above it separated only by projections and ``skip``
    nodes (gather motions, distributed). An interposed SORT breaks the
    walk — a limit above a different sort bounds THAT order, not this
    one's — and a filter above the sort could starve the limit of rows
    the accumulator already dropped. Returns (sortnode, limit+offset) or
    None. Shared by the single-node and distributed analyzers so the
    recognizers cannot drift."""
    sort_i = next((i for i in range(len(chain) - 1, -1, -1)
                   if isinstance(chain[i], N.PSort)), None)
    if sort_i is None:
        return None
    if any(not isinstance(n, (N.PProject, N.PFilter))
           for n in chain[sort_i + 1:]):
        return None
    m = None
    for n in reversed(chain[:sort_i]):
        if isinstance(n, (N.PProject,) + skip):
            continue
        if isinstance(n, N.PLimit):
            m = n.limit + n.offset
        break
    if m is None or m <= 0:
        return None
    return chain[sort_i], m


def _analyze(plan: N.PlanNode) -> Optional[_TileShape]:
    """Recognize a streamable shape: a post chain over either one
    aggregation ("agg") or one bounding ORDER BY + LIMIT ("topn"), over a
    join/filter spine whose probe path ends at a scan."""
    for e in _all_exprs(plan):
        for sub in ex.walk(e):
            if isinstance(sub, ex.SubqueryScalar):
                return None  # subquery plans scan outside the spine budget

    chain: list[N.PlanNode] = []
    cur = plan
    while isinstance(cur, (N.PProject, N.PSort, N.PLimit, N.PFilter)):
        chain.append(cur)
        cur = cur.child

    agg: Optional[N.PAgg] = None
    sortnode: Optional[N.PSort] = None
    winnode: Optional[N.PWindow] = None
    post: list[N.PlanNode] = []
    m = 0
    if isinstance(cur, N.PAgg) and cur.mode == "single":
        agg = cur
        post = chain
        spine_top = agg.child
    elif isinstance(cur, N.PWindow):
        # window mode: a stack of window specs over the spine (one
        # PWindow per distinct OVER clause); above it only
        # column-pruning projections (the nodeWindowAgg spill shape).
        # Chunking needs partition keys COMMON to every spec — each
        # device chunk re-sorts per spec, so only the grouping must
        # align (checked in _plan_window).
        if any(not (isinstance(nd, N.PProject) and all(
                isinstance(e, ex.ColumnRef) for _, e in nd.exprs))
               for nd in chain):
            return None
        post = chain
        wintop = cur
        while isinstance(cur, N.PWindow):
            winnode = cur
            cur = cur.child
        spine_top = cur
    else:
        hit = _topn_bound(chain)
        if hit is not None:
            sortnode, m = hit
        else:
            # no bounding LIMIT: full external sort (host-RAM workfile)
            sortnode = _full_sort_shape(chain)
            if sortnode is None:
                return None
            m = 0
        post = chain[:chain.index(sortnode)]
        spine_top = sortnode.child

    spine: list[N.PlanNode] = []
    builds: list[N.PlanNode] = []
    cur = spine_top
    # graftlint: ignore[seam-loop] bounded plan-tree descent (one step per node, no blocking calls) — terminates with the tree, never a tile/retry loop
    while True:
        if isinstance(cur, (N.PFilter, N.PProject)):
            spine.append(cur)
            cur = cur.child
        elif isinstance(cur, N.PRuntimeFilter):
            spine.append(cur)
            cur = cur.child
        elif isinstance(cur, N.PJoin):
            if cur.kind == "full":
                # FULL joins emit unmatched BUILD rows — once per statement,
                # not once per tile; not streamable on the probe side
                return None
            spine.append(cur)
            builds.append(cur.build)
            cur = cur.probe
        elif isinstance(cur, N.PScan) and cur.table_name != "$dual":
            rows = cur.num_rows if cur.num_rows >= 0 else cur.capacity
            shape = _TileShape(agg, post, spine, cur, builds,
                               stream_rows=max(rows, 1))
            if winnode is not None:
                shape.mode = "window"
                shape.winnode = winnode
                shape.wintop = wintop
            elif agg is None:
                shape.mode = "topn" if m else "sort"
                shape.sortnode = sortnode
                shape.g_cap = m
            return shape
        else:
            return None


def _retile(shape: _TileShape, tile_rows: int) -> None:
    """Set the stream scan to one tile and re-derive spine capacities (the
    same formulas the planner uses, per tile instead of whole): expansion
    joins keep the NDV pair-estimate floor scaled to the tile fraction, and
    any runtime-grown buffer (_min_out_cap, set by grow_expansion retries)
    is never shrunk back."""
    frac = tile_rows / max(shape.stream_rows, 1)
    shape.stream.capacity = tile_rows
    cap = tile_rows
    for node in reversed(shape.spine):
        if isinstance(node, N.PJoin):
            bcap = _out_cap(node.build)
            est = getattr(node, "_est_pairs", None)
            floor = int(2 * est * min(frac, 1.0)) + 8 if est else 0
            floor = max(floor, getattr(node, "_min_out_cap", 0))
            if node.residual is not None:
                # pairs expand internally; output rides the probe capacity
                node.out_capacity = max(bcap + cap, floor)
            elif not node.unique_build:
                node.out_capacity = max(bcap + cap, floor)
                cap = node.out_capacity
    if shape.agg is not None:
        shape.partial_plan.capacity = min(shape.g_cap, max(cap, 1))


def _out_cap(node: N.PlanNode) -> int:
    if isinstance(node, (N.PScan, N.PAgg)):
        return node.capacity
    if isinstance(node, N.PJoin):
        if not node.unique_build:
            return node.out_capacity
        return _out_cap(node.probe)
    if isinstance(node, N.PMotion):
        return node.out_capacity or _out_cap(node.child)
    if isinstance(node, N.PConcat):
        return sum(_out_cap(c) for c in node.inputs)
    kids = node.children()
    return max((_out_cap(c) for c in kids), default=1)


def _acc_width(shape: _TileShape) -> int:
    return 1 + sum(f.type.np_dtype.itemsize
                   for f in shape.partial_plan.fields)


def _step_out_cap(shape) -> int:
    """Rows one tile's step can emit into the merge (shape is the single
    or distributed tile shape — both carry mode/partial_plan)."""
    return shape.partial_plan.capacity if shape.mode == "agg" \
        else _out_cap(shape.partial_plan)


def _merge_bytes(shape: _TileShape) -> int:
    """Accumulator + merge working set: the concat of acc and per-tile
    rows flowing through one sort-based group_aggregate (agg mode) or
    one bounding sort (topn mode)."""
    return 3 * (shape.g_cap + _step_out_cap(shape)) * _acc_width(shape)


def _choose_tile(shape: _TileShape, budget: int) -> Optional[int]:
    """Largest power-of-two tile whose estimated step memory fits: the
    spill-file-count decision of workfile_mgr, made at plan time."""
    t = _MAX_TILE
    while t >= _MIN_TILE:
        _retile(shape, t)
        est = estimate_plan_memory(shape.partial_plan).peak_bytes
        if est + _merge_bytes(shape) <= budget:
            return t
        t >>= 1
    return None


# --------------------------------------------------------------- lowerers


class _ReplacingLowerer(X.Lowerer):
    """Lowerer with a node-identity substitution table: nodes whose ids
    appear in ``replace`` lower to the given (cols, sel) instead of being
    traced (prelude-computed builds, the finalize accumulator leaf)."""

    def __init__(self, tables, replace: dict, **kw):
        super().__init__(tables, **kw)
        self._replace = replace

    def lower(self, node: N.PlanNode):
        hit = self._replace.get(id(node))
        if hit is not None:
            return hit
        return super().lower(node)


class _TileLowerer(_ReplacingLowerer):
    """Step-program lowerer: the stream scan reads the tile input; spine
    builds read their prelude-computed arrays."""

    def __init__(self, tables, stream: N.PScan, tile_n, replace: dict,
                 **kw):
        super().__init__(tables, replace, **kw)
        self._stream = stream
        self._tile_n = tile_n

    def scan(self, node: N.PScan):
        if node is not self._stream:
            return super().scan(node)
        tile = self.tables["$tile"]
        cols = {}
        for phys, out in node.column_map.items():
            cols[out] = tile[phys]
        for phys, out in node.mask_map.items():
            cols[out] = tile[f"$nn:{phys}"]
        sel = jnp.arange(node.capacity) < self._tile_n
        return cols, sel


# --------------------------------------------------------------- execution


class _TileTimer:
    """Per-tile step timing (the ISSUE-9 tiled telemetry): each step's
    wall feeds the engine ``tile_seconds`` histogram — so tile-time
    regressions show in ``meta "metrics"`` without an instrumented
    rerun — and, when the statement is traced, a per-tile span;
    ``stamp()`` summarizes the distribution onto the run report for
    EXPLAIN ANALYZE's tiled trailer. Bounded by construction: one
    fixed-size histogram, and spans ride the trace's own cap."""

    def __init__(self, session):
        from cloudberry_tpu.obs.metrics import _Hist

        self._log = getattr(session, "stmt_log", None)
        self._h = _Hist()

    def step(self, idx: int):
        import contextlib
        import time as _t

        from cloudberry_tpu.obs import trace as OT

        @contextlib.contextmanager
        def _cm():
            t0 = _t.perf_counter()
            try:
                yield
            finally:
                dt = _t.perf_counter() - t0
                self._h.add(dt)
                if self._log is not None and self._log.obs_enabled:
                    self._log.registry.observe("tile_seconds", dt)
                OT.mark("tile-step", t0, tile=idx)

        return _cm()

    def stamp(self, report: dict) -> None:
        if self._h.n:
            report["tile_time"] = {
                "count": self._h.n,
                "mean": round(self._h.total / self._h.n, 6),
                "p95": self._h.quantile(0.95),
            }


def _progress_tracker(exe, n_base: int, skip: int):
    """Live-progress feeder for a single-node tile loop
    (obs/progress.py): one lane — the remaining row prefix of the
    deterministic stream. A no-op object when the statement carries no
    Progress (obs off, or no lifecycle scope)."""
    from cloudberry_tpu.obs.progress import TileTracker, stream_rows

    total = stream_rows(exe.shape.stream, exe.session)
    return TileTracker(max(total - skip, 0), exe.tile_rows,
                       n_base=n_base, base_rows=min(skip, total),
                       rows_total=total)


class SkewSentinel:
    """Mid-statement adaptive-replan watcher for tiled-dist runs.

    The distributed step program already psums every redistribute's
    per-destination row counts for the capacity-forensics channel; the
    sentinel accumulates those vectors host-side across tiles and, when
    the CUMULATIVE distribution crosses the skew alarm
    (``config.feedback.replan_skew_ratio``, 0 = inherit
    ``config.obs.skew_ratio``), asks the session to re-plan the rest of
    the statement: it folds the observed counts into the feedback store
    as a partial sketch, force-checkpoints the carried state
    (exec/recovery.py), and raises TileReplan. Correctness never
    depends on it — an adaptation that cannot checkpoint simply
    disarms and the run finishes on the static plan.

    Guard rails, in check order: feature off / no recovery scope / too
    few tiles seen (``min_tiles``) / statement replan budget spent
    (``max_replans``) / no motion alarmed / ``tile_replan`` fault seam
    armed / checkpoint save failed."""

    def __init__(self, exe, motions, ctx):
        cfg = getattr(exe.session.config, "feedback", None)
        self.exe = exe
        self.session = exe.session
        self.motions = motions
        self.ctx = ctx
        self.min_tiles = cfg.min_tiles if cfg is not None else 2
        self.max_replans = cfg.max_replans if cfg is not None else 0
        self.threshold = float(
            (cfg.replan_skew_ratio or exe.session.config.obs.skew_ratio)
            if cfg is not None else 0.0)
        # collect: accumulate telemetry for the end-of-run fold (the
        # learning half works even with adaptation off); armed: the
        # mid-statement replan trigger itself
        self.collect = bool(cfg is not None and cfg.enabled and motions)
        self.armed = bool(
            self.collect and cfg.adaptive and ctx is not None
            and self.threshold > 0.0)
        self.cum = [np.zeros(exe.nseg, dtype=np.int64) for _ in motions]
        self.demand = [0] * len(motions)

    def observe(self, stats) -> None:
        """Fold one tile's per-motion (required-bucket scalar, psum'd
        per-destination row vector) pairs, traversal order matching
        ``self.motions``."""
        if not self.collect:
            return
        # counter-pinned host fetch: when feedback is off (or the plan
        # has no stat motions) the loop never even passes stats in, so
        # this stays 0 — the no-host-sync contract tests rely on
        log = getattr(self.session, "stmt_log", None)
        if log is not None:
            log.bump("tile_stat_syncs")
        for i, (bucket, rows) in enumerate(stats):
            self.demand[i] = max(self.demand[i], int(np.asarray(bucket)))
            self.cum[i] += np.asarray(rows, dtype=np.int64)

    def _pin(self) -> bool:
        """Stamp the cumulative observations onto the partial plan's
        motions the way record_motion_stats does for the one-shot path;
        True when anything flowed."""
        any_rows = False
        for m, c, d in zip(self.motions, self.cum, self.demand):
            if int(c.sum()) > 0:
                m._seg_rows = c.copy()
                any_rows = True
            if d > 0:
                # per-TILE demand, not cumulative: the rung a re-seeded
                # tiled run needs is the largest single-tile bucket
                m._observed_bucket = max(
                    d, getattr(m, "_observed_bucket", 0) or 0)
        return any_rows

    def fold_final(self) -> None:
        """End-of-run fold: the one-shot dist path folds in
        execute_distributed, the tiled stream folds here."""
        if not self.collect:
            return
        from cloudberry_tpu.plan import feedback as FB

        if self._pin():
            FB.fold_plan(self.session, self.exe.shape.partial_plan)

    def _worst(self):
        worst = None
        for m, c in zip(self.motions, self.cum):
            total = int(c.sum())
            if total <= 0:
                continue
            ratio = float(c.max()) * len(c) / total
            if ratio >= self.threshold and (worst is None
                                            or ratio > worst[1]):
                worst = (m, ratio)
        return worst

    def maybe_replan(self, tiles_local: int, payload_fn,
                     settle=None) -> None:
        """Raise TileReplan when the cumulative distribution alarms and
        the adaptation can resume safely; no-op otherwise.

        ``settle`` is the windowed-dispatch hook (exec/tilepipe.py): a
        zero-arg callable that drains every in-flight tile (folding
        their observations) and returns the new drained-tile count. The
        alarm fires on DRAINED telemetry, but the snapshot must capture
        the carried accumulator, which on accelerators is only valid
        for the newest dispatched step — settling first makes every
        dispatched tile verified-clean, so ``payload_fn`` (the live
        accumulator) and ``tiles_local`` agree again. At window=1 the
        queue is already empty and settle is a no-op, preserving the
        legacy sequence exactly."""
        from cloudberry_tpu.exec import recovery as R
        from cloudberry_tpu.lifecycle import current_handle
        from cloudberry_tpu.obs import trace as OT

        if not self.armed or tiles_local < self.min_tiles:
            return
        session = self.session
        # the replan budget rides the STATEMENT handle (session.sql
        # re-dispatches under the same one), and only handles the
        # session marked adaptation-safe (reads) may restart
        handle = current_handle()
        if handle is None or not getattr(handle, "adaptive_ok", False):
            return
        if getattr(handle, "tile_replans", 0) >= self.max_replans:
            return
        worst = self._worst()
        if worst is None:
            return
        if fault_point("tile_replan"):
            self.armed = False      # seam: suppress the adaptation
            return
        if settle is not None:
            # drain the in-flight window: a check that fires here aborts
            # the replan and rides the normal adaptive-retry path; the
            # drained tiles' observations fold into the cumulative view
            tiles_local = settle()
            worst = self._worst()
            if worst is None:       # the tail un-alarmed the ratio
                return
        # Publish what we actually saw BEFORE deciding to restart: pin
        # the cumulative counts on the partial plan's motions and fold a
        # partial sketch — the re-planned statement prices against it.
        from cloudberry_tpu.plan import feedback as FB

        self._pin()
        FB.fold_plan(session, self.exe.shape.partial_plan, partial=True)
        # The replanned run must resume from HERE, not re-stream: a
        # failed save disarms the sentinel and the static plan finishes.
        if not self.ctx.force_snapshot(tiles_local, payload_fn):
            self.armed = False
            return
        handle.tile_replans = getattr(handle, "tile_replans", 0) + 1
        log = getattr(session, "stmt_log", None)
        if log is not None:
            log.bump("tile_replans")
        import time as _t
        OT.mark("tile-replan", _t.perf_counter(),
                tile=tiles_local, ratio=round(worst[1], 3))
        raise R.TileReplan(
            f"[tile {tiles_local}] cumulative redistribute skew "
            f"{worst[1]:.2f}x crossed the adaptive replan alarm "
            f"{self.threshold:.2f}x; carried state checkpointed",
            tiles_done=tiles_local, ratio=worst[1])


class AdaptiveTiledMixin:
    """Shared adaptive-retry discipline for tiled executables (single-node
    and distributed): classify a detected overflow, grow the guilty buffer
    (accumulator / join pair buffer) or shrink the tile, and re-run — the
    increase-nbatch-and-rescan loop of nodeHash.c, never truncation.

    Requires from the concrete class: ``shape`` (with ``partial_plan`` and
    ``g_cap``), ``tile_rows``, ``budget``, ``report``, ``_compiled``,
    ``_refresh_report()``, ``_run_once()``, ``_groups_ceiling()``, and
    ``_what`` (human name for the budget error)."""

    _what = "tiled execution"

    def refresh_bufpool_charge(self) -> None:
        """Re-stamp the report's ``est_bufpool_bytes``. The report is
        built once per compile but the pool's residency for the
        streamed table moves between statements (admits during a prior
        run, evictions, topology sweeps) — dispatch-time capacity
        recording and report publication both re-read it."""
        bpool = BUF.pool_for(self.session)
        self.report["est_bufpool_bytes"] = (
            bpool.table_bytes(self.shape.stream.table_name)
            if bpool is not None else 0)

    def _publish_report(self) -> None:
        self.refresh_bufpool_charge()
        self.session.last_tiled_report = dict(self.report)

    def _run_adaptive(self) -> ColumnBatch:
        from cloudberry_tpu.lifecycle import check_cancel

        while True:
            # cancel seam: each adaptive round restarts the whole tile
            # stream — a cancelled/over-deadline statement stops between
            # rounds instead of re-streaming the table
            check_cancel()
            try:
                return self._run_once()
            except X.ExecError as e:
                msg = str(e)
                shape = self.shape
                if not msg.startswith("[tile"):
                    # prelude/finalize failure: expansion overflows grow
                    # that join's pair buffer and retry
                    if not X.grow_expansion(shape.partial_plan, msg):
                        raise
                elif ("merge overflow" in msg
                      or "aggregation overflow" in msg):
                    # more groups than estimated: grow the accumulator and
                    # restart the stream — never truncate. Doubling (the
                    # nbatch discipline of nodeHash.c) overshoots the true
                    # group count by at most 2×, which matters downstream:
                    # the distributed finalize merges nseg·g_cap rows.
                    ceiling = self._groups_ceiling()
                    if shape.g_cap >= ceiling:
                        raise
                    shape.g_cap = min(shape.g_cap * 2, ceiling)
                elif "expansion overflow" in msg:
                    # a tile's join fanout blew its pair buffer: grow that
                    # join when the budget allows, else halve the tile
                    if not (self._try_grow(msg)
                            or self._try_halve_tile()):
                        raise
                elif "redistribute overflow" in msg:
                    # an estimate-sized bucket overflowed inside a tile:
                    # smaller tiles shrink every per-tile send bound
                    if not self._try_halve_tile():
                        raise
                else:
                    raise
                if getattr(self, "_deferred_fail", False):
                    # the failed check had already been outrun by newer
                    # in-flight launches (exec/tilepipe.py): this retry
                    # IS the deferred-failure window replay — it resumes
                    # from the last drained-clean checkpoint
                    self._deferred_fail = False
                    log = getattr(self.session, "stmt_log", None)
                    if log is not None:
                        log.bump("tile_window_replays")
                self._compiled = None
                self._refresh_report()
                # a grown accumulator may blow the step budget: smaller
                # tiles buy the room back before giving up
                while self._over_budget() and self._try_halve_tile():
                    self._refresh_report()
                if self._over_budget():
                    raise X.ExecError(
                        f"{self._what} working set (accumulator "
                        f"{shape.g_cap} groups, tile {self.tile_rows} "
                        "rows) exceeds the query memory budget "
                        f"{self.budget >> 20} MiB; raise "
                        "config.resource.query_mem_bytes") from e

    def _over_budget(self) -> bool:
        return self.report["est_step_bytes"] > self.budget

    def _try_grow(self, msg: str) -> bool:
        """Grow the overflowing spine join's pair buffer if the grown step
        still fits the budget; revert (and report False) otherwise."""
        node = X.find_expansion_node(self.shape.partial_plan, msg)
        if node is None:
            return False
        old = getattr(node, "_min_out_cap", 0)
        node._min_out_cap = max(node.out_capacity * 4, 64)
        self._refresh_report()
        if self.report["est_step_bytes"] <= self.budget:
            return True
        node._min_out_cap = old
        self._refresh_report()
        return False

    def _try_halve_tile(self) -> bool:
        if self.tile_rows <= _MIN_TILE:
            return False
        self.tile_rows >>= 1
        return True



class TiledExecutable(AdaptiveTiledMixin):
    """Compiled tiled statement: prelude (once) → step (per tile) →
    finalize. ``report`` records the spill decision for tests/EXPLAIN."""

    def __init__(self, shape: _TileShape, session, tile_rows: int,
                 budget: int):
        self.shape = shape
        self.session = session
        self.tile_rows = tile_rows
        self.budget = budget
        self._platform = jax.default_backend()
        self._use_pallas = session.config.exec.use_pallas
        self._compiled = None
        # server handler threads may hit the cached runner concurrently;
        # retries mutate shared plan capacities, so runs serialize (the
        # admission gate bounds statement concurrency anyway)
        import threading

        self._run_lock = threading.Lock()
        self._refresh_report()

    def _refresh_report(self) -> None:
        shape = self.shape
        _retile(shape, self.tile_rows)
        est = estimate_plan_memory(shape.partial_plan).peak_bytes
        merge_bytes = _merge_bytes(shape)
        self.report = {
            "tiled": True,
            "stream_table": shape.stream.table_name,
            "tile_rows": self.tile_rows,
            "acc_capacity": shape.g_cap,
            "est_step_bytes": est + merge_bytes,
            # scan-pipeline staging charge (exec/scanpipe.py) plus the
            # dispatch window's extra in-flight tiles (exec/tilepipe.py)
            # — obs/capacity.record_tiled adds both to the statement's
            # observed peak
            "est_pipeline_bytes": SP.queue_charge_bytes(
                shape.stream, self.tile_rows, self.session.config)
            + TP.window_charge_bytes(
                shape.stream, self.tile_rows, self.session.config,
                self._platform),
            # HBM buffer-pool residency attributable to the streamed
            # table (exec/bufferpool.py) — charged into the capacity
            # plane next to the pipeline's staging bytes
            "est_bufpool_bytes": _bufpool_charge(
                self.session, shape.stream.table_name),
            "budget_bytes": self.budget,
        }

    # ------------------------------------------------------------ programs

    def _resident_inputs(self) -> dict:
        """All step inputs except the tile: whole (non-stream) tables and
        pruned store reads — exactly prepare_inputs minus the stream."""
        scans = [s for s in X.scans_of(self._whole_plan())
                 if s is not self.shape.stream]
        store_scans = [s for s in scans if hasattr(s, "_store_parts")]
        names = sorted({s.table_name for s in scans
                        if not hasattr(s, "_store_parts")})
        return X._assemble_inputs(names, store_scans, self.session, None)

    def _whole_plan(self) -> N.PlanNode:
        # scans live under the partial plan (spine + builds); the post
        # chain/finalize reference only aggregate outputs
        return self.shape.partial_plan

    def _compile(self):
        if self._compiled is not None:
            return self._compiled
        shape = self.shape
        plat, pallas = self._platform, self._use_pallas

        def prelude_fn(tables):
            low = X.Lowerer(tables, platform=plat, use_pallas=pallas)
            outs = [low.lower_shared(b) for b in shape.builds]
            return outs, low.checks

        group_names = [n for n, _ in shape.agg.group_keys]
        specs = shape.merge_specs
        g_cap = shape.g_cap

        def step_fn(resident, prelude, tile, tile_n, acc):
            tables = dict(resident)
            tables["$tile"] = tile
            replace = {id(b): prelude[i]
                       for i, b in enumerate(shape.builds)}
            low = _TileLowerer(tables, shape.stream, tile_n, replace,
                               platform=plat, use_pallas=pallas)
            pcols, psel = low.lower(shape.partial_plan)
            checks = dict(low.checks)
            acc_cols, acc_sel = acc
            if group_names:
                key_cols = {n: jnp.concatenate([acc_cols[n], pcols[n]])
                            for n in group_names}
                agg_vals = {s.out_name: jnp.concatenate(
                    [acc_cols[s.out_name], pcols[s.out_name]])
                    for s in specs}
                sel = jnp.concatenate([acc_sel, psel])
                # the same fused-or-XLA dispatch the one-shot executor
                # uses: eligible int sums are bit-identical either way,
                # so tiled and one-shot results cannot diverge
                ok, oa, osel, n_groups = X.merge_group_aggregate(
                    key_cols, agg_vals, specs, sel, g_cap, pallas, plat)
                checks["tile merge overflow: more groups than capacity "
                       f"{g_cap}; raise the aggregation capacity"] = \
                    n_groups > g_cap
                return ({**ok, **oa}, osel), checks
            agg_vals = {s.out_name: jnp.concatenate(
                [acc_cols[s.out_name], pcols[s.out_name]])
                for s in specs}
            sel = jnp.concatenate([acc_sel, psel])
            out = K.global_aggregate(agg_vals, specs, sel)
            return (out, jnp.ones((1,), dtype=jnp.bool_)), checks

        def finalize_fn(acc):
            acc_cols, acc_sel = acc
            low = _ReplacingLowerer(
                {}, {id(_leaf_of(shape.root)): (acc_cols, acc_sel)},
                platform=plat, use_pallas=pallas)
            cols, sel = low.lower(shape.root)
            out = {f.name: cols[f.name] for f in shape.root.fields}
            return out, sel, low.checks

        self._compiled = (jax.jit(prelude_fn),
                          jax.jit(step_fn, donate_argnums=TP.step_donation(
                              self._platform)),
                          jax.jit(finalize_fn))
        return self._compiled

    def _init_acc(self):
        shape = self.shape
        g_cap = shape.g_cap
        group_names = {n for n, _ in shape.agg.group_keys}
        cols = {}
        if group_names:
            for f in shape.partial_plan.fields:
                cols[f.name] = jnp.zeros((g_cap,), dtype=f.type.np_dtype)
            return cols, jnp.zeros((g_cap,), dtype=jnp.bool_)
        for f, spec in zip(
                [f for f in shape.partial_plan.fields
                 if f.name not in group_names], shape.merge_specs):
            dt = f.type.np_dtype
            if spec.func == "min":
                ident = np.array(
                    np.finfo(dt).max if np.issubdtype(dt, np.floating)
                    else np.iinfo(dt).max, dtype=dt)
            elif spec.func == "max":
                ident = np.array(
                    np.finfo(dt).min if np.issubdtype(dt, np.floating)
                    else np.iinfo(dt).min, dtype=dt)
            else:
                ident = np.zeros((), dtype=dt)
            cols[f.name] = jnp.full((1,), ident)
        # identity row stays unselected: min/max identities must not leak
        # into the merge as real values when a tile contributes rows
        return cols, jnp.zeros((1,), dtype=jnp.bool_)

    # ----------------------------------------------------------------- run

    def run(self) -> ColumnBatch:
        with self._run_lock:
            return self._run_adaptive()

    def _groups_ceiling(self) -> int:
        return self.shape.agg.capacity

    def _run_once(self) -> ColumnBatch:
        from cloudberry_tpu.exec import recovery as R

        prelude_fn, step_fn, finalize_fn = self._compile()
        resident = self._resident_inputs()
        prelude, pchecks = prelude_fn(resident)
        X.raise_checks(pchecks)

        # mid-statement recovery (exec/recovery.py): resume from the last
        # K-tile checkpoint instead of replaying the whole stream
        ctx = R.begin(self, dist=False)
        acc = self._init_acc()
        if ctx is not None:
            acc = ctx.restore_acc(acc)
        skip = ctx.skip_rows if ctx is not None else 0
        n_base = ctx.tiles_base if ctx is not None else 0
        n_local = 0
        n_sub = 0
        timer = _TileTimer(self.session)
        tracker = _progress_tracker(self, n_base, skip)
        pipe = TP.TilePipe(self.session, TP.effective_window(
            self.session.config, self._platform))
        feed = _tile_feed(self.shape.stream, self.session,
                          self.tile_rows, skip_rows=skip,
                          min_depth=pipe.window)

        def _verified(d):
            # host effects for ONE drained-clean tile, in stream order
            # and in the legacy sequence: progress, then the K-tile
            # checkpoint tick (a staged payload when the submit saw the
            # boundary coming; the live accumulator at window=1, where
            # drain is synchronous and acc IS this tile's state)
            nonlocal n_local
            tile_k, staged = d.payload
            n_local = tile_k
            tracker.step(tile_k)
            if ctx is not None:
                ctx.tick(tile_k, staged if staged is not None
                         else (lambda: R.acc_payload(acc)))

        try:
            for tile, tile_n in feed:
                fault_point("tile_step")
                fault_point("tile_device_lost")
                n_sub += 1
                stage = (ctx is not None and pipe.window > 1
                         and ctx.snapshot_due(n_sub))
                with timer.step(n_base + n_sub - 1):
                    acc, checks = step_fn(resident, prelude, tile,
                                          jnp.asarray(tile_n,
                                                      dtype=jnp.int32),
                                          acc)
                    staged = TP.stage_checkpoint(acc) if stage else None
                    drained = pipe.submit(n_base + n_sub - 1, checks,
                                          (n_sub, staged))
                for d in drained:
                    _verified(d)
            for d in pipe.drain_all():
                _verified(d)
        finally:
            # deterministic teardown on EVERY exit (cancel, overflow
            # retry, device loss): the reader joins and staged tiles
            # release — no orphan thread, no pinned prefetch buffers;
            # abandoned in-flight launches just complete into garbage-
            # collected buffers (nothing to join on the device side)
            if pipe.deferred_fail:
                self._deferred_fail = True
            SP.close_feed(feed)
        SP.stamp_report(self.report, feed)
        n_tiles = n_base + n_local
        timer.stamp(self.report)
        pipe.stamp(self.report)
        if n_tiles == 0:  # empty stream: one all-masked tile seeds the acc
            empty = _empty_tile(self.shape.stream, self.tile_rows)
            acc, checks = step_fn(resident, prelude, empty,
                                  jnp.asarray(0, dtype=jnp.int32), acc)
            _raise_tile_checks(checks, 0)
            n_tiles = 1

        fault_point("tiled_finalize")
        from cloudberry_tpu.lifecycle import check_cancel

        check_cancel()
        cols, sel, fchecks = finalize_fn(acc)
        X.raise_checks(fchecks)
        self.report["n_tiles"] = n_tiles
        if ctx is not None:
            ctx.stamp_report(self.report)
        self._publish_report()
        return X.make_batch(self.shape.root, cols, sel)


class TopNTiledExecutable(TiledExecutable):
    """Tiled statement whose accumulator is the best LIMIT+OFFSET rows
    seen so far (nodeSort.c bounded-heap role): step = spine over one
    tile, then one bounding sort over (accumulator ∪ tile rows), keeping
    the first g_cap positions — selected rows sort first, so the slice
    is exactly the running top-N. Finalize runs the original post chain
    (LIMIT/projections) over the sorted accumulator."""

    _what = "top-N tiled execution"

    def _groups_ceiling(self) -> int:
        return self.shape.g_cap  # fixed: LIMIT itself bounds the acc

    def _init_acc(self):
        shape = self.shape
        cols = {f.name: jnp.zeros((shape.g_cap,), dtype=f.type.np_dtype)
                for f in shape.partial_plan.fields}
        return cols, jnp.zeros((shape.g_cap,), dtype=jnp.bool_)

    def _refresh_report(self) -> None:
        super()._refresh_report()
        self.report["mode"] = "topn"

    def _compile(self):
        if self._compiled is not None:
            return self._compiled
        shape = self.shape
        plat, pallas = self._platform, self._use_pallas
        m = shape.g_cap
        mleaf, msort = shape.finalize["mleaf"], shape.finalize["msort"]
        names = [f.name for f in shape.partial_plan.fields]

        def prelude_fn(tables):
            low = X.Lowerer(tables, platform=plat, use_pallas=pallas)
            outs = [low.lower_shared(b) for b in shape.builds]
            return outs, low.checks

        def step_fn(resident, prelude, tile, tile_n, acc):
            tables = dict(resident)
            tables["$tile"] = tile
            replace = {id(b): prelude[i]
                       for i, b in enumerate(shape.builds)}
            low = _TileLowerer(tables, shape.stream, tile_n, replace,
                               platform=plat, use_pallas=pallas)
            pcols, psel = low.lower(shape.partial_plan)
            checks = dict(low.checks)
            acc_cols, acc_sel = acc
            ccols = {n: jnp.concatenate([acc_cols[n], pcols[n]])
                     for n in names}
            csel = jnp.concatenate([acc_sel, psel])
            low2 = _ReplacingLowerer({}, {id(mleaf): (ccols, csel)},
                                     platform=plat, use_pallas=pallas)
            scols, ssel = low2.lower(msort)
            checks.update(low2.checks)
            return ({n: scols[n][:m] for n in names}, ssel[:m]), checks

        def finalize_fn(acc):
            acc_cols, acc_sel = acc
            low = _ReplacingLowerer(
                {}, {id(_leaf_of(shape.root)): (acc_cols, acc_sel)},
                platform=plat, use_pallas=pallas)
            cols, sel = low.lower(shape.root)
            out = {f.name: cols[f.name] for f in shape.root.fields}
            return out, sel, low.checks

        self._compiled = (jax.jit(prelude_fn),
                          jax.jit(step_fn, donate_argnums=TP.step_donation(
                              self._platform)),
                          jax.jit(finalize_fn))
        return self._compiled


class SortTiledExecutable(TiledExecutable):
    """Tiled statement whose result is a FULL ORDER BY with no bounding
    limit — the external-merge-sort analog (tuplesort.c spill mode,
    workfile_mgr.c's tape role played by host RAM). Per tile, the step
    program runs the spine and emits the surviving rows together with
    one order-normalized u64 column per sort key (same normalization
    kernels.sort_indices uses, so device and host orders cannot
    disagree — descending keys bit-complement, NULL ordering rides the
    binder's is-null companion keys). The host appends each tile's rows
    to the run store; the merge pass is one stable host key sort over
    the collected runs, then the post chain (column pruning, LIMIT)
    applies host-side."""

    _what = "external-sort tiled execution"

    def _groups_ceiling(self) -> int:
        return 0  # no accumulator exists to grow

    def _refresh_report(self) -> None:
        shape = self.shape
        _retile(shape, self.tile_rows)
        est = estimate_plan_memory(shape.partial_plan).peak_bytes
        self.report = {
            "tiled": True,
            "mode": "sort",
            "stream_table": shape.stream.table_name,
            "tile_rows": self.tile_rows,
            "acc_capacity": 0,
            "est_step_bytes": est + _merge_bytes(shape),
            "est_pipeline_bytes": SP.queue_charge_bytes(
                shape.stream, self.tile_rows, self.session.config)
            + TP.window_charge_bytes(
                shape.stream, self.tile_rows, self.session.config,
                self._platform),
            "est_bufpool_bytes": _bufpool_charge(
                self.session, shape.stream.table_name),
            "budget_bytes": self.budget,
        }

    def _compile(self):
        if self._compiled is not None:
            return self._compiled
        shape = self.shape
        plat, pallas = self._platform, self._use_pallas
        sort = shape.sortnode
        names = [f.name for f in sort.child.fields]

        def prelude_fn(tables):
            low = X.Lowerer(tables, platform=plat, use_pallas=pallas)
            outs = [low.lower_shared(b) for b in shape.builds]
            return outs, low.checks

        def step_fn(resident, prelude, tile, tile_n):
            tables = dict(resident)
            tables["$tile"] = tile
            replace = {id(b): prelude[i]
                       for i, b in enumerate(shape.builds)}
            low = _TileLowerer(tables, shape.stream, tile_n, replace,
                               platform=plat, use_pallas=pallas)
            pcols, psel = low.lower(shape.partial_plan)
            n = psel.shape[0]
            keys = []
            for e, asc in sort.keys:
                arr = X._as_column(X._sortable(e, sort.child, pcols), n)
                u = K.sort_key_u64(arr)
                keys.append(u if asc else ~u)
            out = {nm: X._as_column(pcols[nm], n) for nm in names}
            return (out, psel, keys), low.checks

        self._compiled = (jax.jit(prelude_fn), jax.jit(step_fn))
        return self._compiled

    def _stream_sorted(self):
        """Run the tile stream and the merge pass; returns
        (sorted child columns, sorted normalized key columns, n_tiles,
        recovery ctx) as host arrays."""
        from cloudberry_tpu.exec import recovery as R

        prelude_fn, step_fn = self._compile()
        shape = self.shape
        resident = self._resident_inputs()
        prelude, pchecks = prelude_fn(resident)
        X.raise_checks(pchecks)

        ctx = R.begin(self, dist=False)
        names = [f.name for f in shape.sortnode.child.fields]
        runs: dict[str, list] = {nm: [] for nm in names}
        key_runs: list[list] = [[] for _ in shape.sortnode.keys]
        if ctx is not None:
            runs, key_runs = ctx.restore_runs(runs, key_runs)
        skip = ctx.skip_rows if ctx is not None else 0
        n_base = ctx.tiles_base if ctx is not None else 0
        n_local = 0
        n_sub = 0
        timer = _TileTimer(self.session)
        tracker = _progress_tracker(self, n_base, skip)
        pipe = TP.TilePipe(self.session, TP.effective_window(
            self.session.config, self._platform))
        feed = _tile_feed(shape.stream, self.session,
                          self.tile_rows, skip_rows=skip,
                          min_depth=pipe.window)

        def _verified(d):
            # one drained-clean tile: the run-store appends happen HERE
            # (materializing the async copies started at submit), so the
            # host collects tile k's rows while tiles k+1..k+W-1 compute;
            # the checkpoint payload is the runs themselves — host state,
            # exactly as of this tile, no staging needed
            nonlocal n_local
            tile_k, pcols, psel, keys = d.payload
            n_local = tile_k
            tracker.step(tile_k)
            mask = np.asarray(psel)
            for nm in names:
                runs[nm].append(np.asarray(pcols[nm])[mask])
            for i, k in enumerate(keys):
                key_runs[i].append(np.asarray(k)[mask])
            if ctx is not None:
                ctx.tick(tile_k,
                         lambda: R.runs_payload(runs, key_runs))

        try:
            for tile, tile_n in feed:
                fault_point("tile_step")
                fault_point("tile_device_lost")
                n_sub += 1
                with timer.step(n_base + n_sub - 1):
                    (pcols, psel, keys), checks = step_fn(
                        resident, prelude, tile,
                        jnp.asarray(tile_n, dtype=jnp.int32))
                    drained = pipe.submit(n_base + n_sub - 1, checks,
                                          (n_sub, pcols, psel, keys))
                for d in drained:
                    _verified(d)
            for d in pipe.drain_all():
                _verified(d)
        finally:
            if pipe.deferred_fail:
                self._deferred_fail = True
            SP.close_feed(feed)
        SP.stamp_report(self.report, feed)
        timer.stamp(self.report)
        pipe.stamp(self.report)

        fault_point("tiled_finalize")
        from cloudberry_tpu.lifecycle import check_cancel

        check_cancel()
        cols, karr = merge_sorted_runs(runs, key_runs,
                                       shape.sortnode.child.fields,
                                       len(shape.sortnode.keys))
        return cols, karr, max(n_base + n_local, 1), ctx

    def _run_once(self) -> ColumnBatch:
        shape = self.shape
        cols, _karr, n_tiles, ctx = self._stream_sorted()
        cols = host_apply_post(shape.post, cols)
        n_out = len(next(iter(cols.values()))) if cols else 0
        self.report["n_tiles"] = n_tiles
        if ctx is not None:
            ctx.stamp_report(self.report)
        self._publish_report()
        out_node = shape.post[0] if shape.post else shape.sortnode
        return X.make_batch(out_node, cols,
                            np.ones((n_out,), dtype=bool))


class WindowTiledExecutable(SortTiledExecutable):
    """Tiled window functions — the nodeWindowAgg.c spill analog. Phase
    one reuses the external-sort stream, ordered by (partition keys,
    order keys), so the host holds every surviving spine row grouped by
    partition. Phase two packs WHOLE partitions into fixed-capacity
    chunks and runs the original window (+ projection chain) on device
    once per chunk: window functions never cross partitions, so chunks
    are independent and every frame kind stays exact — no carry state.
    Only a single partition larger than the chunk capacity cannot
    stream; that raises with a clear message (the reference's
    one-partition tuplestore has the same working-set floor)."""

    _what = "windowed tiled execution"

    def _refresh_report(self) -> None:
        super()._refresh_report()
        self.report["mode"] = "window"

    def _chunk_fn(self):
        if getattr(self, "_chunk_compiled", None) is not None:
            return self._chunk_compiled
        shape = self.shape
        win = shape.winnode
        plat, pallas = self._platform, self._use_pallas
        cap = self.tile_rows

        def run_chunk(chunk_cols, n_valid):
            sel = jnp.arange(cap) < n_valid
            low = _ReplacingLowerer(
                {}, {id(win.child): (chunk_cols, sel)},
                platform=plat, use_pallas=pallas)
            cols, osel = low.lower(shape.root)
            out = {f.name: cols[f.name] for f in shape.root.fields}
            return out, osel, low.checks

        self._chunk_compiled = jax.jit(run_chunk)
        return self._chunk_compiled

    def _run_once(self) -> ColumnBatch:
        shape = self.shape
        self._chunk_compiled = None  # capacity may have changed
        cols, karr, n_tiles, ctx = self._stream_sorted()
        names = [f.name for f in shape.winnode.child.fields]
        final, n_chunks = window_chunk_pass(
            self._chunk_fn(), shape.root, names, cols, karr,
            shape.n_ckeys, self.tile_rows)
        n_out = len(next(iter(final.values()))) if final else 0
        self.report["n_tiles"] = n_tiles
        self.report["n_chunks"] = n_chunks
        if ctx is not None:
            ctx.stamp_report(self.report)
        self._publish_report()
        return X.make_batch(shape.root, final,
                            np.ones((n_out,), dtype=bool))


def window_chunk_pass(run, root, names, cols, karr, npk, cap):
    """Phase two of window spill, shared by the single-node and
    distributed executables: pack WHOLE partitions (runs of equal
    normalized chunk keys) into fixed-capacity chunks and feed each
    through the jitted window program ``run``. Returns (output columns,
    chunk count)."""
    out_fields = root.fields
    n = len(cols[names[0]]) if names else 0
    if n == 0:
        return ({f.name: np.zeros((0,), dtype=f.type.np_dtype)
                 for f in out_fields}, 0)
    new_part = np.zeros(n, dtype=bool)
    new_part[0] = True
    for k in karr[:npk]:
        new_part[1:] |= k[1:] != k[:-1]
    starts = np.flatnonzero(new_part)
    sizes = np.diff(np.append(starts, n))
    if sizes.max(initial=0) > cap:
        raise X.ExecError(
            f"windowed tiled execution: one partition holds "
            f"{int(sizes.max())} rows, more than the {cap}-row chunk "
            "the memory budget allows; raise "
            "config.resource.query_mem_bytes")
    outs: dict[str, list] = {f.name: [] for f in out_fields}
    n_chunks = 0
    chunk_lo = chunk_hi = 0

    def flush(lo, hi):
        nonlocal n_chunks
        if hi <= lo:
            return
        m = hi - lo
        chunk = {}
        for nm in names:
            a = cols[nm][lo:hi]
            if m < cap:
                a = np.concatenate(
                    [a, np.zeros((cap - m,), dtype=a.dtype)])
            chunk[nm] = a
        ocols, osel, checks = run(chunk, jnp.asarray(m, dtype=jnp.int32))
        _raise_tile_checks(checks, n_chunks)
        n_chunks += 1
        mask = np.asarray(osel)
        for nm in outs:
            outs[nm].append(np.asarray(ocols[nm])[mask])

    for s, size in zip(starts, sizes):
        if chunk_hi - chunk_lo + size > cap and chunk_hi > chunk_lo:
            flush(chunk_lo, chunk_hi)
            chunk_lo = s
        chunk_hi = s + size
    flush(chunk_lo, chunk_hi)
    final = {nm: np.concatenate(arrs) if arrs else
             np.zeros((0,), dtype=root.field(nm).type.np_dtype)
             for nm, arrs in outs.items()}
    return final, n_chunks


def _leaf_of(root: N.PlanNode) -> N.PlanNode:
    cur = root
    while not isinstance(cur, _AccLeaf):
        cur = cur.child  # post chain + finalize project are all unary
    return cur


def _raise_tile_checks(checks: dict, tile_idx: int) -> None:
    # the per-tile cancel seam (the CHECK_FOR_INTERRUPTS row-boundary
    # analog): every step/chunk of the single-node AND distributed tiled
    # executables passes through here, so cancellation latency is bounded
    # by one tile's device launch
    from cloudberry_tpu.lifecycle import check_cancel

    check_cancel()
    for msg, bad in checks.items():
        if bool(np.asarray(bad).any()):
            raise X.ExecError(f"[tile {tile_idx}] {msg}")


def _expr_dict(plan: N.PlanNode, e: ex.Expr):
    if isinstance(e, ex.ColumnRef):
        try:
            return plan.field(e.name).sdict
        except KeyError:
            return None
    return None


# -------------------------------------------------------------- tile feed


def _phys_cols(scan: N.PScan) -> list[str]:
    return sorted(set(scan.column_map) | set(scan.mask_map))


def _empty_tile(scan: N.PScan, tile_rows: int) -> dict:
    t = {}
    for phys in scan.column_map:
        t[phys] = np.zeros((tile_rows,), dtype=np.int64)
    for phys in scan.mask_map:
        t[f"$nn:{phys}"] = np.zeros((tile_rows,), dtype=np.bool_)
    return t


def _tile_feed(scan: N.PScan, session, tile_rows: int,
               skip_rows: int = 0, min_depth: int = 1):
    """The single-node tile feed: (tile dict of padded arrays, n_valid)
    items, wrapped in the asynchronous scan pipeline when
    ``config.scan_pipeline`` enables it (exec/scanpipe.py — prefetch +
    column-parallel decode + double-buffered device staging; tile order
    and content are the synchronous feed's, bit-identical on/off).
    Cold tables stream micro-partition files (host staging: the device
    never holds more than one tile); warm tables slice their RAM
    arrays. ``skip_rows`` drops the already-consumed prefix — the
    mid-statement resume entry point (exec/recovery.py): single-node
    consumption is always a prefix of the deterministic stream order.
    Callers must close the feed (scanpipe.close_feed) on every exit."""
    stats = SP.ScanStats()
    if hasattr(scan, "_store_parts"):
        gen = _store_tiles(scan, session, tile_rows, skip_rows, stats)
    else:
        gen = _ram_tiles(scan, session, tile_rows, skip_rows)
    # min_depth: the dispatch window (exec/tilepipe.py) keeps up to W
    # tiles in flight — a prefetch queue shallower than W would starve
    # the window it exists to feed
    return SP.maybe_pipeline(gen, session.config, device_stage=True,
                             stats=stats, min_depth=min_depth)


def _ram_tiles(scan: N.PScan, session, tile_rows: int,
               skip_rows: int = 0):
    """Warm-table tile producer: slices of the resident RAM arrays."""
    t = session.catalog.table(scan.table_name)
    t.ensure_loaded()
    cols = {phys: np.asarray(t.data[phys]) for phys in scan.column_map}
    for phys in scan.mask_map:
        vm = t.validity.get(phys)
        cols[f"$nn:{phys}"] = (np.asarray(vm, dtype=np.bool_)
                               if vm is not None
                               else np.ones(t.num_rows, dtype=np.bool_))
    rows = t.num_rows
    for off in range(min(skip_rows, max(rows, 0)), max(rows, 0),
                     tile_rows):
        n = min(tile_rows, rows - off)
        yield _pad_tile(cols, off, n, tile_rows), n


class _PendBuf:
    """Offset-cursor ring over decoded partition chunks. ``take(n)``
    copies ONLY the emitted rows — each row at most once, never the
    whole pending tail the old code re-concatenated per emitted tile
    (O(n²) over a partition). A tile covering a chunk EXACTLY hands
    the chunk array over zero-copy; partial-chunk tiles copy rather
    than emit a view, because a view's base is the whole decoded
    partition column and the prefetch queue would pin partitions, not
    tiles (the out-of-core bound is one partition + bounded staging).
    ``skip(n)`` advances the cursor without touching a byte (the
    resume prefix). All columns share one chunk-length spine, so the
    cursor is maintained once."""

    def __init__(self, stats=None):
        self._names: Optional[list[str]] = None
        self._chunks: dict[str, list] = {}
        self._lens: list[int] = []
        self._off = 0           # consumed rows of the FIRST chunk
        self.rows = 0           # rows pending past the cursor
        self._stats = stats

    def append(self, cols: dict) -> None:
        n = len(next(iter(cols.values()))) if cols else 0
        if self._names is None:
            self._names = list(cols)
            self._chunks = {nm: [] for nm in self._names}
        if n == 0:
            return
        for nm in self._names:
            self._chunks[nm].append(cols[nm])
        self._lens.append(n)
        self.rows += n

    def _plan(self, n: int):
        """Slice plan [(chunk_idx, lo, hi)] covering the next n rows,
        plus the advanced cursor (chunks_to_drop, new_offset)."""
        plan = []
        i, off, need = 0, self._off, n
        while need > 0:
            length = self._lens[i]
            t = min(length - off, need)
            plan.append((i, off, off + t))
            need -= t
            off += t
            if off == length:
                i += 1
                off = 0
        return plan, i, off

    def _advance(self, drop: int, off: int, n: int) -> None:
        for _ in range(drop):
            self._lens.pop(0)
            for nm in self._names:
                self._chunks[nm].pop(0)
        self._off = off
        self.rows -= n

    def skip(self, n: int) -> None:
        _, drop, off = self._plan(n)
        self._advance(drop, off, n)

    def take(self, n: int) -> dict:
        plan, drop, off = self._plan(n)
        whole = (len(plan) == 1 and plan[0][1] == 0
                 and plan[0][2] == self._lens[plan[0][0]])
        out = {}
        for nm in self._names:
            chunks = self._chunks[nm]
            if whole:
                out[nm] = chunks[plan[0][0]]
            else:
                parts = [chunks[i][lo:hi] for i, lo, hi in plan]
                out[nm] = parts[0].copy() if len(parts) == 1 \
                    else np.concatenate(parts)
        if self._stats is not None:
            if whole:
                self._stats.view_rows += n
            else:
                self._stats.copy_rows += n
        self._advance(drop, off, n)
        return out


def _bufpool_charge(session, table: str) -> int:
    """The buffer pool's resident bytes for one table — the tiled
    report's ``est_bufpool_bytes`` capacity-plane charge."""
    bpool = BUF.pool_for(session)
    return bpool.table_bytes(table) if bpool is not None else 0


def _pool_chunk(scan: N.PScan, ent: dict) -> dict:
    """Assemble one feed chunk from a buffer-pool entry (the canonical
    ``{"cols", "validity"}`` read_partitions split) — the exact dict
    the cold path builds, so pooled and decoded chunks are
    interchangeable bit-for-bit."""
    cols, validity = ent["cols"], ent["validity"]
    n = len(next(iter(cols.values()))) if cols else 0
    chunk = {}
    for phys in scan.column_map:
        chunk[phys] = cols[phys]
    for phys in scan.mask_map:
        vm = validity.get(phys)
        chunk[f"$nn:{phys}"] = (vm if vm is not None
                                else np.ones(n, dtype=np.bool_))
    return chunk


def _store_tiles(scan: N.PScan, session, tile_rows: int,
                 skip_rows: int = 0, stats=None):
    """Stream a pruned cold scan part-by-part, re-chunked to tile_rows:
    the out-of-core path — peak host memory is one partition + the
    pipeline's bounded staging. A resume's ``skip_rows`` drops whole
    already-consumed partitions WITHOUT reading or decoding them (the
    replay cost of a checkpointed restart is bounded by one partition
    plus ≤ K tiles, never the consumed prefix). Partitions resident in
    the HBM buffer pool (exec/bufferpool.py) are served from the device
    copy — no read, no decode, no host→device transfer; only misses go
    to the store (and hot misses are admitted for next time)."""
    import time as _t

    store = session.catalog.store
    needed = _phys_cols(scan)
    stats = stats if stats is not None else SP.ScanStats()
    pool = SP.decode_pool(session.config)
    bpool = BUF.pool_for(session)
    cols_key = tuple(needed)
    log = getattr(session, "stmt_log", None)
    obs = log is not None and getattr(log, "obs_enabled", False)
    buf = _PendBuf(stats)
    skip_left = max(int(skip_rows), 0)

    parts = list(scan._store_parts)
    start = 0
    for part in parts:
        eff = int(part["num_rows"]) - len(part.get("deleted") or ())
        if skip_left < eff:
            break
        skip_left -= eff
        start += 1
        stats.parts_skipped += 1

    def drain(final: bool):
        nonlocal skip_left
        if skip_left > 0 and buf.rows > 0:
            t = min(skip_left, buf.rows)
            buf.skip(t)  # sub-partition resume remainder: cursor only
            skip_left -= t
        while buf.rows >= tile_rows or (final and buf.rows > 0):
            take = min(tile_rows, buf.rows)
            yield _pad_tile(buf.take(take), 0, take, tile_rows), take

    for part in parts[start:]:
        key = None
        if bpool is not None:
            key = BUF.partition_key(session, scan.table_name, part,
                                    cols_key)
            ent = bpool.lookup(key, log)
            if ent is not None:
                # HBM hit: the decoded chunk is already on-device —
                # the host path (read/decode/transfer) is skipped
                # entirely, like the resume parts_skipped fast path
                stats.parts_resident += 1
                buf.append(_pool_chunk(scan, ent))
                yield from drain(final=False)
                continue
        fault_point("scan_decode")
        dts: list = []  # per-column decode seconds (list.append: atomic)
        t0 = _t.perf_counter()
        cols, validity = store.read_partitions(
            scan.table_name, [part], needed, pool=pool,
            on_decode=dts.append)
        stats.read_s += _t.perf_counter() - t0
        stats.parts_read += 1
        stats.decode_s += sum(dts)
        if log is not None:
            log.bump("host_decodes")
        if obs:
            for dt in dts:
                log.registry.observe("decode_seconds", dt)
        ent = {"cols": {c: np.asarray(v) for c, v in cols.items()},
               "validity": {c: np.asarray(v, dtype=np.bool_)
                            for c, v in validity.items()}}
        chunk = _pool_chunk(scan, ent)
        stats.bytes_decoded += sum(int(a.nbytes)
                                   for a in chunk.values())
        if bpool is not None:
            bpool.offer(key, ent, table=scan.table_name, log=log)
        buf.append(chunk)
        yield from drain(final=False)
    yield from drain(final=True)


def _pad_tile(cols: dict, off: int, n: int, tile_rows: int) -> dict:
    out = {}
    for name, arr in cols.items():
        if not isinstance(arr, np.ndarray) and off == 0 \
                and n == tile_rows and len(arr) == tile_rows:
            # device-resident (buffer-pool) column covering the tile
            # exactly: hand it through — routing it via numpy would
            # round-trip HBM→host→HBM
            out[name] = arr
            continue
        sl = arr[off:off + n]
        if n < tile_rows:
            sl = np.concatenate(
                [sl, np.zeros((tile_rows - n,), dtype=arr.dtype)])
        out[name] = np.ascontiguousarray(sl)
    return out
