"""Parallel retrieve cursors — the endpoint subsystem analog.

Reference: ``DECLARE c PARALLEL RETRIEVE CURSOR FOR ...`` leaves each
segment's result slice ON the segment as a named endpoint; clients open
retrieve-mode connections per endpoint and drain them in parallel with
token auth (src/backend/cdb/endpoint/README, cdbendpoint.c:31-143,
cdbendpointretrieve.c). The point: result extraction scales with segments
instead of funneling through the QD.

Here: the cursor's query executes with the FINAL GATHER MOTION stripped
(when the plan allows — only row-wise Project/Filter may sit above it, the
``GetParallelCursorEndpointPosition`` decision), so the SPMD program's
output stays sharded; each segment's rows become one endpoint. Plans whose
top requires a singleton (global Sort/Limit/aggregate) fall back to ONE
endpoint at the coordinator — the reference's ON_ENTRY position. Clients
retrieve per endpoint over the serving layer ({"retrieve": ...}), in
parallel across threads, authenticated by the cursor's token (the
EndpointTokenHash analog).
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass, field

import numpy as np

from cloudberry_tpu.plan import nodes as N


class CursorError(ValueError):
    pass


@dataclass
class Endpoint:
    segment: int
    batch: object           # ColumnBatch holding this shard's rows
    pos: int = 0            # rows already retrieved
    _decoded: dict | None = None   # decode-once cache (O(limit) chunks)
    _lock: object = field(default_factory=__import__("threading").Lock)

    @property
    def rows_total(self) -> int:
        return self.batch.num_rows()

    def decoded(self) -> dict:
        if self._decoded is None:
            self._decoded = self.batch.decoded_columns()
        return self._decoded


@dataclass
class ParallelCursor:
    name: str
    token: str
    endpoints: list = field(default_factory=list)
    parallel: bool = True   # False = ON_ENTRY fallback (one endpoint)
    vmem_id: int = 0        # lifetime reservation for the held results

    def info(self) -> dict:
        return {"cursor": self.name, "token": self.token,
                "parallel": self.parallel,
                "endpoints": [{"segment": e.segment,
                               "rows": e.rows_total - e.pos}
                              for e in self.endpoints]}


def declare(session, name: str, query_ast) -> dict:
    """Execute the cursor's query, keeping results sharded per segment
    when the plan shape allows; registers the endpoints on the session."""
    from cloudberry_tpu.exec import executor as X
    from cloudberry_tpu.plan.binder import Binder
    from cloudberry_tpu.plan.planner import _optimize

    from cloudberry_tpu.exec.resource import check_admission

    name = name.lower()
    if name in session.parallel_cursors:
        raise CursorError(f"cursor {name!r} already exists")
    plan = _optimize(Binder(session.catalog, session.config).bind_query(query_ast), session)
    # the cursor's query is a statement like any other: per-query budget,
    # queue slot (MAX_COST, priority) and vmem reservation all apply
    est = check_admission(plan, session)
    nseg = session.config.n_segments
    endpoints: list[Endpoint] = []
    parallel = False
    with session._gate, session._admitted(est.peak_bytes):
        if nseg > 1 and getattr(plan, "_direct_segment", None) is None:
            stripped = _strip_top_gather(plan)
            if stripped is not None:
                from cloudberry_tpu.exec.dist_executor import (
                    compile_distributed, prepare_dist_inputs,
                    record_jf_counters, record_motion_stats)

                fn = compile_distributed(stripped, session)
                inputs, _ = prepare_dist_inputs(stripped, session)
                cols, sel, checks, stats = fn(inputs)
                record_motion_stats(stripped, stats, session=session)
                X.raise_checks(checks)
                record_jf_counters(stats,
                                   getattr(session, "stmt_log", None))
                from cloudberry_tpu.plan.feedback import fold_plan

                fold_plan(session, stripped)
                sel_np = np.asarray(sel)
                for s in range(nseg):
                    shard_cols = {k: np.asarray(v)[s]
                                  for k, v in cols.items()}
                    endpoints.append(Endpoint(
                        s, X.make_batch(stripped, shard_cols, sel_np[s])))
                parallel = True
        if not endpoints:
            # ON_ENTRY fallback: the top demands a singleton (global sort/
            # limit/aggregate) — one endpoint at the coordinator
            from cloudberry_tpu.exec.executor import execute

            if nseg > 1:
                from cloudberry_tpu.exec.dist_executor import (
                    execute_distributed)

                batch = execute_distributed(plan, session)
            else:
                batch = execute(plan, session)
            endpoints = [Endpoint(0, batch)]
    cur = ParallelCursor(name, uuid.uuid4().hex, endpoints, parallel)
    # endpoints HOLD their result shards until CLOSE — that memory stays
    # reserved against the engine-wide red line for the cursor's lifetime
    held = sum(int(np.asarray(a).nbytes)
               for e in endpoints for a in e.batch.columns.values())
    cur.vmem_id = next(session._stmt_ids)
    session._vmem.reserve(cur.vmem_id, held, timeout_s=10)
    session.parallel_cursors[name] = cur
    return cur.info()


def retrieve(session, name: str, segment: int, limit: int | None = None,
             token: str | None = None) -> dict:
    """Drain (up to ``limit``) rows from one endpoint — the RETRIEVE
    command. ``token`` must match when given (wire clients always pass
    it; the in-process API may omit)."""
    from cloudberry_tpu.utils.faultinject import fault_point

    fault_point("endpoint_drain")
    cur = session.parallel_cursors.get(name.lower())
    if cur is None:
        raise CursorError(f"unknown cursor {name!r}")
    if token is not None and token != cur.token:
        raise CursorError("invalid endpoint token")
    ep = next((e for e in cur.endpoints if e.segment == segment), None)
    if ep is None:
        raise CursorError(f"cursor {name!r} has no endpoint for segment "
                          f"{segment}")
    # one position per endpoint: concurrent retrieve-mode clients must
    # never receive the same rows (the reference allows ONE retrieving
    # session per endpoint; this lock enforces the same exclusivity)
    with ep._lock:
        cols = ep.decoded()
        names = list(cols)
        arrays = list(cols.values())
        total = len(arrays[0]) if arrays else 0
        hi = total if limit is None else min(ep.pos + max(limit, 0), total)
        rows = [[a[i] for a in arrays] for i in range(ep.pos, hi)]
        ep.pos = hi
    return {"columns": names, "rows": rows,
            "remaining": total - hi, "segment": segment}


def close_cursor(session, name: str) -> str:
    cur = session.parallel_cursors.pop(name.lower(), None)
    if cur is None:
        raise CursorError(f"unknown cursor {name!r}")
    session._vmem.release(cur.vmem_id)
    return f"CLOSE {name}"


def _strip_top_gather(plan: N.PlanNode):
    """Splice out the top gather motion when only row-wise nodes sit above
    it; None when the plan's top genuinely needs a singleton."""
    spine = []
    node = plan
    while isinstance(node, (N.PProject, N.PFilter)):
        spine.append(node)
        node = node.child
    if not (isinstance(node, N.PMotion) and node.kind == "gather"
            and not node.pre_compact):
        return None
    child = node.child
    if not spine:
        return child
    spine[-1].child = child
    for up in spine:
        up.sharding = child.sharding
    return plan
