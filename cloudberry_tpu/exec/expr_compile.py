"""Compile bound expressions to jax.numpy — the ExprState/XLA bridge.

``compile_expr`` returns a function of (columns: dict[str, Array]) → Array.
Everything is vectorized over the batch; XLA fuses the resulting elementwise
graph into the surrounding kernel (the reference gets per-tuple interpreted
evaluation via ExecEvalExpr — here fusion is free).
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp
import numpy as np

from cloudberry_tpu.plan import expr as ex
from cloudberry_tpu.types import DType

Columns = dict[str, jnp.ndarray]


def compile_expr(e: ex.Expr) -> Callable[[Columns], jnp.ndarray]:
    if isinstance(e, ex.ColumnRef):
        name = e.name
        return lambda cols: cols[name]

    if isinstance(e, ex.Literal):
        val = np.asarray(e.value, dtype=e.dtype.np_dtype)
        return lambda cols: jnp.asarray(val)

    if isinstance(e, ex.Param):
        # runtime-bound literal: the Lowerer injects the slot's value next
        # to the columns (from the program's "$params" input), so a generic
        # plan re-executes with new literals WITHOUT retracing. A trace
        # without bindings (non-generic recompile of a rewritten plan)
        # bakes the build-time value — the original statement's constant.
        name = e.input_name
        fallback = None if e.value is None else \
            np.asarray(e.value, dtype=e.dtype.np_dtype)
        if fallback is None:
            return lambda cols: cols[name]
        return lambda cols: cols[name] if name in cols \
            else jnp.asarray(fallback)

    if isinstance(e, ex.BinOp):
        lf, rf = compile_expr(e.left), compile_expr(e.right)
        op = _BINOPS[e.op]
        return lambda cols: op(lf(cols), rf(cols))

    if isinstance(e, ex.UnaryOp):
        f = compile_expr(e.operand)
        if e.op == "not":
            return lambda cols: jnp.logical_not(f(cols))
        if e.op == "-":
            return lambda cols: -f(cols)
        raise NotImplementedError(e.op)

    if isinstance(e, ex.Cast):
        f = compile_expr(e.operand)
        src, dst = e.operand.dtype, e.dtype
        dt = dst.np_dtype
        if src.base == DType.DECIMAL and dst.base == DType.FLOAT64:
            inv = 1.0 / (10.0 ** src.scale)
            return lambda cols: f(cols).astype(dt) * inv
        if src.base == DType.FLOAT64 and dst.base == DType.DECIMAL:
            mul = 10.0 ** dst.scale
            return lambda cols: jnp.rint(f(cols) * mul).astype(dt)
        if src.base == DType.DECIMAL and dst.base == DType.DECIMAL:
            if dst.scale >= src.scale:
                mul = np.int64(10 ** (dst.scale - src.scale))
                return lambda cols: f(cols) * mul
            return lambda cols: _scale_down(f(cols), src.scale - dst.scale)
        if src.base in (DType.INT32, DType.INT64) and dst.base == DType.DECIMAL:
            mul = np.int64(10 ** dst.scale)
            return lambda cols: f(cols).astype(dt) * mul
        if src.base == DType.DECIMAL and dst.base in (DType.INT32, DType.INT64):
            return lambda cols: _scale_down(f(cols), src.scale).astype(dt)
        return lambda cols: f(cols).astype(dt)

    if isinstance(e, ex.Func):
        return _compile_func(e)

    if isinstance(e, ex.CaseWhen):
        whens = [(compile_expr(c), compile_expr(v)) for c, v in e.whens]
        other = compile_expr(e.otherwise) if e.otherwise is not None else None
        zero = np.asarray(0, dtype=e.dtype.np_dtype)

        def run_case(cols):
            out = other(cols) if other is not None else jnp.asarray(zero)
            # Evaluate in reverse so the FIRST matching WHEN wins.
            for cf, vf in reversed(whens):
                out = jnp.where(cf(cols), vf(cols), out)
            return out

        return run_case

    if isinstance(e, ex.DictLookup):
        f = compile_expr(e.column)
        table = jnp.asarray(e.table)

        def lookup(cols):
            codes = f(cols)
            # code -1 (value absent from dictionary) must not match predicates
            safe = jnp.clip(codes, 0, table.shape[0] - 1)
            hit = jnp.take(table, safe, axis=0)
            if table.dtype == np.bool_:
                return jnp.where(codes >= 0, hit, False)
            return jnp.where(codes >= 0, hit, -1)

        return lookup

    if isinstance(e, ex.IsValid):
        names, neg = e.mask_names, e.negate

        def valid(cols):
            # mask columns may be bool or 0/1 ints (agg companions)
            v = cols[names[0]].astype(jnp.bool_)
            for n in names[1:]:
                v = jnp.logical_and(v, cols[n].astype(jnp.bool_))
            return jnp.logical_not(v) if neg else v

        return valid

    raise NotImplementedError(type(e).__name__)


def _scale_down(x: jnp.ndarray, k: int) -> jnp.ndarray:
    """Rounded (half away from zero) integer division by 10**k — rescales a
    decimal product back to its result scale."""
    if k == 0:
        return x
    d = np.int64(10 ** k)
    half = np.int64(10 ** k // 2)
    return jnp.where(x >= 0, (x + half) // d, -((-x + half) // d))


def _compile_func(e: ex.Func):
    args = [compile_expr(a) for a in e.args]
    name = e.name
    if name == "extract_year":
        # days-since-epoch → civil year (vectorized Hinnant algorithm).
        return lambda cols: _civil_from_days(args[0](cols))[0]
    if name == "extract_month":
        return lambda cols: _civil_from_days(args[0](cols))[1]
    if name == "abs":
        return lambda cols: jnp.abs(args[0](cols))
    if name == "sqrt":
        # guard tiny negative values from the stddev identity's cancellation
        return lambda cols: jnp.sqrt(jnp.maximum(args[0](cols), 0.0))
    if name == "scale_down":
        # args: (decimal expr, literal k) — binder-inserted rescale after
        # decimal multiplication.
        k = int(e.args[1].value)  # type: ignore[attr-defined]
        return lambda cols: _scale_down(args[0](cols), k)
    if name.startswith("udf:"):
        # jit scalar UDF (exec/udf.py): the registered callable traces
        # into the program — a TPU-native function body
        from cloudberry_tpu.exec import udf as U

        u = U.lookup(name[4:])
        if u is not None and u.jit:
            fn = u.fn
            return lambda cols: fn(*[a(cols) for a in args])
    raise NotImplementedError(f"function {name}")


def _civil_from_days(z):
    """days since 1970-01-01 → (year, month, day); Howard Hinnant's
    branchless civil-from-days, exact for all int32 days."""
    z = z.astype(jnp.int64) + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = mp + jnp.where(mp < 10, 3, -9)
    y = y + (m <= 2)
    return y.astype(jnp.int32), m.astype(jnp.int32), d.astype(jnp.int32)


def _safe_div(a, b):
    # SQL raises on division by zero; masked-out lanes may legitimately hold
    # zeros, so evaluate total-function style: 0 for zero divisors.
    b = jnp.asarray(b)
    nz = b != 0
    return jnp.where(nz, a / jnp.where(nz, b, 1), 0)


def _safe_mod(a, b):
    # SQL modulo truncates toward zero (fmod semantics), unlike Python's
    # floor-mod; zero divisors evaluate total-function style like _safe_div.
    b = jnp.asarray(b)
    nz = b != 0
    return jnp.where(nz, jnp.fmod(a, jnp.where(nz, b, 1)), 0)


_BINOPS = {
    "+": jnp.add,
    "-": jnp.subtract,
    "*": jnp.multiply,
    "/": _safe_div,
    "%": _safe_mod,
    "=": lambda a, b: a == b,
    "<>": lambda a, b: a != b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "and": jnp.logical_and,
    "or": jnp.logical_or,
}
