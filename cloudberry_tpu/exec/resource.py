"""Resource governance — the vmem-tracker / resource-group analog.

The reference tracks per-segment virtual memory in chunks with a red zone and
a runaway killer (vmem_tracker.c:94, redzone_handler.c, runaway_cleaner.c),
and gates statement admission through a shared slot pool (resgroup.c:135).
Here memory is PREDICTABLE — every node's capacity and column widths are
static at plan time — so governance is:

- a plan-time memory estimator (sum of live intermediate arrays, an upper
  bound analogous to per-operator memory quotas), refusing queries whose
  estimate exceeds ``resource.query_mem_bytes`` BEFORE compiling (the
  admission decision the reference can only make with runtime tracking);
- a concurrency gate (slot pool) limiting simultaneous statements.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

from cloudberry_tpu.plan import nodes as N


class ResourceError(RuntimeError):
    pass


class RunawayError(ResourceError):
    """A RUNNING statement's adaptive growth crossed the vmem red line —
    it is terminated (runaway_cleaner.c), never spilled."""


class TenantQueueFull(ResourceError):
    """Per-tenant admission refusal: the tenant's bounded request queue
    (or concurrency slot wait) stayed full past the grace period.
    RETRYABLE by taxonomy name (lifecycle._RETRYABLE_NAMES) — the
    refusal is about load, not the statement."""


@dataclass
class TenantGroup:
    """One named workload tenant's resource-group record — the
    resgroup.c analog extended from admission-only to THROUGHPUT
    scheduling (sched/tenancy.py owns the deficit-weighted-round-robin
    pick order and aging; this record is the declared shape plus the
    runtime accounting it schedules with). All mutable fields are
    guarded by the owning TenantScheduler's lock."""

    name: str
    weight: int = 1
    max_concurrency: int = 0        # concurrent statements; 0 = unlimited
    max_queue: int = 64             # bounded queue depth (backpressure)
    # -- runtime state (TenantScheduler's lock) --
    deficit: float = 0.0            # DWRR deficit counter, in requests
    queued: int = 0                 # waiting in this tenant's QUEUE
    waiting: int = 0                # direct-path slot() waiters — kept
    # separate from queued: the two paths would otherwise fight over one
    # counter (slot increments, enqueue overwrites with len(queue))
    running: int = 0                # picked/admitted, not yet finished
    last_pick_t: float = 0.0        # monotonic time of the last pick —
    # the aging channel only serves tenants the scheduler has NOT
    # touched lately (over-age heads alone would turn deep saturation
    # into global FIFO and erase the weights)
    # -- observability counters --
    picks: int = 0                  # requests admitted by the scheduler
    served: int = 0                 # requests finished (ok or error)
    rejected: int = 0               # TenantQueueFull refusals
    aged: int = 0                   # picks forced by the starvation bound
    wait_sum_ms: float = 0.0        # queue-wait accumulation (picked)
    wait_max_ms: float = 0.0
    max_depth: int = 0              # peak queue depth observed


@dataclass
class MemoryEstimate:
    peak_bytes: int
    per_node: list[tuple[str, int]]


def estimate_plan_memory(plan: N.PlanNode) -> MemoryEstimate:
    """Upper-bound device bytes per segment for one query.

    Node capacities are already per-segment after the distribution pass
    (scan capacities are shard capacities, motion capacities are receive
    buffers), so summing capacity × Σ column widths (+ masks) directly gives
    the per-segment bound. An over-estimate (XLA frees fused intermediates)
    but shape-exact — the point is a hard admission bound, not a profile."""
    per_node: list[tuple[str, int]] = []
    total = 0

    def width(node: N.PlanNode) -> int:
        w = 1  # selection mask
        for f in node.fields:
            w += f.type.np_dtype.itemsize
        return w

    def cap_of(node: N.PlanNode) -> int:
        if isinstance(node, N.PScan):
            return node.capacity
        if isinstance(node, N.PAgg):
            return node.capacity
        if isinstance(node, N.PMotion):
            return node.out_capacity or cap_of(node.child)
        if isinstance(node, N.PJoin):
            if not node.unique_build:
                return node.out_capacity
            return cap_of(node.probe)
        if isinstance(node, N.PConcat):
            return sum(cap_of(c) for c in node.inputs)
        kids = node.children()
        return max((cap_of(c) for c in kids), default=1)

    def rec(node: N.PlanNode):
        nonlocal total
        b = cap_of(node) * width(node)
        per_node.append((node.title(), b))
        total += b
        for c in node.children():
            rec(c)

    rec(plan)
    return MemoryEstimate(total, per_node)


def check_admission(plan: N.PlanNode, session) -> MemoryEstimate:
    from cloudberry_tpu.utils.faultinject import fault_point

    fault_point("admission_check")
    est = estimate_plan_memory(plan)
    budget = session.config.resource.query_mem_bytes
    if est.peak_bytes > budget:
        top = sorted(est.per_node, key=lambda x: -x[1])[:3]
        raise ResourceError(
            f"query memory estimate {est.peak_bytes >> 20} MiB exceeds the "
            f"per-query budget {budget >> 20} MiB "
            f"(largest nodes: {top}); raise "
            "config.resource.query_mem_bytes or reduce capacities")
    return est


_PRIORITY = {"min": 0, "low": 100, "medium": 200, "high": 300, "max": 400}


@dataclass
class ResourceQueue:
    """A named admission queue (resqueue.c analog): bounded concurrent
    statements, a plan-cost ceiling (here: the memory estimate in bytes —
    the engine's native cost unit), and a backoff.c-style priority weight
    that orders WAITERS (higher priority wakes first)."""

    name: str
    active_statements: int = 0      # 0 = unlimited
    max_cost: int = 0               # bytes; 0 = unlimited
    priority: str = "medium"
    active: int = 0                 # running statements (observability)
    waiting: int = 0


class QueueManager:
    """Slot accounting for every resource queue in one engine process.
    Waiters admit in (priority desc, arrival) order via a per-queue heap —
    the prioritization backoff.c implements with CPU weights, expressed
    here at the admission boundary where this engine schedules work."""

    def __init__(self):
        self._cond = threading.Condition()
        self._seq = 0
        self._waiters: dict[str, list] = {}

    def slot(self, queue: ResourceQueue, cost: int, priority: str,
             timeout_s: float = 60.0):
        import contextlib
        import heapq
        import time as _t

        if queue.max_cost and cost > queue.max_cost:
            raise ResourceError(
                f"resource queue {queue.name!r}: statement cost "
                f"{cost >> 20} MiB exceeds MAX_COST "
                f"{queue.max_cost >> 20} MiB")

        @contextlib.contextmanager
        def _slot():
            if not queue.active_statements:
                with self._cond:
                    queue.active += 1
                try:
                    yield
                finally:
                    with self._cond:
                        queue.active -= 1
                return
            key = None
            with self._cond:
                self._seq += 1
                key = (-_PRIORITY.get(priority, 200), self._seq)
                heap = self._waiters.setdefault(queue.name, [])
                heapq.heappush(heap, key)
                queue.waiting = len(heap)
                end = _t.monotonic() + timeout_s
                try:
                    # admit only when a slot is free AND no better-ranked
                    # waiter exists (priority beats arrival)
                    while queue.active >= queue.active_statements \
                            or heap[0] != key:
                        left = end - _t.monotonic()
                        if left <= 0:
                            raise ResourceError(
                                f"resource queue {queue.name!r}: no slot "
                                f"within {timeout_s:.0f}s "
                                f"({queue.active} active, "
                                f"{len(heap)} waiting)")
                        self._cond.wait(timeout=min(left, 1.0))
                    heapq.heappop(heap)
                finally:
                    if heap and key in heap:
                        heap.remove(key)
                        heapq.heapify(heap)
                    queue.waiting = len(heap)
                    # whoever is next-ranked must learn the head changed
                    # NOW, not on its poll timeout
                    self._cond.notify_all()
                queue.active += 1
            try:
                yield
            finally:
                with self._cond:
                    queue.active -= 1
                    self._cond.notify_all()

        return _slot()


class VmemTracker:
    """Engine-wide memory reservation (vmem_tracker.c + redzone_handler.c
    analog): every admitted statement reserves its plan-time estimate;
    reservations past the red line WAIT (bounded), and a RUNNING statement
    whose adaptive growth (join-expansion retry) would cross the red line
    is TERMINATED — the runaway_cleaner.c decision, made exactly at the
    one point where this engine's memory is not statically predictable."""

    def __init__(self, budget_bytes: int):
        self.budget = budget_bytes
        self.used = 0
        self.by_stmt: dict[int, int] = {}
        self._cond = threading.Condition()

    def reserve(self, stmt_id: int, nbytes: int,
                timeout_s: float = 60.0) -> None:
        import time as _t

        if nbytes > self.budget:
            # can NEVER fit — fail fast instead of holding queue/gate
            # slots for the whole timeout
            raise ResourceError(
                f"vmem red zone: {nbytes >> 20} MiB exceeds the entire "
                f"engine budget {self.budget >> 20} MiB")
        end = _t.monotonic() + timeout_s
        with self._cond:
            while self.used + nbytes > self.budget:
                self._cond.wait(timeout=max(
                    min(end - _t.monotonic(), 1.0), 0.01))
                if _t.monotonic() >= end:
                    raise ResourceError(
                        f"vmem red zone: {nbytes >> 20} MiB reservation "
                        f"cannot fit ({self.used >> 20} MiB of "
                        f"{self.budget >> 20} MiB in use) after "
                        f"{timeout_s:.0f}s")
            self.used += nbytes
            self.by_stmt[stmt_id] = self.by_stmt.get(stmt_id, 0) + nbytes

    def grow(self, stmt_id: int, new_total: int) -> None:
        """Re-reserve a RUNNING statement at a larger estimate; crossing
        the red line terminates THIS statement (it is the runaway — its
        growth, not its admission, broke the budget)."""
        with self._cond:
            cur = self.by_stmt.get(stmt_id, 0)
            if self.used - cur + new_total > self.budget:
                raise RunawayError(
                    "runaway query terminated: adaptive growth to "
                    f"{new_total >> 20} MiB would cross the vmem red "
                    f"zone ({(self.used - cur) >> 20} MiB held by other "
                    f"statements, budget {self.budget >> 20} MiB)")
            self.used += new_total - cur
            self.by_stmt[stmt_id] = new_total

    def release(self, stmt_id: int) -> None:
        with self._cond:
            self.used -= self.by_stmt.pop(stmt_id, 0)
            self._cond.notify_all()


class AdmissionGate:
    """Slot-pool concurrency limit (ResGroupSlotData free list analog).
    Tracks active and peak occupancy so servers/tests can OBSERVE that
    admission control actually bounded concurrency."""

    def __init__(self, max_concurrency: int):
        self._sem = threading.BoundedSemaphore(max_concurrency)
        self.max_concurrency = max_concurrency
        self._lock = threading.Lock()
        self.active = 0
        self.peak = 0
        self.total_admitted = 0

    def __enter__(self):
        acquired = self._sem.acquire(timeout=60.0)
        if not acquired:
            raise ResourceError(
                "admission timeout: all "
                f"{self.max_concurrency} statement slots busy for 60s")
        with self._lock:
            self.active += 1
            self.peak = max(self.peak, self.active)
            self.total_admitted += 1
        return self

    def __exit__(self, *exc):
        with self._lock:
            self.active -= 1
        self._sem.release()
        return False
