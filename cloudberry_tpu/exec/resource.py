"""Resource governance — the vmem-tracker / resource-group analog.

The reference tracks per-segment virtual memory in chunks with a red zone and
a runaway killer (vmem_tracker.c:94, redzone_handler.c, runaway_cleaner.c),
and gates statement admission through a shared slot pool (resgroup.c:135).
Here memory is PREDICTABLE — every node's capacity and column widths are
static at plan time — so governance is:

- a plan-time memory estimator (sum of live intermediate arrays, an upper
  bound analogous to per-operator memory quotas), refusing queries whose
  estimate exceeds ``resource.query_mem_bytes`` BEFORE compiling (the
  admission decision the reference can only make with runtime tracking);
- a concurrency gate (slot pool) limiting simultaneous statements.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

from cloudberry_tpu.plan import nodes as N


class ResourceError(RuntimeError):
    pass


@dataclass
class MemoryEstimate:
    peak_bytes: int
    per_node: list[tuple[str, int]]


def estimate_plan_memory(plan: N.PlanNode) -> MemoryEstimate:
    """Upper-bound device bytes per segment for one query.

    Node capacities are already per-segment after the distribution pass
    (scan capacities are shard capacities, motion capacities are receive
    buffers), so summing capacity × Σ column widths (+ masks) directly gives
    the per-segment bound. An over-estimate (XLA frees fused intermediates)
    but shape-exact — the point is a hard admission bound, not a profile."""
    per_node: list[tuple[str, int]] = []
    total = 0

    def width(node: N.PlanNode) -> int:
        w = 1  # selection mask
        for f in node.fields:
            w += f.type.np_dtype.itemsize
        return w

    def cap_of(node: N.PlanNode) -> int:
        if isinstance(node, N.PScan):
            return node.capacity
        if isinstance(node, N.PAgg):
            return node.capacity
        if isinstance(node, N.PMotion):
            return node.out_capacity or cap_of(node.child)
        if isinstance(node, N.PJoin):
            if not node.unique_build:
                return node.out_capacity
            return cap_of(node.probe)
        if isinstance(node, N.PConcat):
            return sum(cap_of(c) for c in node.inputs)
        kids = node.children()
        return max((cap_of(c) for c in kids), default=1)

    def rec(node: N.PlanNode):
        nonlocal total
        b = cap_of(node) * width(node)
        per_node.append((node.title(), b))
        total += b
        for c in node.children():
            rec(c)

    rec(plan)
    return MemoryEstimate(total, per_node)


def check_admission(plan: N.PlanNode, session) -> MemoryEstimate:
    est = estimate_plan_memory(plan)
    budget = session.config.resource.query_mem_bytes
    if est.peak_bytes > budget:
        top = sorted(est.per_node, key=lambda x: -x[1])[:3]
        raise ResourceError(
            f"query memory estimate {est.peak_bytes >> 20} MiB exceeds the "
            f"per-query budget {budget >> 20} MiB "
            f"(largest nodes: {top}); raise "
            "config.resource.query_mem_bytes or reduce capacities")
    return est


class AdmissionGate:
    """Slot-pool concurrency limit (ResGroupSlotData free list analog).
    Tracks active and peak occupancy so servers/tests can OBSERVE that
    admission control actually bounded concurrency."""

    def __init__(self, max_concurrency: int):
        self._sem = threading.BoundedSemaphore(max_concurrency)
        self.max_concurrency = max_concurrency
        self._lock = threading.Lock()
        self.active = 0
        self.peak = 0
        self.total_admitted = 0

    def __enter__(self):
        acquired = self._sem.acquire(timeout=60.0)
        if not acquired:
            raise ResourceError(
                "admission timeout: all "
                f"{self.max_concurrency} statement slots busy for 60s")
        with self._lock:
            self.active += 1
            self.peak = max(self.peak, self.active)
            self.total_admitted += 1
        return self

    def __exit__(self, *exc):
        with self._lock:
            self.active -= 1
        self._sem.release()
        return False
