"""Query instrumentation — the instrument.c / explain_gp.c analog.

The reference times every executor node per tuple (InstrStartNode/
InstrStopNode) and ships per-QE stats to the QD for distributed EXPLAIN
ANALYZE (cdbexplain_sendExecStats, explain_gp.c:384). Here the whole plan is
ONE fused XLA program, so per-node wall time is not separable — but per-node
ROW COUNTS are (cheap in-program reductions), and they answer the questions
EXPLAIN ANALYZE usually answers (selectivity, join fanout, motion width).
Whole-query compile and execute wall times complete the picture, split
honestly: the AOT lower→compile API times compilation alone, and the
fallback two-call method subtracts a warm execution from the cold first
call (the old code labeled the whole first call ``compile_s`` even though
that call also executed).

``StatementLog`` is also the engine's telemetry hub (ISSUE 9): its
counters live on an ``obs.metrics.MetricsRegistry`` (``counters`` is a
view), finished statements feed the pg_stat_statements-class aggregate
table (obs/statements.py), and completed trace span trees land in a
bounded ring (obs/trace.py) — one instance spans every backend of a
server, so `meta "metrics"/"statements"/"trace"` answer engine-wide.

The ``metrics_hook`` list on a Session is the query_info_collect_hook
analog (src/include/utils/metrics_utils.h:39): every instrumented run
emits a QueryMetrics record to each registered hook; a raising hook is
counted (``metrics_hook_errors``) and never aborts the statement.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import jax.numpy as jnp
import numpy as np

from cloudberry_tpu.plan import nodes as N


class StatementLog:
    """Per-engine statement history + active registry — the
    pg_stat_activity / log-collector analog. One instance is shared by
    every connection session of a server (like the admission gate), so
    "who is running what" spans backends. Ring-buffered: observability
    must never grow without bound."""

    def __init__(self, capacity: int = 256):
        import collections
        import itertools
        import threading

        from cloudberry_tpu.obs.metrics import CounterView, MetricsRegistry
        from cloudberry_tpu.obs.statements import StatementStats

        self._recent = collections.deque(maxlen=capacity)
        self._active: dict[int, dict] = {}
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        # engine-wide counters (compiles, dispatches, stmt_cache_hits,
        # generic_hits, ...) re-homed onto the obs metrics registry
        # (obs/metrics.py): ONE home for counters/gauges/histograms,
        # with a Prometheus exposition; ``counters`` stays as a mapping
        # view so pre-registry readers keep working
        self.registry = MetricsRegistry()
        self.counters = CounterView(self.registry)
        # pg_stat_statements analog: per-skeleton aggregates fed by
        # finish(); bounded (obs/statements.py)
        self.statements = StatementStats()
        # completed statement trace span trees, newest last (bounded)
        self._trace_ring = collections.deque(maxlen=64)
        self._trace_seq = itertools.count()
        # slow-statement flight bundles, newest last (obs/flightrec.py;
        # bounded — the forensics plane must never become the leak)
        self._flight_ring = collections.deque(maxlen=16)
        self.obs_enabled = True
        self.trace_sample = 1
        self.slow_ms = 5000.0

    def configure_obs(self, obs_cfg) -> None:
        """Apply a session's ObsConfig (config.py). Called once at
        session construction; server backends share the server's log, so
        the serving config wins engine-wide."""
        import collections

        from cloudberry_tpu.obs.statements import StatementStats

        self.obs_enabled = bool(obs_cfg.enabled)
        self.trace_sample = max(1, int(obs_cfg.trace_sample))
        self._trace_ring = collections.deque(
            self._trace_ring, maxlen=max(1, obs_cfg.trace_ring))
        self._flight_ring = collections.deque(
            self._flight_ring, maxlen=max(1, obs_cfg.flight_ring))
        self.slow_ms = float(getattr(obs_cfg, "slow_ms", 0.0))
        if self.statements.max_rows != obs_cfg.statements_max:
            self.statements = StatementStats(max(1, obs_cfg.statements_max))
        self._max_spans = max(16, obs_cfg.max_spans)

    def bump(self, name: str, n: int = 1, tenant: str | None = None) -> None:
        self.registry.bump(name, n, tenant=tenant)

    def counter(self, name: str) -> int:
        return self.registry.counter(name)

    def counter_snapshot(self) -> dict:
        return self.registry.counter_snapshot()

    # ------------------------------------------------------------- tracing

    def trace_this(self) -> bool:
        """Sampling gate: keep every Nth statement's span tree."""
        if not self.obs_enabled:
            return False
        return next(self._trace_seq) % self.trace_sample == 0

    def start_trace(self, sid: int, sql: str, tenant: str | None = None):
        """A Trace for statement ``sid`` when tracing is on and the
        sampler picks it, else None. The caller hangs it on the
        statement's lifecycle handle (handle.trace) — that is how spans
        follow the statement across threads."""
        if not self.trace_this():
            return None
        from cloudberry_tpu.obs.trace import Trace

        return Trace(sid, sql, max_spans=getattr(self, "_max_spans", 512),
                     tenant=tenant)

    def traces(self, limit: int = 16) -> list[dict]:
        """Most recent completed trace exports, newest first."""
        out = list(self._trace_ring)[-max(1, limit):]
        return out[::-1]

    # ------------------------------------------------------ flight ring

    def add_flight(self, bundle: dict) -> None:
        """Record one flight-recorder bundle (obs/flightrec.py); deque
        appends are GIL-atomic, like the trace ring's."""
        self._flight_ring.append(bundle)
        self.registry.bump("flight_captures")

    def flights(self, limit: int = 8) -> list[dict]:
        """Most recent flight bundles, newest first (``meta "flight"``)."""
        out = list(self._flight_ring)[-max(1, limit):]
        return out[::-1]

    def ring_sizes(self) -> dict:
        """Current ring occupancy — the capacity plane's gauge feed."""
        return {"traces": len(self._trace_ring),
                "flights": len(self._flight_ring)}

    def begin(self, sql: str, session_id: int = 0) -> int:
        sid = next(self._ids)
        with self._lock:
            self._active[sid] = {
                "id": sid, "session": session_id, "state": "running",
                "sql": sql[:500], "started": time.time(),
                # durations derive from the MONOTONIC clock (the same
                # clock lifecycle deadlines use); "started" stays wall
                # time for the activity view's human timestamps
                "_t0": time.monotonic()}
        return sid

    # ------------------------------------------------ statement lifecycle
    # The active registry doubles as the cancellation directory (the
    # pg_stat_activity + pg_cancel_backend pair): a session attaches its
    # StatementHandle at begin time, and any thread — the watchdog, the
    # server's `cancel <id>` verb — cancels by statement id.

    def attach(self, sid: int, handle) -> None:
        """Register a lifecycle.StatementHandle for an active statement."""
        with self._lock:
            entry = self._active.get(sid)
            if entry is not None:
                entry["handle"] = handle

    def active_handles(self) -> list[tuple[int, object]]:
        """(statement id, handle) for every active statement that has
        one — the watchdog's scan set."""
        with self._lock:
            return [(sid, e["handle"]) for sid, e in self._active.items()
                    if e.get("handle") is not None]

    def cancel(self, sid: int, reason: str = "cancelled") -> bool:
        """Cancel an active statement by id (pg_cancel_backend analog).
        Returns False when the id is not an active, cancellable
        statement (already finished, or never attached a handle)."""
        with self._lock:
            entry = self._active.get(sid)
            handle = entry.get("handle") if entry is not None else None
        if handle is None:
            return False
        if handle.token.cancel(reason,
                               f"statement {sid} cancelled by request"):
            self.bump("cancel_requests")
        self.mark_cancelling(sid)
        return True

    def mark_cancelling(self, sid: int) -> None:
        with self._lock:
            entry = self._active.get(sid)
            if entry is not None:
                entry["state"] = "cancelling"

    def set_state(self, sid: int, state: str) -> None:
        """Lifecycle state for the activity view (running/recovering).
        'cancelling' is sticky — a cancelled statement must never read
        as healthy again."""
        with self._lock:
            entry = self._active.get(sid)
            if entry is not None and entry.get("state") != "cancelling":
                entry["state"] = state

    def annotate(self, sid: int, **kv) -> None:
        """Attach observability fields to an ACTIVE statement (retry
        attempts, backoff); they ride into the history entry at
        finish()."""
        with self._lock:
            entry = self._active.get(sid)
            if entry is not None:
                entry.update(kv)

    def finish(self, sid: int, status: str, rows: int = -1,
               error: str | None = None, **extra) -> None:
        with self._lock:
            entry = self._active.pop(sid, None)
            if entry is None:
                return
            # the handle (and its token) must not outlive the statement
            # in the history ring; its trace closes below, outside the
            # lock (export walks the span list)
            handle = entry.pop("handle", None)
            entry.pop("state", None)
            entry["wall_s"] = round(
                time.monotonic() - entry.pop("_t0"), 4)
            entry["status"] = status
            entry["rows"] = rows
            if error:
                entry["error"] = error[:500]
            # per-statement scheduler observability (compile count, cache
            # path, batch membership) rides the history entry
            entry.update(extra)
            self._recent.append(entry)
        if not self.obs_enabled:
            return
        if status == "requeued":
            # dispatcher bookkeeping, not an execution: the statement
            # re-runs through session.sql (which logs/traces it for
            # real) — feeding this stub into the statements table /
            # latency histogram / trace ring would double-count it
            return
        # live progress closes with the statement: success is EXACTLY
        # 1.0 (the monotone contract's endpoint), and the final
        # fraction rides the history entry so a failed statement's
        # partial progress stays inspectable after the fact
        prog = getattr(handle, "progress", None)
        if prog is not None:
            if status != "error":
                prog.complete()
            entry["progress"] = prog.fraction
        # pg_stat_statements aggregation + trace close ride every finish
        # path (session.sql, the dispatcher's batched finishes) — one
        # funnel, so the counters-consistency contract holds engine-wide
        self.statements.observe(entry)
        self.registry.observe("statement_seconds", entry["wall_s"])
        trace = getattr(handle, "trace", None)
        if trace is not None:
            trace.finish(status)
            self._trace_ring.append(trace.export())
            self.registry.bump("trace_statements")
            if trace.dropped:
                self.registry.bump("trace_spans_dropped", trace.dropped)

    def activity(self) -> list[dict]:
        """Currently-executing statements (pg_stat_activity role), with
        live lifecycle state: id, state (running/cancelling), elapsed,
        and time left to the deadline when one is set."""
        mono = time.monotonic()
        out = []
        with self._lock:
            for e in self._active.values():
                row = {k: v for k, v in e.items()
                       if k not in ("handle", "_t0")}
                row["elapsed_s"] = round(mono - e["_t0"], 4)
                h = e.get("handle")
                if h is not None and h.deadline is not None:
                    row["deadline_in_s"] = round(h.deadline - mono, 4)
                p = getattr(h, "progress", None)
                if p is not None:
                    # Progress._lock is a declared leaf below this lock
                    row["progress"] = round(p.fraction, 4)
                out.append(row)
        return out

    def progress_rows(self) -> list[dict]:
        """Live per-statement progress (``meta "progress"``): every
        active statement's monotone fraction + tile/row positions, with
        enough identity (id, sql, state, elapsed) to act on — the
        pg_stat_progress_* role."""
        mono = time.monotonic()
        out = []
        with self._lock:
            entries = [(dict(id=e["id"], sql=e["sql"],
                             state=e.get("state", "running"),
                             elapsed_s=round(mono - e["_t0"], 4)),
                        getattr(e.get("handle"), "progress", None))
                       for e in self._active.values()]
        for row, p in entries:
            row.update(p.snapshot() if p is not None
                       else {"fraction": None})
            out.append(row)
        return out

    def recent(self, limit: int = 50) -> list[dict]:
        """Most recent completed statements, newest first."""
        with self._lock:
            out = list(self._recent)[-limit:]
        return out[::-1]


@dataclass
class QueryMetrics:
    """One executed statement's stats (the metrics-collector payload)."""
    query: str
    wall_s: float
    compile_s: float
    rows_out: int
    # plan-order list of (node title, sharding, rows selected after the node)
    node_rows: list[tuple[str, str, int]] = field(default_factory=list)
    # XLA program constructions this run charged to the engine counter
    # (the StatementLog compile counter — the honest-split cross-check)
    compiles: int = 0


class InstrumentingMixin:
    """Mixes into a Lowerer: records post-node selected-row counts."""

    def __init_instrument__(self):
        self.node_counts: dict[int, jnp.ndarray] = {}

    def lower(self, node):  # type: ignore[override]
        cols, sel = super().lower(node)  # type: ignore[misc]
        self.node_counts[id(node)] = jnp.sum(sel.astype(jnp.int64))
        return cols, sel


def plan_nodes_in_order(plan: N.PlanNode) -> list[N.PlanNode]:
    out = []

    def rec(n):
        out.append(n)
        for c in n.children():
            rec(c)

    rec(plan)
    return out


# ------------------------------------------------------- timing discipline


def _timed_compile_run(fn, inputs, log=None):
    """(result, compile_s, exec_s) for a jitted ``fn`` on ``inputs`` —
    the honest compile-vs-execute split. Preferred: the AOT API
    (``fn.lower().compile()``) times compilation ALONE and executes
    once. Fallback (older jax / non-jit callables): two calls — the
    first pays compile+execute, the second executes warm, and the split
    is the difference (never negative). Both legs record trace spans
    and stage histograms when the thread is inside a traced statement."""
    import jax

    from cloudberry_tpu.obs import metrics as OM
    from cloudberry_tpu.obs import trace as OT

    t0 = time.monotonic()
    compiled = None
    try:
        with OT.span("compile"):
            compiled = fn.lower(inputs).compile()
    except (AttributeError, TypeError):
        compiled = None
    if compiled is not None:
        compile_s = time.monotonic() - t0
        OM.observe_stage(log, "compile", compile_s)
        t1 = time.monotonic()
        with OT.span("launch", mode="instrumented"), \
                OT.device_annotation("launch"):
            result = compiled(inputs)
            jax.block_until_ready(result)
        exec_s = time.monotonic() - t1
        OM.observe_stage(log, "launch", exec_s)
        return result, compile_s, exec_s
    with OT.span("compile+launch"):
        result = fn(inputs)
        jax.block_until_ready(result)
    first_s = time.monotonic() - t0
    t1 = time.monotonic()
    with OT.span("launch", mode="instrumented"), \
            OT.device_annotation("launch"):
        result = fn(inputs)
        jax.block_until_ready(result)
    exec_s = time.monotonic() - t1
    OM.observe_stage(log, "compile", max(first_s - exec_s, 0.0))
    OM.observe_stage(log, "launch", exec_s)
    return result, max(first_s - exec_s, 0.0), exec_s


# ---------------------------------------------------------- plan annotation


def motion_annotations(plan: N.PlanNode, counts: dict,
                       packed: bool = True) -> dict:
    """Per-node EXPLAIN ANALYZE annotations beyond row counts:

    - PMotion: collective launches (1 fused on the packed wire, one per
      column otherwise), estimated wire bytes (rows into the motion ×
      packed row width), the capacity rung for redistributes, and —
      when the run recorded per-destination demand (``_seg_rows``,
      exec/dist_executor.py) — the observed skew ratio (max/mean rows
      per destination) with the hottest destination's row count;
    - PRuntimeFilter: observed jf_rows_in/out when the digest executor
      recorded them (``_jf_pre``/``_jf_post``, exec/dist_executor.py).
    """
    from cloudberry_tpu.obs.capacity import _wire_row_bytes

    out: dict[int, str] = {}
    for n in plan_nodes_in_order(plan):
        if isinstance(n, N.PMotion):
            fields = n.child.fields
            row_bytes = _wire_row_bytes(n)
            launches = 1 if packed else max(1, len(fields))
            rows = counts.get(id(n.child), -1)
            bits = [f"launches={launches}"]
            if rows >= 0:
                bits.append(f"wire_bytes={rows * row_bytes}")
            if n.kind == "redistribute":
                bits.append(f"rung={n.bucket_cap}")
                ratio = getattr(n, "_skew_ratio", None)
                if ratio is not None:
                    bits.append(f"skew={ratio:.2f}")
                    seg_rows = getattr(n, "_seg_rows", None)
                    if seg_rows is not None:
                        bits.append(
                            f"hot_seg_rows={int(np.max(seg_rows))}")
            out[id(n)] = "  ".join(bits)
        elif isinstance(n, N.PRuntimeFilter):
            pre = getattr(n, "_jf_pre", None)
            post = getattr(n, "_jf_post", None)
            if pre is not None and post is not None:
                out[id(n)] = f"jf_rows_in={pre}  jf_rows_out={post}"
    return out


def _tiled_lines(report: dict) -> list[str]:
    """EXPLAIN ANALYZE trailer for tiled (out-of-core) execution:
    per-tile time distribution + checkpoint/resume counters from the
    run's report (exec/tiled.py, exec/recovery.py)."""
    lines = [f"Tiled execution: {report.get('n_tiles', '?')} tiles of "
             f"{report.get('tile_rows', '?')} rows "
             f"(stream {report.get('stream_table', '?')})"]
    th = report.get("tile_time")
    if th:
        lines.append(
            f"  tile step: mean {th['mean'] * 1000:.2f} ms  "
            f"p95 {th['p95'] * 1000:.2f} ms  over {th['count']} tiles")
    # windowed dispatch line (exec/tilepipe.py) only when a window was
    # actually open — window=1 is the legacy loop and its trailer is
    # pinned by existing tests
    if report.get("tile_window", 1) > 1:
        lines.append(
            f"  tile dispatch: window {report['tile_window']}  "
            f"in-flight peak {report.get('inflight_depth', 0)}  "
            f"drain stall "
            f"{report.get('drain_stall_s', 0) * 1000:.1f} ms")
    pl = report.get("pipeline")
    if pl:
        if pl.get("enabled"):
            # stall attribution (exec/scanpipe.py): feed = the host work
            # the pipeline moved off the critical path, stall = what the
            # device still waited for, decode/read split the feed side
            bits = [f"prefetch depth {pl.get('depth', '?')}",
                    f"feed {pl.get('feed_s', 0) * 1000:.1f} ms",
                    f"stall {pl.get('stall_s', 0) * 1000:.1f} ms"]
            if "overlap_frac" in pl:
                bits.append(f"overlap {pl['overlap_frac'] * 100:.0f}%")
            if pl.get("decode_s"):
                bits.append(f"decode {pl['decode_s'] * 1000:.1f} ms")
            if pl.get("read_s"):
                bits.append(f"read {pl['read_s'] * 1000:.1f} ms")
            lines.append("  scan pipeline: " + "  ".join(bits))
        else:
            lines.append("  scan pipeline: off")
    ck = {k: report[k] for k in ("checkpoints", "resumed_from_tile",
                                 "tiles_replayed") if k in report}
    if ck:
        lines.append("  recovery: " + "  ".join(
            f"{k}={v}" for k, v in ck.items()))
    return lines


def explain_analyze_text(plan: N.PlanNode, counts: dict[int, int],
                         wall_s: float, compile_s: float,
                         annotations: dict | None = None,
                         tiled_report: dict | None = None) -> str:
    """Render the plan tree with actual row counts (EXPLAIN ANALYZE)
    plus the motion/join annotations and the tiled-execution trailer."""
    annotations = annotations or {}

    def rec(n: N.PlanNode, indent: int) -> list[str]:
        rows = counts.get(id(n))
        extra = f"  rows={rows}" if rows is not None else ""
        sh = f"  [{n.sharding}]" if n.sharding else ""
        ann = annotations.get(id(n))
        lines = [" " * indent + "-> " + n.title() + sh + extra
                 + (f"  ({ann})" if ann else "")]
        for c in n.children():
            lines += rec(c, indent + 3)
        return lines

    lines = rec(plan, 0)
    if tiled_report:
        lines += _tiled_lines(tiled_report)
    lines.append(f"Execution time: {wall_s * 1000:.2f} ms "
                 f"(compile {compile_s * 1000:.2f} ms)")
    return "\n".join(lines)


# --------------------------------------------------- the instrumented runs


def run_instrumented(plan: N.PlanNode, session, query: str = ""):
    """Execute with instrumentation; returns (ColumnBatch, QueryMetrics).

    The LEGACY side path: a private jitted program outside the statement
    pipeline (no lifecycle handle, no admission, no generic-plan form).
    Kept as the parity oracle for run_pipeline and for library callers
    that want counts without pipeline semantics.
    """
    from cloudberry_tpu.exec import executor as X

    if session.config.n_segments > 1:
        return _run_instrumented_dist(plan, session, query)

    import jax

    class InstrLowerer(InstrumentingMixin, X.Lowerer):
        def __init__(self, tables, platform=None):
            X.Lowerer.__init__(self, tables, platform)
            self.__init_instrument__()

    def run(tables):
        low = InstrLowerer(tables)
        cols, sel = low.lower(plan)
        out = {f.name: cols[f.name] for f in plan.fields}
        return out, sel, low.checks, low.node_counts

    fn = jax.jit(run)
    tables = X.prepare_plan_inputs(plan, session)
    (cols, sel, checks, counts), compile_s, wall_s = \
        _timed_compile_run(fn, tables)
    X.raise_checks(checks)
    batch = X.make_batch(plan, cols, sel)

    counts_host = {k: int(np.asarray(v)) for k, v in counts.items()}
    metrics = _metrics(plan, counts_host, query, wall_s, compile_s,
                       int(np.asarray(sel).sum()))
    _emit(session, metrics)
    return batch, metrics


def _run_instrumented_dist(plan: N.PlanNode, session, query: str):
    """Distributed: per-node counts summed over segments (post-gather nodes
    count once via segment 0 — they are replicated)."""
    import jax

    from cloudberry_tpu.exec import dist_executor as DX
    from cloudberry_tpu.exec import executor as X
    from jax.sharding import PartitionSpec as P

    # reuse the dist executor wiring but with an instrumenting lowerer
    nseg = session.config.n_segments
    mesh = DX.segment_mesh(nseg,
                           getattr(session, "_live_device_ids", None))
    inputs, in_specs = DX.prepare_dist_inputs(plan, session)

    from cloudberry_tpu.parallel.transport import (hier_topology,
                                                   make_transport)

    ic = session.config.interconnect
    # instrument the program the engine actually runs: on a two-level
    # session the real path is hierarchical, and EXPLAIN ANALYZE's
    # counts/annotations must describe THAT program, not a flat side
    # path (compile_distributed's same-entry-point contract)
    topo = hier_topology(session.config, nseg,
                         getattr(session, "_live_device_ids", None))
    tx = make_transport(ic.backend, nseg, chunks=ic.ring_chunks,
                        topo=topo)
    packed = ic.packed_wire

    class InstrDistLowerer(InstrumentingMixin, DX.DistLowerer):
        def __init__(self, tables, nseg):
            DX.DistLowerer.__init__(self, tables, nseg, tx=tx,
                                    packed=packed)
            self.__init_instrument__()

    def seg_fn(tables):
        low = InstrDistLowerer(tables, nseg)
        cols, sel = low.lower(plan)
        out = {f.name: cols[f.name][None] for f in plan.fields}
        checks = {k: jnp.asarray(v).reshape(1) for k, v in low.checks.items()}
        counts = {k: jnp.asarray(v).reshape(1)
                  for k, v in low.node_counts.items()}
        return out, sel[None], checks, counts

    out_specs = ({f.name: P(DX.SEG_AXIS) for f in plan.fields},
                 P(DX.SEG_AXIS), P(DX.SEG_AXIS), P(DX.SEG_AXIS))
    fn = jax.jit(DX._shard_map(seg_fn, mesh, (in_specs,), out_specs))
    (cols, sel, checks, counts), compile_s, wall_s = \
        _timed_compile_run(fn, inputs)
    X.raise_checks(checks)
    host_cols = {k: np.asarray(v)[0] for k, v in cols.items()}
    host_sel = np.asarray(sel)[0]
    batch = X.make_batch(plan, host_cols, host_sel)

    counts_host = _dist_counts_host(plan, counts)
    metrics = _metrics(plan, counts_host, query, wall_s, compile_s,
                       int(host_sel.sum()))
    _emit(session, metrics)
    return batch, metrics


def _dist_counts_host(plan, counts) -> dict:
    """Per-seg count arrays → one number per node: partitioned nodes sum
    across segments, replicated nodes count once (segment 0)."""
    counts_host = {}
    for n in plan_nodes_in_order(plan):
        arr = counts.get(id(n))
        if arr is None:
            continue
        per_seg = np.asarray(arr)
        if n.sharding is not None and n.sharding.is_partitioned:
            counts_host[id(n)] = int(per_seg.sum())
        else:
            counts_host[id(n)] = int(per_seg[0])  # replicated: count once
    return counts_host


# --------------------------------------- EXPLAIN ANALYZE via the pipeline


def run_pipeline(plan: N.PlanNode, session, query: str):
    """EXPLAIN ANALYZE through the STATEMENT PIPELINE (ISSUE 9): the
    same lifecycle bracket (handle + scope + StatementLog entry), the
    same dispatch seams and admission gate, the shared compile entry
    points (executor.compile_plan / dist_executor.compile_distributed
    with ``instrument=True``) and — when the plan parameterizes — the
    GENERIC-PLAN FORM: literals rewritten to ``$params`` slots exactly
    as sched/paramplan.py compiles them, so what EXPLAIN ANALYZE times
    is the program the serving path actually runs, not a private
    lowerer's variant.

    Returns (batch, QueryMetrics, annotations): per-node row counts plus
    the motion/join annotations for explain_analyze_text."""
    from cloudberry_tpu import lifecycle
    from cloudberry_tpu.exec import executor as X
    from cloudberry_tpu.utils.faultinject import fault_point

    log = session.stmt_log
    log_id = log.begin(query, session._session_id)
    deadline = None
    timeout = session.config.statement_timeout_s
    if timeout:
        deadline = time.monotonic() + timeout
    handle = lifecycle.StatementHandle(log_id, deadline=deadline)
    handle.trace = log.start_trace(log_id, query)
    if log.obs_enabled:
        from cloudberry_tpu.obs.progress import Progress

        handle.progress = Progress()
    log.attach(log_id, handle)
    compiles_before = log.counter("compiles")
    try:
        with lifecycle.statement_scope(handle):
            log.bump("dispatches")
            session._dispatch_seams(fault_point)
            batch, metrics, annotations = _pipeline_once(
                plan, session, query)
    except BaseException as e:
        log.finish(log_id, "error", error=f"{type(e).__name__}: {e}")
        raise
    metrics.compiles = log.counter("compiles") - compiles_before
    log.finish(log_id, "ok", rows=batch.num_rows(),
               compiles=metrics.compiles)
    _emit(session, metrics)
    return batch, metrics, annotations


def _generic_form(session, plan):
    """Rewrite the plan to its generic form (literals → $params slots,
    scan row counts → $nrw slots) and return the bindings — the same
    walk the plan cache performs (sched/paramplan.analyze). Plans the
    walker does not model keep their baked literals (bindings = {})."""
    from cloudberry_tpu.sched import paramplan

    if not session.config.sched.generic_plans \
            or getattr(plan, "_no_stmt_cache", False):
        return {}
    try:
        _sig, bindings, _keyed, _slots = paramplan.analyze(
            session, plan, rewrite=True)
    except paramplan.UnsupportedPlan:
        return {}
    return bindings


def _pipeline_once(plan, session, query):
    from cloudberry_tpu.exec import executor as X
    from cloudberry_tpu.exec.resource import ResourceError, check_admission

    session.last_tiled_report = None  # set again by the tiled fallback
    packed = session.config.interconnect.packed_wire
    try:
        est = check_admission(plan, session)
    except ResourceError:
        # over-budget plans take the tiled (out-of-core) path like any
        # statement would; per-node counts are not separable there, but
        # the tiled report (per-tile time histogram, checkpoint/resume
        # counters) rides the rendered output instead
        from cloudberry_tpu.exec.tiled import plan_tiled

        texe = plan_tiled(plan, session)
        if texe is None:
            raise
        from cloudberry_tpu.obs import capacity as OC

        texe.refresh_bufpool_charge()
        OC.record_tiled(session.stmt_log, texe.report)
        t0 = time.monotonic()
        with session._gate, session._admitted(
                session.config.resource.query_mem_bytes):
            batch = texe.run()
        wall_s = time.monotonic() - t0
        OC.record_tile_dispatch(session.stmt_log, texe.report)
        metrics = _metrics(plan, {}, query, wall_s, 0.0,
                           batch.num_rows())
        return batch, metrics, motion_annotations(plan, {}, packed)
    bindings = _generic_form(session, plan)
    from cloudberry_tpu.obs import capacity as OC

    OC.record_statement(session.stmt_log, plan, session, est=est)
    seg = getattr(plan, "_direct_segment", None)
    with session._gate, session._admitted(est.peak_bytes):
        if session.config.n_segments > 1 and seg is None:
            from cloudberry_tpu.exec import dist_executor as DX

            fn = DX.compile_distributed(
                plan, session,
                param_keys=sorted(bindings) if bindings else None,
                instrument=True)
            inputs, _ = DX.prepare_dist_inputs(plan, session)
            if bindings:
                inputs["$params"] = dict(bindings)
            (cols, sel, checks, stats), compile_s, wall_s = \
                _timed_compile_run(fn, inputs, log=session.stmt_log)
            DX.record_motion_stats(plan, stats, session=session)
            X.raise_checks(checks)
            DX.record_jf_counters(stats, session.stmt_log)
            from cloudberry_tpu.plan.feedback import fold_plan

            fold_plan(session, plan)
            counts_host = DX.instrument_counts(plan, stats)
            host_cols = {k: DX._local_row(v) for k, v in cols.items()}
            host_sel = DX._local_row(sel)
            batch = X.make_batch(plan, host_cols, host_sel)
            rows_out = int(host_sel.sum())
        else:
            exe = X.compile_plan(plan, session, instrument=True)
            inputs = X.prepare_inputs(exe, session, segment=seg)
            if bindings:
                inputs["$params"] = dict(bindings)
            (cols, sel, checks, counts), compile_s, wall_s = \
                _timed_compile_run(exe.fn, inputs, log=session.stmt_log)
            X.raise_checks(checks)
            batch = X.make_batch(plan, cols, sel)
            counts_host = {k: int(np.asarray(v))
                           for k, v in counts.items()}
            rows_out = int(np.asarray(sel).sum())
    metrics = _metrics(plan, counts_host, query, wall_s, compile_s,
                       rows_out)
    return batch, metrics, motion_annotations(plan, counts_host, packed)


def _metrics(plan, counts_host, query, wall_s, compile_s, rows_out):
    node_rows = [(n.title(), str(n.sharding) if n.sharding else "",
                  counts_host.get(id(n), -1))
                 for n in plan_nodes_in_order(plan)]
    return QueryMetrics(query=query, wall_s=wall_s, compile_s=compile_s,
                        rows_out=rows_out, node_rows=node_rows)


def _emit(session, metrics: QueryMetrics) -> None:
    """Deliver to every metrics hook, exception-safely: a raising hook
    is the OBSERVER's bug — it is counted (metrics_hook_errors) and must
    never abort the observed statement (the reference likewise shields
    the executor from a broken query_info_collect_hook)."""
    for hook in getattr(session, "metrics_hooks", []):
        try:
            hook(metrics)
        except Exception:
            log = getattr(session, "stmt_log", None)
            if log is not None:
                log.bump("metrics_hook_errors")
