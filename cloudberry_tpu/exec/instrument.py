"""Query instrumentation — the instrument.c / explain_gp.c analog.

The reference times every executor node per tuple (InstrStartNode/
InstrStopNode) and ships per-QE stats to the QD for distributed EXPLAIN
ANALYZE (cdbexplain_sendExecStats, explain_gp.c:384). Here the whole plan is
ONE fused XLA program, so per-node wall time is not separable — but per-node
ROW COUNTS are (cheap in-program reductions), and they answer the questions
EXPLAIN ANALYZE usually answers (selectivity, join fanout, motion width).
Whole-query compile and execute wall times complete the picture.

The ``metrics_hook`` list on a Session is the query_info_collect_hook analog
(src/include/utils/metrics_utils.h:39): every instrumented run emits a
QueryMetrics record to each registered hook.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import jax.numpy as jnp
import numpy as np

from cloudberry_tpu.plan import nodes as N


class StatementLog:
    """Per-engine statement history + active registry — the
    pg_stat_activity / log-collector analog. One instance is shared by
    every connection session of a server (like the admission gate), so
    "who is running what" spans backends. Ring-buffered: observability
    must never grow without bound."""

    def __init__(self, capacity: int = 256):
        import collections
        import itertools
        import threading

        self._recent = collections.deque(maxlen=capacity)
        self._active: dict[int, dict] = {}
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        # engine-wide scheduler/plan-cache counters (compiles, dispatches,
        # stmt_cache_hits, generic_hits, generic_builds, param_binds, ...):
        # the compile-hit / parameterization observability the serving
        # layer exposes via serve/meta.py "sched"
        self.counters = collections.Counter()

    def bump(self, name: str, n: int = 1) -> None:
        with self._lock:
            self.counters[name] += n

    def counter(self, name: str) -> int:
        with self._lock:
            return int(self.counters.get(name, 0))

    def counter_snapshot(self) -> dict:
        with self._lock:
            return {k: int(v) for k, v in sorted(self.counters.items())}

    def begin(self, sql: str, session_id: int = 0) -> int:
        sid = next(self._ids)
        with self._lock:
            self._active[sid] = {
                "id": sid, "session": session_id, "state": "running",
                "sql": sql[:500], "started": time.time()}
        return sid

    # ------------------------------------------------ statement lifecycle
    # The active registry doubles as the cancellation directory (the
    # pg_stat_activity + pg_cancel_backend pair): a session attaches its
    # StatementHandle at begin time, and any thread — the watchdog, the
    # server's `cancel <id>` verb — cancels by statement id.

    def attach(self, sid: int, handle) -> None:
        """Register a lifecycle.StatementHandle for an active statement."""
        with self._lock:
            entry = self._active.get(sid)
            if entry is not None:
                entry["handle"] = handle

    def active_handles(self) -> list[tuple[int, object]]:
        """(statement id, handle) for every active statement that has
        one — the watchdog's scan set."""
        with self._lock:
            return [(sid, e["handle"]) for sid, e in self._active.items()
                    if e.get("handle") is not None]

    def cancel(self, sid: int, reason: str = "cancelled") -> bool:
        """Cancel an active statement by id (pg_cancel_backend analog).
        Returns False when the id is not an active, cancellable
        statement (already finished, or never attached a handle)."""
        with self._lock:
            entry = self._active.get(sid)
            handle = entry.get("handle") if entry is not None else None
        if handle is None:
            return False
        if handle.token.cancel(reason,
                               f"statement {sid} cancelled by request"):
            self.bump("cancel_requests")
        self.mark_cancelling(sid)
        return True

    def mark_cancelling(self, sid: int) -> None:
        with self._lock:
            entry = self._active.get(sid)
            if entry is not None:
                entry["state"] = "cancelling"

    def set_state(self, sid: int, state: str) -> None:
        """Lifecycle state for the activity view (running/recovering).
        'cancelling' is sticky — a cancelled statement must never read
        as healthy again."""
        with self._lock:
            entry = self._active.get(sid)
            if entry is not None and entry.get("state") != "cancelling":
                entry["state"] = state

    def annotate(self, sid: int, **kv) -> None:
        """Attach observability fields to an ACTIVE statement (retry
        attempts, backoff); they ride into the history entry at
        finish()."""
        with self._lock:
            entry = self._active.get(sid)
            if entry is not None:
                entry.update(kv)

    def finish(self, sid: int, status: str, rows: int = -1,
               error: str | None = None, **extra) -> None:
        with self._lock:
            entry = self._active.pop(sid, None)
            if entry is None:
                return
            # the handle (and its token) must not outlive the statement
            # in the history ring
            entry.pop("handle", None)
            entry.pop("state", None)
            entry["wall_s"] = round(time.time() - entry["started"], 4)
            entry["status"] = status
            entry["rows"] = rows
            if error:
                entry["error"] = error[:500]
            # per-statement scheduler observability (compile count, cache
            # path, batch membership) rides the history entry
            entry.update(extra)
            self._recent.append(entry)

    def activity(self) -> list[dict]:
        """Currently-executing statements (pg_stat_activity role), with
        live lifecycle state: id, state (running/cancelling), elapsed,
        and time left to the deadline when one is set."""
        now = time.time()
        mono = time.monotonic()
        out = []
        with self._lock:
            for e in self._active.values():
                row = {k: v for k, v in e.items() if k != "handle"}
                row["elapsed_s"] = round(now - e["started"], 4)
                h = e.get("handle")
                if h is not None and h.deadline is not None:
                    row["deadline_in_s"] = round(h.deadline - mono, 4)
                out.append(row)
        return out

    def recent(self, limit: int = 50) -> list[dict]:
        """Most recent completed statements, newest first."""
        with self._lock:
            out = list(self._recent)[-limit:]
        return out[::-1]


@dataclass
class QueryMetrics:
    """One executed statement's stats (the metrics-collector payload)."""
    query: str
    wall_s: float
    compile_s: float
    rows_out: int
    # plan-order list of (node title, sharding, rows selected after the node)
    node_rows: list[tuple[str, str, int]] = field(default_factory=list)


class InstrumentingMixin:
    """Mixes into a Lowerer: records post-node selected-row counts."""

    def __init_instrument__(self):
        self.node_counts: dict[int, jnp.ndarray] = {}

    def lower(self, node):  # type: ignore[override]
        cols, sel = super().lower(node)  # type: ignore[misc]
        self.node_counts[id(node)] = jnp.sum(sel.astype(jnp.int64))
        return cols, sel


def plan_nodes_in_order(plan: N.PlanNode) -> list[N.PlanNode]:
    out = []

    def rec(n):
        out.append(n)
        for c in n.children():
            rec(c)

    rec(plan)
    return out


def explain_analyze_text(plan: N.PlanNode, counts: dict[int, int],
                         wall_s: float, compile_s: float) -> str:
    """Render the plan tree with actual row counts (EXPLAIN ANALYZE)."""

    def rec(n: N.PlanNode, indent: int) -> list[str]:
        rows = counts.get(id(n))
        extra = f"  rows={rows}" if rows is not None else ""
        sh = f"  [{n.sharding}]" if n.sharding else ""
        lines = [" " * indent + "-> " + n.title() + sh + extra]
        for c in n.children():
            lines += rec(c, indent + 3)
        return lines

    lines = rec(plan, 0)
    lines.append(f"Execution time: {wall_s * 1000:.2f} ms "
                 f"(compile {compile_s * 1000:.2f} ms)")
    return "\n".join(lines)


def run_instrumented(plan: N.PlanNode, session, query: str = ""):
    """Execute with instrumentation; returns (ColumnBatch, QueryMetrics).

    Single-segment path; distributed instrumentation sums per-segment counts.
    """
    import jax

    from cloudberry_tpu.exec import executor as X

    if session.config.n_segments > 1:
        return _run_instrumented_dist(plan, session, query)

    class InstrLowerer(InstrumentingMixin, X.Lowerer):
        def __init__(self, tables, platform=None):
            X.Lowerer.__init__(self, tables, platform)
            self.__init_instrument__()

    def run(tables):
        low = InstrLowerer(tables)
        cols, sel = low.lower(plan)
        out = {f.name: cols[f.name] for f in plan.fields}
        return out, sel, low.checks, low.node_counts

    fn = jax.jit(run)
    tables = X.prepare_plan_inputs(plan, session)
    t0 = time.time()
    result = fn(tables)
    jax.block_until_ready(result)
    compile_s = time.time() - t0
    t1 = time.time()
    cols, sel, checks, counts = fn(tables)
    jax.block_until_ready(sel)
    wall_s = time.time() - t1
    X.raise_checks(checks)
    batch = X.make_batch(plan, cols, sel)

    counts_host = {k: int(np.asarray(v)) for k, v in counts.items()}
    metrics = _metrics(plan, counts_host, query, wall_s, compile_s,
                       int(np.asarray(sel).sum()))
    _emit(session, metrics)
    return batch, metrics


def _run_instrumented_dist(plan: N.PlanNode, session, query: str):
    """Distributed: per-node counts summed over segments (post-gather nodes
    count once via segment 0 — they are replicated)."""
    import jax

    from cloudberry_tpu.exec import dist_executor as DX
    from cloudberry_tpu.exec import executor as X
    from jax.sharding import PartitionSpec as P

    # reuse the dist executor wiring but with an instrumenting lowerer
    nseg = session.config.n_segments
    mesh = DX.segment_mesh(nseg,
                           getattr(session, "_live_device_ids", None))
    inputs, in_specs = DX.prepare_dist_inputs(plan, session)

    from cloudberry_tpu.parallel.transport import make_transport

    ic = session.config.interconnect
    tx = make_transport(ic.backend, nseg, chunks=ic.ring_chunks)
    packed = ic.packed_wire

    class InstrDistLowerer(InstrumentingMixin, DX.DistLowerer):
        def __init__(self, tables, nseg):
            DX.DistLowerer.__init__(self, tables, nseg, tx=tx,
                                    packed=packed)
            self.__init_instrument__()

    def seg_fn(tables):
        low = InstrDistLowerer(tables, nseg)
        cols, sel = low.lower(plan)
        out = {f.name: cols[f.name][None] for f in plan.fields}
        checks = {k: jnp.asarray(v).reshape(1) for k, v in low.checks.items()}
        counts = {k: jnp.asarray(v).reshape(1)
                  for k, v in low.node_counts.items()}
        return out, sel[None], checks, counts

    out_specs = ({f.name: P(DX.SEG_AXIS) for f in plan.fields},
                 P(DX.SEG_AXIS), P(DX.SEG_AXIS), P(DX.SEG_AXIS))
    fn = jax.jit(DX._shard_map(seg_fn, mesh, (in_specs,), out_specs))
    t0 = time.time()
    result = fn(inputs)
    jax.block_until_ready(result)
    compile_s = time.time() - t0
    t1 = time.time()
    cols, sel, checks, counts = fn(inputs)
    jax.block_until_ready(sel)
    wall_s = time.time() - t1
    X.raise_checks(checks)
    host_cols = {k: np.asarray(v)[0] for k, v in cols.items()}
    host_sel = np.asarray(sel)[0]
    batch = X.make_batch(plan, host_cols, host_sel)

    nodes = plan_nodes_in_order(plan)
    counts_host = {}
    for n in nodes:
        arr = counts.get(id(n))
        if arr is None:
            continue
        per_seg = np.asarray(arr)
        if n.sharding is not None and n.sharding.is_partitioned:
            counts_host[id(n)] = int(per_seg.sum())
        else:
            counts_host[id(n)] = int(per_seg[0])  # replicated: count once
    metrics = _metrics(plan, counts_host, query, wall_s, compile_s,
                       int(host_sel.sum()))
    _emit(session, metrics)
    return batch, metrics


def _metrics(plan, counts_host, query, wall_s, compile_s, rows_out):
    node_rows = [(n.title(), str(n.sharding) if n.sharding else "",
                  counts_host.get(id(n), -1))
                 for n in plan_nodes_in_order(plan)]
    return QueryMetrics(query=query, wall_s=wall_s, compile_s=compile_s,
                        rows_out=rows_out, node_rows=node_rows)


def _emit(session, metrics: QueryMetrics) -> None:
    for hook in getattr(session, "metrics_hooks", []):
        hook(metrics)
