"""Mid-statement fault recovery — tile-granular checkpoints + degraded
resume.

The reference survives segment failure with FTS + mirror promotion
(SURVEY §7.1): a statement's in-flight state lives on mirrored disks, so
a dead segment's work is not lost. Mesh slots have no mirrors — segments
are stateless over immutable storage — so the analog is CHECKPOINTED
RE-EXECUTION: the tiled executors (exec/tiled.py, exec/tiled_dist.py)
already cross a host boundary after every tile, and the state carried
between tiles is small by construction (agg partials bounded by the
accumulator capacity, top-N heaps bounded by the LIMIT, sort-merge runs
already host-resident). Every K-th tile that carried state is
snapshotted to a host-side, statement-scoped checkpoint; when a device
loss kills the statement mid-stream, the session's retry
(parallel/health.py run_with_retry) probes the mesh, optionally degrades
it to the survivors, re-plans, and the NEW executable resumes from the
checkpoint — replaying at most K tiles instead of the whole stream.

Resume must be bit-identical to an uninterrupted run. The pieces that
make it so:

- the tile stream is deterministic: warm tables stream host RAM in row
  (or shard-layout) order, so "consumed rows" fully describes progress.
  Single-node consumption is a row-count prefix; distributed consumption
  is a boolean mask over the table's global row indices (reconstructed
  from the deterministic jump-hash shard layout, so nothing extra is
  stored per tile);
- partial merges are associative (the two-stage agg discipline,
  plan/distribute.py:_split_aggs), so the remaining rows may be re-tiled
  — and re-SHARDED, when the mesh CHANGED between attempts (smaller
  after a device loss, larger or smaller after an online topology
  cutover landed mid-statement, parallel/topology.py) — without
  changing the answer; cross-epoch resumes count as
  ``topo_resharded_resumes``;
- on a degraded resume the remaining rows re-shard by the SAME jump hash
  the placement layer uses at the new segment count, so every plan
  invariant (colocation, direct dispatch) holds on the survivor mesh;
- checkpointed partials re-place onto the survivors by mode: partials
  that flow through a merge Motion (two-stage agg) or a global gather
  (top-N) are placement-free and round-robin; sort/window run stores are
  already pooled host-side; colocated one-stage agg partials would need
  the group-key hash to re-place, so a CHANGED-nseg resume declines
  there (fresh re-execution — still correct, just not incremental) and
  the decline is counted.

Deliberately NOT checkpointed: prelude build results (recomputed — they
are deterministic functions of resident tables), the finalize program,
the window chunk pass (phase two re-runs from the completed stream
snapshot), non-tiled one-shot statements (their whole state is one
launch), and writes (DML is never retried, so a checkpoint could only
mask a replay hazard).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from cloudberry_tpu.utils.faultinject import fault_point


class TileReplan(Exception):
    """Mid-statement adaptive replan request (NOT a failure).

    Raised by the tiled-dist skew sentinel (exec/tiled.py SkewSentinel)
    after it has (a) folded the cumulative per-destination motion rows
    into the feedback store as a partial sketch and (b) durably
    checkpointed the carried state via RecoveryCtx.force_snapshot. The
    session's statement retry treats it like a topology race: evict the
    cached statement, re-plan — the memo now sees the fresh sketch —
    and the new executable resumes from the checkpoint (plan_signature
    deliberately excludes nseg/tile size/motion choices, so a
    differently-shaped plan still accepts it).

    Deliberately NOT an executor ExecError subclass: the adaptive
    grow/halve loop (exec/tiled.py _run_adaptive) absorbs ExecError to
    retry at a new capacity, and an adaptation request must propagate
    past it to the session."""

    def __init__(self, msg: str, tiles_done: int = 0, ratio: float = 0.0):
        super().__init__(msg)
        self.tiles_done = tiles_done
        self.ratio = ratio


# The declared re-placement rule per checkpointed mode — HOW a
# snapshot's carried state re-places onto a changed (degraded) mesh.
# Keys must equal exec/tiled.py CHECKPOINT_MODES (the plan verifier's
# recovery-mode-unreplaceable rule and graftlint's planprops pass hold
# the two tables together both ways); _accept() consults this registry
# — both membership AND the placement_free flag — so an undeclared
# mode can never resume from a checkpoint, and a placement-free mode
# is data here, not a literal buried in the acceptance logic.
REPLACEABLE = {
    "agg": {"placement_free": False,
            "rule": "round-robin partials ahead of the merge motion "
                    "(colocated one-stage at changed nseg DECLINES)"},
    "topn": {"placement_free": False,
             "rule": "host-side global top-m via sort_key_u64, "
                     "then round-robin"},
    "sort": {"placement_free": True,
             "rule": "run stores are pooled already"},
    "window": {"placement_free": True,
               "rule": "run stores are pooled already"},
}


@dataclass
class TileCheckpoint:
    """One statement's resumable state at a tile boundary."""

    signature: tuple          # plan identity the resume must match
    mode: str                 # agg | topn | sort | window
    nseg: int                 # mesh the snapshot was produced on
    tile_rows: int            # tile size at snapshot time (telemetry)
    tiles_done: int           # cumulative tiles consumed across attempts
    consumed: object          # int row prefix (single) | bool mask (dist)
    payload: dict             # mode-specific host state (numpy only)
    g_cap: int = 0            # accumulator capacity at snapshot
    created: float = field(default_factory=time.monotonic)


class RecoveryStore:
    """Host-side, statement-scoped checkpoint store (one per session
    tree; server connection sessions share the owning session's).
    Bounded LRU two ways: by statement count AND by pinned host BYTES
    (``config.recovery.max_bytes``) — a long statement with many big
    checkpoints must not pin unbounded host memory. Evicting a victim
    only costs it a full replay on its next device loss (recovery is an
    optimization by contract); evictions count as ``ckpt_evictions``
    and the live pin total feeds the ``mem_recovery_pins_bytes`` gauge
    (obs/capacity.py). Checkpoints also die with their statement
    (session.sql discards on completion)."""

    def __init__(self, max_statements: int = 8, max_bytes: int = 0,
                 log=None):
        self._lock = threading.Lock()
        self._ckpts: dict[int, TileCheckpoint] = {}
        # tiles the CURRENT attempt of a statement has completed — the
        # resume reads it to compute how many tiles the failed attempt
        # lost since its last snapshot (tiles_replayed)
        self._progress: dict[int, int] = {}
        self.max_statements = max_statements
        self.max_bytes = int(max_bytes)
        self._bytes = 0
        self._log = log

    @staticmethod
    def _ckpt_nbytes(ckpt: TileCheckpoint) -> int:
        from cloudberry_tpu.obs.capacity import nbytes_of

        return nbytes_of(ckpt.payload) + nbytes_of(ckpt.consumed)

    def save(self, sid: int, ckpt: TileCheckpoint) -> None:
        nb = self._ckpt_nbytes(ckpt)
        evicted = 0
        refused = 0
        if self.max_bytes > 0 and nb > self.max_bytes:
            # one snapshot alone over the budget: refuse the pin
            # outright — evicting innocents would not make it fit, and
            # the statement's own EARLIER (within-budget) checkpoint
            # stays pinned so a loss still resumes from there
            refused = 1
        else:
            with self._lock:
                old = self._ckpts.pop(sid, None)
                if old is not None:
                    self._bytes -= getattr(old, "_nbytes", 0)
                ckpt._nbytes = nb
                while self._ckpts and (
                        len(self._ckpts) >= self.max_statements
                        or (self.max_bytes > 0
                            and self._bytes + nb > self.max_bytes)):
                    victim = self._ckpts.pop(next(iter(self._ckpts)))
                    self._bytes -= getattr(victim, "_nbytes", 0)
                    evicted += 1
                self._ckpts[sid] = ckpt
                self._bytes += nb
        # counter bumps outside the store lock: the store lock stays a
        # near-leaf that never calls out while held
        if self._log is not None:
            if evicted:
                self._log.bump("ckpt_evictions", evicted)
            if refused:
                self._log.bump("ckpt_oversize_refused", refused)

    def pinned_bytes(self) -> int:
        with self._lock:
            return int(self._bytes)

    def pinned_count(self) -> int:
        with self._lock:
            return len(self._ckpts)

    def load(self, sid: int, signature: tuple) -> Optional[TileCheckpoint]:
        with self._lock:
            ckpt = self._ckpts.get(sid)
            if ckpt is not None:
                # refresh recency: a statement waiting out its retry
                # backoff must not lose its checkpoint to saves from
                # max_statements other statements in that window
                self._ckpts.pop(sid)
                self._ckpts[sid] = ckpt
        if ckpt is None or ckpt.signature != signature:
            return None
        return ckpt

    def note_progress(self, sid: int, tiles_total: int) -> None:
        with self._lock:
            self._progress[sid] = tiles_total
            while len(self._progress) > 4 * self.max_statements:
                self._progress.pop(next(iter(self._progress)))

    def progress(self, sid: int) -> int:
        with self._lock:
            return self._progress.get(sid, 0)

    def discard(self, sid: int) -> None:
        with self._lock:
            ckpt = self._ckpts.pop(sid, None)
            if ckpt is not None:
                self._bytes -= getattr(ckpt, "_nbytes", 0)
            self._progress.pop(sid, None)


# ------------------------------------------------------------- signature


def plan_signature(exe) -> tuple:
    """Identity a checkpoint must match to seed a resumed executable:
    same stream (table + data version + pruned part list), same mode,
    same carried-state schema, same merge semantics. Deliberately NOT
    nseg or tile_rows — those may legitimately change across a degraded
    re-plan."""
    shape = exe.shape
    t = exe.session.catalog.tables.get(shape.stream.table_name)
    parts = getattr(shape.stream, "_store_parts", None)
    sig = (shape.stream.table_name,
           getattr(t, "_version", 0),
           shape.mode,
           tuple((f.name, str(np.dtype(f.type.np_dtype)))
                 for f in shape.partial_plan.fields),
           tuple(parts) if parts is not None else None)
    if shape.mode == "agg":
        groups = getattr(shape, "group_names", None)
        if groups is None:
            groups = [n for n, _ in shape.agg.group_keys]
        sig += (tuple(groups),
                tuple((s.func, s.out_name) for s in shape.merge_specs))
    else:
        sig += (repr(shape.sortnode.keys) if shape.sortnode is not None
                else None,)
    return sig


def _statement_id() -> Optional[int]:
    from cloudberry_tpu.lifecycle import current_handle

    h = current_handle()
    sid = getattr(h, "statement_id", None)
    return sid if isinstance(sid, int) else None


# --------------------------------------------------------------- payloads


def acc_payload(acc) -> dict:
    """Host snapshot of an accumulator (cols dict, sel) — forces a
    device→host copy, so the state survives the device that made it."""
    cols, sel = acc
    return {"cols": {n: np.asarray(a) for n, a in cols.items()},
            "sel": np.asarray(sel)}


def runs_payload(runs: dict, key_runs: list) -> dict:
    """Host snapshot of a sort/window run store. The per-tile arrays are
    append-only, so shallow list copies pin the state without copying a
    byte of row data."""
    return {"runs": {n: list(arrs) for n, arrs in runs.items()},
            "key_runs": [list(arrs) for arrs in key_runs]}


# ----------------------------------------------------- shard-layout math
# The deterministic shard layout (session.sharded_table): stable argsort
# of the jump-hash assignment, shard s owning sorted positions
# [starts[s], starts[s]+counts[s]). Reconstructable from the table alone,
# so checkpoints never store per-tile row identities.


def _shard_layout(table, nseg: int):
    assign = table.shard_assignment(nseg)
    if assign is None:  # replicated tables never stream (walk guarantees)
        raise ValueError("replicated table cannot be a tile stream")
    order = np.argsort(assign, kind="stable")
    counts = np.bincount(assign, minlength=nseg).astype(np.int64)
    starts = np.concatenate([[0], np.cumsum(counts)])
    return order, counts, starts


def fresh_consumed_mask(table, nseg: int, tile_rows: int,
                        tiles: int, layout=None) -> np.ndarray:
    """Global consumed-row mask after ``tiles`` lock-step tiles of the
    standard distributed feed (_dist_tile_feed): each shard consumed its
    first min(tiles·tile_rows, count) layout rows. ``layout`` reuses a
    prior _shard_layout — the layout is invariant for a run (table
    version and nseg are fixed), and recomputing it hashes + argsorts
    the whole table."""
    order, counts, starts = (layout if layout is not None
                             else _shard_layout(table, nseg))
    mask = np.zeros(table.num_rows, dtype=np.bool_)
    for s in range(nseg):
        c = int(min(tiles * tile_rows, counts[s]))
        mask[order[starts[s]:starts[s] + c]] = True
    return mask


class _ResumedDistFeed:
    """Tile feed over the REMAINING rows of a distributed stream,
    re-sharded by the placement hash at the (possibly degraded) current
    segment count. With an unchanged nseg this reproduces exactly the
    suffix of the original feed; with a smaller nseg it is the degraded
    re-plan's stream — every plan invariant re-derives because the
    sharding rule is the same jump hash placement uses."""

    def __init__(self, scan, session, tile_rows: int,
                 consumed_mask: np.ndarray, nseg: int):
        t = session.catalog.table(scan.table_name)
        t.ensure_loaded()
        self.base_mask = consumed_mask
        self.tile_rows = tile_rows
        self.nseg = nseg
        remaining = np.flatnonzero(~consumed_mask)
        assign = t.shard_assignment(nseg)
        a = assign[remaining]
        order = np.argsort(a, kind="stable")
        self.rsorted = remaining[order]
        self.counts = np.bincount(a, minlength=nseg).astype(np.int64)
        self.starts = np.concatenate([[0], np.cumsum(self.counts)])
        cols: dict[str, np.ndarray] = {}
        for phys in scan.column_map:
            cols[phys] = np.asarray(t.data[phys])
        for phys in scan.mask_map:
            vm = t.validity.get(phys)
            cols[f"$nn:{phys}"] = (np.asarray(vm, dtype=np.bool_)
                                   if vm is not None
                                   else np.ones(t.num_rows, dtype=np.bool_))
        self._cols = cols

    def __iter__(self):
        nseg, tile_rows = self.nseg, self.tile_rows
        max_rows = int(self.counts.max()) if len(self.counts) else 0
        lanes = np.arange(tile_rows)
        for off in range(0, max_rows, tile_rows):
            idx = np.zeros((nseg, tile_rows), dtype=np.int64)
            tile_ns = np.clip(self.counts - off, 0, tile_rows)
            for s in range(nseg):
                n_s = int(tile_ns[s])
                lo = int(self.starts[s]) + off
                idx[s, :n_s] = self.rsorted[lo:lo + n_s]
            pad = lanes[None, :] >= tile_ns[:, None]
            tile = {}
            for name, arr in self._cols.items():
                g = arr[idx]
                g[pad] = 0  # padded lanes mirror the zero-fill feed
                tile[name] = np.ascontiguousarray(g)
            yield tile, tile_ns

    def consumed_after(self, tiles_local: int) -> np.ndarray:
        mask = self.base_mask.copy()
        for s in range(self.nseg):
            c = int(min(tiles_local * self.tile_rows, self.counts[s]))
            lo = int(self.starts[s])
            mask[self.rsorted[lo:lo + c]] = True
        return mask


# ----------------------------------------------------------- restore math


def _pad_acc(payload: dict, cap: int):
    """Grow a snapshotted accumulator to the current capacity (adaptive
    g_cap growth between attempts); unchanged capacity restores
    verbatim. Never shrinks — callers decline that resume instead."""
    cols, sel = payload["cols"], payload["sel"]
    old = sel.shape[-1]
    if old == cap:
        return dict(cols), sel
    extra = cap - old
    out = {}
    for n, a in cols.items():
        pad_shape = a.shape[:-1] + (extra,)
        out[n] = np.concatenate([a, np.zeros(pad_shape, dtype=a.dtype)],
                                axis=-1)
    sel = np.concatenate(
        [sel, np.zeros(sel.shape[:-1] + (extra,), dtype=np.bool_)],
        axis=-1)
    return out, sel


def _pooled_rows(payload: dict):
    """Selected accumulator rows pooled across every segment block."""
    sel = payload["sel"]
    flat_sel = sel.reshape(-1)
    return ({n: a.reshape(-1, *a.shape[2:])[flat_sel]
             for n, a in payload["cols"].items()},
            int(flat_sel.sum()))


def _round_robin_acc(rows: dict, n_rows: int, fields, nseg: int,
                     cap: int):
    """Place pooled partial rows round-robin onto ``nseg`` accumulator
    blocks of ``cap`` rows — legal whenever a Motion (or the topn global
    gather) re-routes partials by value at finalize time."""
    cols = {f.name: np.zeros((nseg, cap), dtype=f.type.np_dtype)
            for f in fields}
    sel = np.zeros((nseg, cap), dtype=np.bool_)
    if n_rows:
        segs = np.arange(n_rows) % nseg
        slots = np.arange(n_rows) // nseg
        for f in fields:
            cols[f.name][segs, slots] = rows[f.name]
        sel[segs, slots] = True
    return cols, sel


def _host_topn(rows: dict, n_rows: int, sort_keys, m: int):
    """The best ``m`` pooled top-N rows by the device's own key
    normalization (kernels.sort_key_u64 evaluated host-side — the same
    function, so host and device orders cannot disagree). Only
    ColumnRef keys qualify; callers decline otherwise."""
    import jax.numpy as jnp

    from cloudberry_tpu.exec import kernels as K
    from cloudberry_tpu.plan import expr as ex

    if n_rows <= m:
        return rows, n_rows
    karr = []
    for e, asc in sort_keys:
        if not isinstance(e, ex.ColumnRef):
            return None  # caller declines
        u = np.asarray(K.sort_key_u64(jnp.asarray(rows[e.name])))
        karr.append(u if asc else ~u)
    order = np.lexsort(tuple(reversed(karr)))[:m]
    return {n: a[order] for n, a in rows.items()}, m


# ------------------------------------------------------------ the context


class RecoveryCtx:
    """Per-_run_once recovery state: loads a matching checkpoint (maybe
    re-sharding it onto a degraded mesh), tracks progress, and snapshots
    the carried state every K tiles. A declined or absent checkpoint
    degrades to a fresh run — recovery is an optimization, never a
    correctness dependency."""

    def __init__(self, exe, dist: bool):
        self.exe = exe
        self.dist = dist
        self.session = exe.session
        self.cfg = self.session.config.recovery
        self.store = self.session._recovery
        self.log = self.session.stmt_log
        self.sid = _statement_id()
        self.sig = plan_signature(exe)
        self.ckpt: Optional[TileCheckpoint] = None
        self.resumed = False
        self.tiles_base = 0
        self.skip_rows = 0
        self.replayed = 0
        self._feed: Optional[_ResumedDistFeed] = None
        self._layout = None  # cached fresh-path _shard_layout
        self._restored_acc = None
        self._last_snapshot = 0
        self._ckpt_broken = False
        if self.sid is None:
            return
        prior = self.store.progress(self.sid)
        ckpt = self.store.load(self.sid, self.sig)
        if ckpt is not None and fault_point("ckpt_resume"):
            ckpt = None  # chaos arm: force a fresh run
        if ckpt is not None and not self._accept(ckpt):
            self.log.bump("tile_resume_declined")
            ckpt = None
        if ckpt is not None:
            self.ckpt = ckpt
            self.resumed = True
            self.tiles_base = ckpt.tiles_done
            self._last_snapshot = ckpt.tiles_done
            if not dist:
                self.skip_rows = int(ckpt.consumed)
            self.log.bump("tile_resumes")
            if dist and ckpt.nseg != exe.nseg:
                # the checkpoint crossed a topology change (failover
                # shrink or an online expand cutover landing mid-
                # statement): the remaining rows re-shard at the new
                # segment count — counted so a flip's mid-statement
                # cost is visible next to the epoch counters
                self.log.bump("topo_resharded_resumes")
        # tiles the failed (or overflowed) attempt completed past the
        # checkpoint are the replay cost of this attempt — ≤ K when a
        # snapshot existed, the whole prior progress when none did
        self.replayed = max(0, prior - self.tiles_base)
        if self.replayed:
            self.log.bump("tiles_replayed", self.replayed)
        self.store.note_progress(self.sid, self.tiles_base)

    # ------------------------------------------------------- acceptance

    def _accept(self, ckpt: TileCheckpoint) -> bool:
        exe, shape = self.exe, self.exe.shape
        mode = shape.mode
        spec = REPLACEABLE.get(mode)
        if spec is None:
            return False  # no declared re-placement rule: never resume
        if spec["placement_free"]:
            return True  # host run stores need no re-placement
        cur_cap = self._current_cap()
        if self.dist:
            nseg = exe.nseg
            if ckpt.nseg == nseg:
                return ckpt.g_cap <= cur_cap
            # changed mesh: only placement-free partials can re-shard
            if mode == "agg":
                if shape.merge_motion is None or not shape.group_names:
                    # colocated one-stage (group-key hash would have to
                    # re-place rows) and global single-row accumulators
                    # (capacity 1 cannot absorb pooled partials) decline
                    return False
                return True
            if mode == "topn":
                from cloudberry_tpu.plan import expr as ex

                return all(isinstance(e, ex.ColumnRef)
                           for e, _ in shape.sortnode.keys)
            return False
        return ckpt.g_cap <= cur_cap

    def _current_cap(self) -> int:
        shape = self.exe.shape
        if shape.mode == "agg":
            groups = getattr(shape, "group_names", None)
            if groups is None:
                groups = [n for n, _ in shape.agg.group_keys]
            return shape.g_cap if groups else 1
        return shape.g_cap

    # --------------------------------------------------------- restoring

    def _decline(self) -> None:
        """Fall back to a fresh run mid-prepare: recovery is an
        optimization — any restore failure must cost only the replay."""
        self.resumed = False
        self.ckpt = None
        self.tiles_base = 0
        self.skip_rows = 0
        self._feed = None
        self._restored_acc = None
        self._last_snapshot = 0
        self.log.bump("tile_resume_declined")
        if self.sid is not None:
            self.store.note_progress(self.sid, 0)

    def prepare_dist(self) -> None:
        """All fallible distributed-resume work in one guarded place,
        BEFORE the executable re-tiles and compiles: build the
        remaining-row feed, and on a changed mesh re-shard the pooled
        partials (which may need a larger per-segment accumulator than
        the fresh plan chose)."""
        if not (self.resumed and self.dist):
            return
        try:
            exe, shape, ckpt = self.exe, self.exe.shape, self.ckpt
            nseg = exe.nseg
            self._feed = _ResumedDistFeed(
                shape.stream, self.session, exe.tile_rows, ckpt.consumed,
                nseg)
            if ckpt.nseg == nseg or shape.mode not in ("agg", "topn"):
                return
            rows, n_rows = _pooled_rows(ckpt.payload)
            if shape.mode == "topn":
                hit = _host_topn(rows, n_rows, shape.sortnode.keys,
                                 shape.g_cap)
                if hit is None:  # non-ColumnRef key slipped acceptance
                    raise ValueError("topn keys not host-sortable")
                rows, n_rows = hit
            need = -(-n_rows // nseg) if n_rows else 0  # ceil
            if shape.mode == "agg" and need > shape.g_cap:
                shape.g_cap = need
                exe._compiled = None
                exe._refresh_report()
            cap = self._current_cap()
            self._restored_acc = _round_robin_acc(
                rows, n_rows, shape.partial_plan.fields, nseg, cap)
        except Exception:  # noqa: BLE001 — degrade to a fresh run
            self._decline()

    def restore_acc(self, acc):
        """Initial accumulator from the checkpoint (agg/topn modes).
        Read ``skip_rows``/``tiles_base`` AFTER this call — a failed
        restore declines the resume and returns the fresh ``acc``."""
        if not self.resumed:
            return acc
        if self._restored_acc is not None:  # degraded re-shard
            return self._restored_acc
        try:
            return _pad_acc(self.ckpt.payload, self._current_cap())
        except Exception:  # noqa: BLE001 — degrade to a fresh run
            self._decline()
            return acc

    def restore_runs(self, runs, key_runs):
        """Initial (runs, key_runs) from the checkpoint (sort/window
        modes); the fresh stores pass through on a declined resume."""
        if not self.resumed:
            return runs, key_runs
        try:
            p = self.ckpt.payload
            return ({n: list(arrs) for n, arrs in p["runs"].items()},
                    [list(arrs) for arrs in p["key_runs"]])
        except Exception:  # noqa: BLE001 — degrade to a fresh run
            self._decline()
            return runs, key_runs

    def feed(self):
        """The distributed remaining-row feed for a resumed run; None
        means the standard fresh feed applies."""
        return self._feed if self.resumed else None

    # ------------------------------------------------------ tick/snapshot

    def snapshot_due(self, tiles_local: int) -> bool:
        """True when ``tick`` at this tile ordinal would snapshot. The
        windowed dispatcher (exec/tilepipe.py) asks at SUBMIT time so it
        can stage the accumulator's device copy + async D2H before the
        next step donates the buffer; the save itself still happens at
        drain time, once the tile has verified clean. Drains run in
        stream order and ``_last_snapshot`` only advances at drains, so
        submit-time "due" is a superset of drain-time "due" — a stale
        capture is wasted staging, never a missed snapshot."""
        if (self.sid is None or not self.cfg.enabled
                or self.cfg.checkpoint_every <= 0 or self._ckpt_broken):
            return False
        total = self.tiles_base + tiles_local
        return total - self._last_snapshot >= self.cfg.checkpoint_every

    def tick(self, tiles_local: int, payload_fn) -> None:
        """After every completed tile: note progress; snapshot at the
        K-tile boundary. ``payload_fn`` builds the host payload lazily —
        it only runs when a snapshot is actually due."""
        if self.sid is None:
            return
        total = self.tiles_base + tiles_local
        self.store.note_progress(self.sid, total)
        if not self.cfg.enabled or self.cfg.checkpoint_every <= 0:
            return
        if self._ckpt_broken:
            return
        if total - self._last_snapshot < self.cfg.checkpoint_every:
            return
        if fault_point("ckpt_save"):
            return  # chaos arm: suppress checkpointing
        try:
            self._snapshot(total, tiles_local, payload_fn())
        except Exception:  # noqa: BLE001
            # checkpointing is an optimization, never a correctness
            # dependency: a failed snapshot (e.g. the streamed table was
            # dropped by a concurrent session) must not kill an
            # otherwise healthy statement — stop checkpointing and let
            # the run finish (a later device loss just replays more)
            self._ckpt_broken = True
            self.log.bump("tile_ckpt_failed")

    def force_snapshot(self, tiles_local: int, payload_fn) -> bool:
        """Snapshot NOW, ignoring the K-tile cadence — the mid-statement
        adaptive replan (exec/tiled_dist.py) checkpoints the carried
        state at the alarm tile so the replanned executable resumes from
        exactly here instead of re-streaming. True when the checkpoint
        was durably saved; an adaptation must not proceed on a failed
        save (the replanned run would replay consumed tiles)."""
        if self.sid is None or not self.cfg.enabled or self._ckpt_broken:
            return False
        total = self.tiles_base + tiles_local
        if self._last_snapshot == total:
            return True      # the cadence tick already saved this tile
        try:
            self._snapshot(total, tiles_local, payload_fn())
            return True
        except Exception:  # noqa: BLE001 — same degrade rule as tick()
            self._ckpt_broken = True
            self.log.bump("tile_ckpt_failed")
            return False

    def _snapshot(self, tiles_total: int, tiles_local: int,
                  payload: dict) -> None:
        exe = self.exe
        if self.dist:
            nseg = exe.nseg
            if self._feed is not None:
                consumed = self._feed.consumed_after(tiles_local)
            else:
                t = self.session.catalog.table(
                    exe.shape.stream.table_name)
                if self._layout is None:
                    self._layout = _shard_layout(t, nseg)
                consumed = fresh_consumed_mask(
                    t, nseg, exe.tile_rows, tiles_local,
                    layout=self._layout)
        else:
            consumed = self.skip_rows + tiles_local * exe.tile_rows
            nseg = 1
        self.store.save(self.sid, TileCheckpoint(
            signature=self.sig, mode=exe.shape.mode, nseg=nseg,
            tile_rows=exe.tile_rows, tiles_done=tiles_total,
            consumed=consumed, payload=payload,
            g_cap=self._current_cap()))
        self._last_snapshot = tiles_total
        self.log.bump("tile_checkpoints")

    def stamp_report(self, report: dict) -> None:
        report["resumed_from_tile"] = self.tiles_base
        report["tiles_replayed"] = self.replayed


def begin(exe, dist: bool) -> Optional[RecoveryCtx]:
    """Recovery context for one executable run, or None when the
    subsystem is off / there is no statement scope to key on. Never
    raises: a broken checkpoint must degrade to a fresh run, not fail
    the statement."""
    session = exe.session
    cfg = getattr(session.config, "recovery", None)
    if cfg is None or not cfg.enabled \
            or getattr(session, "_recovery", None) is None:
        return None
    try:
        return RecoveryCtx(exe, dist)
    except Exception:  # noqa: BLE001 — resume is best-effort by contract
        try:
            session.stmt_log.bump("tile_resume_declined")
        except Exception:  # noqa: BLE001
            pass
        return None
