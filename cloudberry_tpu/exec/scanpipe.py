"""Asynchronous tiled-scan pipeline — prefetch + parallel decode +
device double-buffering.

The tiled executors (exec/tiled.py, exec/tiled_dist.py) stream a table
as fixed-shape tiles; before this module the feed was fully synchronous:
read a micro-partition, decode every column, concatenate, pad, feed —
all on the statement thread, device idle the whole time. JAX's async
dispatch already overlaps *compute* for free; the win left on the table
is moving the HOST work (IO, zstd/zlib/dvarint decode, padding, the
host→device copy) off the critical path — the same shape as a training
input pipeline, and Theseus's data-movement thesis (PAPERS.md) applied
to the scan side instead of the wire.

Pieces:

- ``ScanPipeline``: a bounded prefetch queue (``config.scan_pipeline.
  prefetch_tiles``) fed by ONE background reader thread that runs the
  tile-producing generator. The reader installs the statement's
  lifecycle scope (lifecycle.statement_scope), so cooperative
  cancellation/deadline checks fire inside the worker exactly like on
  the statement thread, and the ``scan_prefetch`` fault seam arms there.
  Producer errors buffer behind already-staged tiles and re-raise on
  the consumer — tile order and content are EXACTLY the synchronous
  feed's, so pipeline on/off is bit-identical by construction.
- double-buffered ``jax.device_put``: when the consumer pops tile k it
  eagerly stages tile k+1 (if already queued) onto the device, so the
  transfer of k+1 overlaps the dispatch of k (single-node path; the
  distributed path stages host-side only — shard_map owns placement).
- a shared decode pool (``decode_workers`` daemon threads) for
  column-parallel micro-partition decode: the codecs release the GIL,
  each worker keeps its own decompression context
  (storage/micropartition.py), and per-column decode seconds feed the
  ``decode_seconds`` histogram so EXPLAIN ANALYZE's tiled trailer can
  attribute stall time to IO vs decode vs compute.

Lifecycle/recovery composition: each ``_run_once`` builds a fresh
pipeline and the tile loops close it in a ``finally`` (close_feed), so
adaptive grow-and-retry restarts drain and reseed the queue, a
checkpoint resume replays from the stream offset (prefetched-but-
unconsumed tiles are simply dropped — progress is consumed tiles, never
staged ones), and a cancelled statement leaves no orphan reader thread
(join with timeout, pinned by tests). Queue memory is charged into the
statement's capacity estimate (queue_charge_bytes → est_pipeline_bytes
→ obs/capacity.record_tiled).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

from cloudberry_tpu.utils.faultinject import fault_point

_EOS = object()     # producer exhausted
_EMPTY = object()   # nothing queued right now (non-blocking take)


class ScanStats:
    """Per-feed host-side accounting, written by whichever thread runs
    the producing generator (the reader thread when pipelined, the
    statement thread otherwise) and read only after the feed closed —
    no lock by design; the join in close() is the ordering (a timed-out
    join marks the feed leaked and the snapshot is skipped — reading
    would race the still-running writer)."""

    __slots__ = ("decode_s", "read_s", "parts_read", "parts_skipped",
                 "parts_resident", "bytes_decoded", "copy_rows",
                 "view_rows")

    def __init__(self):
        self.decode_s = 0.0      # pure column-decode seconds
        self.read_s = 0.0        # partition read wall (IO + decode)
        self.parts_read = 0
        self.parts_skipped = 0   # resume fast-path: skipped whole files
        self.parts_resident = 0  # served from the HBM buffer pool
        self.bytes_decoded = 0
        self.copy_rows = 0       # rows copied on emit (each at most once)
        self.view_rows = 0       # chunk-exact zero-copy emits

    def snapshot(self) -> dict:
        return {
            "decode_s": round(self.decode_s, 6),
            "read_s": round(self.read_s, 6),
            "parts_read": self.parts_read,
            "parts_skipped": self.parts_skipped,
            "parts_resident": self.parts_resident,
            "bytes_decoded": self.bytes_decoded,
        }


class ScanPipeline:
    """Bounded prefetch queue over a tile generator. Iterating yields
    exactly the generator's items in order; ``close()`` stops the
    reader and joins it. All cross-thread state lives under ``_cond``
    (a leaf: nothing is called while it is held); ``_staged`` is a
    consumer-thread-only slot and never crosses threads."""

    def __init__(self, gen, depth: int = 2, device_stage: bool = False,
                 stats: Optional[ScanStats] = None):
        from cloudberry_tpu.lifecycle import current_handle

        self._gen = gen
        self.depth = max(int(depth), 1)
        self._device_stage = bool(device_stage)
        self.scan_stats = stats
        self._handle = current_handle()
        self._cond = threading.Condition()
        self._buf: deque = deque()
        self._open = True        # consumer still wants tiles
        self._done = False       # producer finished (or died)
        self._err: Optional[BaseException] = None
        # telemetry (mutations under _cond)
        self.tiles = 0           # tiles staged by the reader
        self.feed_s = 0.0        # producer busy seconds (read+decode+pad)
        self.stall_s = 0.0       # consumer blocked-on-empty-queue seconds
        self.max_depth = 0       # queue high-water mark
        self._staged = None      # consumer-only: device-put next tile
        self._reader_leaked = False  # join timed out in close()
        self._thread = threading.Thread(target=self._reader, daemon=True,
                                        name="cbtpu-scan-reader")
        self._thread.start()

    # ------------------------------------------------------------ producer

    def _reader(self) -> None:
        from cloudberry_tpu.lifecycle import check_cancel, statement_scope

        scope = (statement_scope(self._handle)
                 if self._handle is not None else None)
        if scope is not None:
            scope.__enter__()
        try:
            it = iter(self._gen)
            while True:
                # cancel/deadline seam INSIDE the worker: a cancelled
                # statement stops the prefetch within one tile's work
                check_cancel()
                fault_point("scan_prefetch")
                t0 = time.perf_counter()
                try:
                    item = next(it)
                except StopIteration:
                    break
                if not self._offer(item, time.perf_counter() - t0):
                    break  # consumer closed: stop reading
        except BaseException as e:  # noqa: BLE001 — re-raised on consumer
            with self._cond:
                self._err = e
                self._cond.notify_all()
        finally:
            close = getattr(self._gen, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
            with self._cond:
                self._done = True
                self._cond.notify_all()
            if scope is not None:
                scope.__exit__(None, None, None)

    def _offer(self, item, feed_dt: float) -> bool:
        """Queue one tile, waiting while the bounded buffer is full.
        False when the consumer closed the pipeline."""
        from cloudberry_tpu.lifecycle import check_cancel

        while True:
            with self._cond:
                if not self._open:
                    return False
                if len(self._buf) < self.depth:
                    self._buf.append(item)
                    self.tiles += 1
                    self.feed_s += feed_dt
                    if len(self._buf) > self.max_depth:
                        self.max_depth = len(self._buf)
                    self._cond.notify_all()
                    return True
                self._cond.wait(0.05)
            # outside the lock: the cancel token is its own leaf lock
            check_cancel()

    # ------------------------------------------------------------ consumer

    def __iter__(self) -> "ScanPipeline":
        return self

    def __next__(self):
        if self._staged is not None:
            item = self._staged
            self._staged = None
        else:
            item = self._take(block=True)
            if item is _EOS:
                raise StopIteration
            item = self._stage(item)
        # double-buffer: stage the NEXT tile's device transfer while the
        # caller dispatches this one (non-blocking — never stalls here)
        nxt = self._take(block=False)
        if nxt is not _EOS and nxt is not _EMPTY:
            self._staged = self._stage(nxt)
        return item

    def _take(self, block: bool):
        from cloudberry_tpu.lifecycle import check_cancel

        t0 = None
        while True:
            err = None
            with self._cond:
                if self._buf:
                    item = self._buf.popleft()
                    self._cond.notify_all()
                    if t0 is not None:
                        self.stall_s += time.perf_counter() - t0
                    return item
                if not block:
                    # the double-buffer probe must NEVER raise: a
                    # pending producer error belongs to the NEXT
                    # blocking take, after the caller consumed the
                    # tile it already popped
                    return _EOS if (self._done and self._err is None) \
                        else _EMPTY
                if self._err is not None:
                    # staged tiles drained first: the error surfaces at
                    # the same stream position the synchronous feed
                    # would have raised it
                    err = self._err
                elif self._done:
                    return _EOS
                else:
                    if t0 is None:
                        t0 = time.perf_counter()
                    self._cond.wait(0.05)
            if err is not None:
                raise err
            check_cancel()

    def _stage(self, item):
        if not self._device_stage:
            return item
        import jax

        tile, n = item
        return ({k: jax.device_put(v) for k, v in tile.items()}, n)

    # ------------------------------------------------------------ teardown

    def close(self) -> None:
        """Stop the reader and release every staged buffer. Idempotent;
        the tile loops call it in a ``finally`` so retries/cancellation
        never leak a reader thread or pin prefetched tiles."""
        with self._cond:
            self._open = False
            self._buf.clear()
            self._cond.notify_all()
        self._thread.join(timeout=10.0)
        # a reader wedged past the join timeout (e.g. a hung partition
        # read) leaks as a daemon thread; record it so stats() never
        # reads ScanStats concurrently with the still-running writer
        self._reader_leaked = self._thread.is_alive()
        self._staged = None

    def stats(self) -> dict:
        with self._cond:
            feed_s = self.feed_s
            rec = {
                "enabled": True,
                "depth": self.depth,
                "tiles_prefetched": self.tiles,
                "max_depth": self.max_depth,
                "feed_s": round(feed_s, 6),
                "stall_s": round(self.stall_s, 6),
            }
        # overlap fraction: the share of producer work hidden behind
        # compute — feed time the consumer did NOT wait for
        if feed_s > 0:
            rec["overlap_frac"] = round(
                max(0.0, 1.0 - min(self.stall_s, feed_s) / feed_s), 4)
        st = self.scan_stats
        if self._reader_leaked:
            rec["reader_leaked"] = True  # snapshot would race the writer
        elif st is not None:
            rec.update(st.snapshot())
        return rec


class PlainFeed:
    """The pipeline-off twin: same close()/scan_stats surface over the
    raw generator, so the tile loops (and the report stamp) treat both
    modes uniformly and the A/B differs only in WHERE the host work
    runs."""

    def __init__(self, gen, stats: Optional[ScanStats] = None):
        self._gen = gen
        self.scan_stats = stats

    def __iter__(self):
        return self

    def __next__(self):
        return next(self._gen)

    def close(self) -> None:
        self._gen.close()

    def stats(self) -> dict:
        rec = {"enabled": False}
        if self.scan_stats is not None:
            rec.update(self.scan_stats.snapshot())
        return rec


def maybe_pipeline(gen, config, device_stage: bool = False,
                   stats: Optional[ScanStats] = None,
                   min_depth: int = 1):
    """Wrap a tile generator in the prefetch pipeline when
    ``config.scan_pipeline`` enables it; a PlainFeed otherwise (the
    synchronous path, unchanged semantics). ``min_depth`` lets the
    windowed tile dispatcher (exec/tilepipe.py) deepen the prefetch
    queue to its in-flight window so the feed never becomes the
    bottleneck behind a W-deep device queue; it never turns the
    pipeline ON when the config disabled it."""
    sp = getattr(config, "scan_pipeline", None)
    if sp is not None and sp.enabled and sp.prefetch_tiles >= 1:
        return ScanPipeline(gen, depth=max(sp.prefetch_tiles, min_depth),
                            device_stage=device_stage and sp.device_buffer,
                            stats=stats)
    return PlainFeed(gen, stats=stats)


def close_feed(feed) -> None:
    """Deterministic feed teardown for the tile loops' ``finally``:
    works for ScanPipeline, PlainFeed, and bare generators."""
    close = getattr(feed, "close", None)
    if close is not None:
        close()


def stamp_report(report: dict, feed) -> None:
    """Fold the feed's pipeline/decode accounting into the tiled run
    report (read by EXPLAIN ANALYZE's trailer and the bench ladder).
    Call AFTER the loop finished (and the feed closed): the stats are
    stable then."""
    stats_fn = getattr(feed, "stats", None)
    if stats_fn is not None:
        report["pipeline"] = stats_fn()


# ------------------------------------------------------------ decode pool


_pool = None
_pool_workers = 0
_pool_lock = threading.Lock()


def decode_pool(config):
    """The shared column-decode thread pool (daemon workers, lazily
    created, grown to the largest requested size). None when the
    pipeline is off, decode_workers <= 1, or the host exposes a single
    usable core — column-parallel decode cannot win there and the
    extra threads only add GIL contention (measured ~10% regression on
    a 1-core container); callers then decode serially on the reader
    thread, which still overlaps the consumer."""
    global _pool, _pool_workers
    sp = getattr(config, "scan_pipeline", None)
    if sp is None or not sp.enabled or sp.decode_workers <= 1:
        return None
    import os

    try:
        cores = len(os.sched_getaffinity(0))
    except AttributeError:  # platforms without affinity
        cores = os.cpu_count() or 1
    if cores < 2:
        return None
    from concurrent.futures import ThreadPoolExecutor

    with _pool_lock:
        if _pool is None or _pool_workers < sp.decode_workers:
            # the superseded pool (if any) is deliberately NOT shut
            # down: a concurrent feed may have captured it, and
            # submit() on a shut-down executor raises. Its idle daemon
            # workers are a bounded, grow-only leak.
            _pool = ThreadPoolExecutor(
                max_workers=sp.decode_workers,
                thread_name_prefix="cbtpu-scan-decode")
            _pool_workers = sp.decode_workers
        return _pool


# --------------------------------------------------------- memory charge


def tile_host_bytes(scan, tile_rows: int, nseg: int = 1) -> int:
    """Host bytes one staged tile pins: every physical column at its
    dtype width plus one bool per validity column, times the padded
    tile shape (× nseg for the distributed (nseg, tile_rows) tiles)."""
    import numpy as np

    width = 0
    for _ in scan.mask_map:
        width += 1
    try:
        for f in scan.fields:
            width += np.dtype(f.type.np_dtype).itemsize
    except Exception:  # noqa: BLE001 — conservative fallback
        width += 8 * max(len(scan.column_map), 1)
    return int(width) * int(tile_rows) * max(int(nseg), 1)


def queue_charge_bytes(scan, tile_rows: int, config,
                       nseg: int = 1) -> int:
    """The capacity-plane charge for the pipeline's staging memory:
    ``prefetch_tiles`` × one tile's working set (obs/capacity.py
    record_tiled adds it to the statement's observed peak)."""
    sp = getattr(config, "scan_pipeline", None)
    if sp is None or not sp.enabled or sp.prefetch_tiles < 1:
        return 0
    return sp.prefetch_tiles * tile_host_bytes(scan, tile_rows, nseg)
