"""Distributed tiled (out-of-core) execution — spill on the segment mesh.

The reference spills operator state per segment process (workfile_mgr.c,
nodeHash.c's increase-nbatch discipline) while Motion keeps flowing between
slices. The XLA translation (exec/tiled.py rationale) moves the spill
boundary to plan time; HERE it moves onto the mesh: when an
admission-rejected plan is distributed (n_segments > 1), the probe-side
stream is tiled PER SEGMENT and each step is one shard_map program over the
segment mesh — the plan's Motions (redistribute / runtime filters) execute
INSIDE every step as per-tile collectives:

- prelude (once): every spine join's build subtree — including its own
  motions (broadcast of small tables, build-side redistributes) — computed
  by one SPMD program; the per-segment results stay resident on device;
- step (per tile): each segment feeds tile t of ITS shard; the spine's
  redistribute motions run per tile with bucket capacity min(planned, tile)
  — a tile of T rows can never send more than T rows to one destination,
  so per-tile flow control is overflow-free whenever the planned cap was
  exact; the tile's partial aggregation merges into a per-segment
  fixed-capacity accumulator (associative partials — any tile order and
  count gives the same answer, plan/distribute.py:_split_aggs);
- finalize (once): the accumulators take the partial aggregation's place in
  the ORIGINAL distributed plan — the merge motion (gather / redistribute
  by group keys), final aggregation, and post chain run unchanged as one
  last SPMD program.

Peak device memory per segment is the admitted estimate: resident builds +
one tile's working set (including its post-motion receive buffers) + the
accumulator — independent of the streamed table's size. That is the SF100
contract: shard size is bounded by host RAM, device HBM only by the budget.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from cloudberry_tpu.columnar.batch import ColumnBatch
from cloudberry_tpu.exec import bufferpool as BUF
from cloudberry_tpu.exec import executor as X
from cloudberry_tpu.exec import kernels as K
from cloudberry_tpu.exec import scanpipe as SP
from cloudberry_tpu.exec import tilepipe as TP
from cloudberry_tpu.exec.dist_executor import (DistLowerer, _local_row,
                                               _shard_map,
                                               prepare_dist_inputs)
from cloudberry_tpu.exec.resource import estimate_plan_memory
from cloudberry_tpu.exec.tiled import (_MAX_TILE, _MIN_TILE, _acc_width,
                                       _expr_dict, _merge_bytes, _out_cap,
                                       _raise_tile_checks, AdaptiveTiledMixin)
from cloudberry_tpu.parallel.mesh import SEG_AXIS, segment_mesh
from cloudberry_tpu.parallel.topology import \
    topology_token as _topology_token
from cloudberry_tpu.plan import expr as ex
from cloudberry_tpu.plan import nodes as N
from cloudberry_tpu.utils.faultinject import fault_point
from cloudberry_tpu.plan.distribute import (_all_exprs, _finalize_project,
                                            _split_aggs)


@dataclass
class _DistTileShape:
    """Everything the rewrite discovered about the distributed plan."""

    root: N.PlanNode                 # finalize program root (whole plan)
    replace_node: N.PlanNode         # node the accumulator stands in for
    partial_plan: N.PAgg             # per-tile partial aggregation
    merge_motion: Optional[N.PMotion]  # motion above the partial (case A)
    final_agg: Optional[N.PAgg]      # merge aggregation (case A)
    spine: list[N.PlanNode]          # partial.child .. just above the stream
    stream: N.PScan                  # the tiled per-segment scan
    builds: list[N.PlanNode]         # prelude-computed subtrees
    stream_rows: int = 0             # max per-segment shard rows
    merge_specs: list = field(default_factory=list)
    group_names: list = field(default_factory=list)
    g_cap: int = 0                   # per-segment accumulator capacity
    max_groups: int = 0              # hard ceiling for g_cap growth
    mode: str = "agg"
    sortnode: Optional[N.PSort] = None  # topn/sort: the (synthetic) sort
    post: list = field(default_factory=list)  # topn: chain above spine
    post_above: list = field(default_factory=list)  # sort: above the sort
    winnode: Optional[N.PWindow] = None  # window: BOTTOM of the stack
    n_ckeys: int = 0                     # window: chunk-key count


def plan_tiled_dist(plan: N.PlanNode, session) -> Optional["DistTiledExecutable"]:
    """Re-plan an admission-rejected DISTRIBUTED statement for tiled
    execution over the segment mesh. None when the plan shape or the
    budget cannot support it."""
    if not session.config.resource.enable_spill:
        return None
    if getattr(plan, "_direct_segment", None) is not None:
        return None
    shape = _analyze_dist(plan, session)
    if shape is None:
        return None

    # whole-run growth marks belong to the untiled attempt; the tiled
    # adaptive loop re-learns spine buffer sizes per tile (builds keep
    # theirs — the prelude still computes whole builds)
    for node in shape.spine:
        if isinstance(node, N.PJoin) and hasattr(node, "_min_out_cap"):
            del node._min_out_cap
    # join-index inputs are a one-shot-executor feature (exec/joinindex):
    # tiled step programs assemble their own inputs, so drop the
    # annotations and let joins argsort in-program — speculatively: a
    # decline below restores them for the one-shot fallback
    from cloudberry_tpu.exec.joinindex import (restore_join_index,
                                               stash_join_index,
                                               strip_join_index)

    jix_stash = stash_join_index(plan)
    strip_join_index(plan)

    if shape.mode == "agg":
        from cloudberry_tpu.plan.cost import estimate_rows

        try:
            est_groups = estimate_rows(shape.partial_plan, session.catalog)
        except Exception:
            est_groups = 1024
        shape.g_cap = int(min(shape.max_groups,
                              max(1024, 4 * int(est_groups) + 1)))
        if not shape.group_names:
            shape.g_cap = 1

    budget = session.config.resource.query_mem_bytes
    tile_rows = _choose_tile_dist(shape, budget, session.config.n_segments)
    if tile_rows is None and shape.mode == "topn":
        # LIMIT+OFFSET exceeds any resident accumulator: fall back to
        # the full external sort (host RAM is the workfile) when the
        # chain above the sort can apply host-side
        s2 = _to_dist_sort(shape)
        if s2 is None:
            restore_join_index(jix_stash)
            return None
        shape = s2
        tile_rows = _choose_tile_dist(shape, budget,
                                      session.config.n_segments)
    if tile_rows is None:
        restore_join_index(jix_stash)
        return None
    cls = {"topn": DistTopNTiledExecutable,
           "sort": DistSortTiledExecutable,
           "window": DistWindowTiledExecutable,
           "agg": DistTiledExecutable}[shape.mode]
    return cls(shape, session, tile_rows, budget)


def _to_dist_sort(shape: _DistTileShape) -> Optional[_DistTileShape]:
    """Re-aim a topn shape at the external-sort executable."""
    from cloudberry_tpu.exec.tiled import host_post_ok

    post_above = shape.post[:shape.post.index(shape.sortnode)]
    if not host_post_ok(post_above, shape.sortnode.keys):
        return None
    shape.mode = "sort"
    shape.g_cap = 0
    shape.post_above = post_above
    return shape


def _analyze_dist(plan: N.PlanNode, session) -> Optional[_DistTileShape]:
    """Recognize the streamable distributed shape: post chain (projections /
    sorts / limits / gather motions) over a two-stage aggregation
    (final ← motion ← partial) — or a colocated one-stage aggregation —
    over a join/filter/redistribute spine whose probe path ends at a
    partitioned scan."""
    for e in _all_exprs(plan):
        for sub in ex.walk(e):
            if isinstance(sub, ex.SubqueryScalar):
                return None  # subquery plans scan outside the spine budget

    post: list[N.PlanNode] = []
    cur = plan
    while True:
        if isinstance(cur, (N.PProject, N.PSort, N.PLimit, N.PFilter)):
            post.append(cur)
            cur = cur.child
        elif isinstance(cur, N.PMotion) and cur.kind == "gather":
            post.append(cur)
            cur = cur.child
        else:
            break
    if isinstance(cur, N.PWindow):
        return _analyze_dist_window(plan, post, cur, session)
    if not isinstance(cur, N.PAgg):
        return _analyze_dist_topn(plan, post, session)

    if cur.mode == "final":
        final_agg = cur
        motion = final_agg.child
        if not isinstance(motion, N.PMotion) \
                or motion.kind not in ("gather", "redistribute"):
            return None
        partial = motion.child
        if not isinstance(partial, N.PAgg) or partial.mode != "partial":
            return None
        merge_specs = [K.AggSpec(call.func, name)
                       for name, call in final_agg.aggs]
        group_names = [n for n, _ in partial.group_keys]
        spine_res = _walk_spine(partial.child, session)
        if spine_res is None:
            return None
        spine, stream, builds, stream_rows = spine_res
        return _DistTileShape(
            root=plan, replace_node=partial, partial_plan=partial,
            merge_motion=motion, final_agg=final_agg, spine=spine,
            stream=stream, builds=builds, stream_rows=stream_rows,
            merge_specs=merge_specs, group_names=group_names,
            max_groups=partial.capacity)

    if cur.mode != "single":
        return None
    # one-stage colocated aggregation: build the partial/merge split the
    # single-node tiled planner uses; the accumulator IS the final state
    # per segment (groups are colocated), so finalize is just the
    # finalize-projection + post chain
    agg = cur
    try:
        partial_aggs, final_aggs, finalize = _split_aggs(agg.aggs)
    except ValueError:
        return None
    spine_res = _walk_spine(agg.child, session)
    if spine_res is None:
        return None
    spine, stream, builds, stream_rows = spine_res

    from cloudberry_tpu.exec.tiled import _AccLeaf

    partial = N.PAgg(agg.child, agg.group_keys, partial_aggs,
                     capacity=agg.capacity, mode="partial")
    partial.fields = [
        N.PlanField(n, e.dtype, _expr_dict(agg.child, e))
        for n, e in agg.group_keys
    ] + [N.PlanField(n, c.dtype, None) for n, c in partial_aggs]

    leaf = _AccLeaf()
    leaf.fields = list(partial.fields)
    leaf.sharding = agg.sharding
    fproj = _finalize_project(leaf, agg, finalize)
    fproj.sharding = agg.sharding
    if post:
        post[-1].child = fproj
        root = post[0]
    else:
        root = fproj
    merge_specs = [K.AggSpec(call.func, name) for name, call in final_aggs]
    return _DistTileShape(
        root=root, replace_node=leaf, partial_plan=partial,
        merge_motion=None, final_agg=None, spine=spine, stream=stream,
        builds=builds, stream_rows=stream_rows, merge_specs=merge_specs,
        group_names=[n for n, _ in agg.group_keys],
        max_groups=agg.capacity)


def _analyze_dist_topn(plan, post, session) -> Optional[_DistTileShape]:
    """ORDER BY + LIMIT with no aggregation: per-segment bounded top-N
    accumulators (the distributed twin of tiled.py's topn mode). Every
    segment keeps the best LIMIT+OFFSET rows of ITS stream — the global
    top-N is a subset of that union — and finalize re-runs the ORIGINAL
    plan (pre-gather compaction, gather, sorts, limits) over the
    accumulators as one SPMD program."""
    from cloudberry_tpu.exec.tiled import _topn_bound

    # motions in the chain are gathers (the walk guarantees): row-set-
    # preserving, so the limit search may cross them
    hit = _topn_bound(post, skip=(N.PMotion,))
    if hit is None:
        return _analyze_dist_sort(plan, post, session)
    sortnode, m = hit
    spine_res = _walk_spine(sortnode.child, session)
    if spine_res is None:
        return None
    spine, stream, builds, stream_rows = spine_res
    shape = _DistTileShape(
        root=plan, replace_node=sortnode.child,
        partial_plan=sortnode.child, merge_motion=None, final_agg=None,
        spine=spine, stream=stream, builds=builds,
        stream_rows=stream_rows, mode="topn", sortnode=sortnode,
        post=post)
    shape.g_cap = m
    shape.max_groups = m
    return shape


def _analyze_dist_sort(plan, post, session) -> Optional[_DistTileShape]:
    """Unbounded ORDER BY, distributed: the external-sort stream runs
    per segment (the spine's own motions execute per tile); the host
    pools every segment's rows — the gather is subsumed by collection —
    and the merge pass plus the chain above the sort apply host-side
    (tiled.py SortTiledExecutable's discipline on the mesh)."""
    sort_i = next((i for i in range(len(post) - 1, -1, -1)
                   if isinstance(post[i], N.PSort)), None)
    if sort_i is None:
        return None
    from cloudberry_tpu.exec.tiled import host_post_ok

    sortnode = post[sort_i]
    post_above = post[:sort_i]
    if not host_post_ok(post_above, sortnode.keys):
        return None
    below = sortnode.child
    while isinstance(below, N.PMotion) and below.kind == "gather":
        below = below.child
    spine_res = _walk_spine(below, session)
    if spine_res is None:
        return None
    spine, stream, builds, stream_rows = spine_res
    shape = _DistTileShape(
        root=plan, replace_node=below, partial_plan=below,
        merge_motion=None, final_agg=None, spine=spine, stream=stream,
        builds=builds, stream_rows=stream_rows, mode="sort",
        sortnode=sortnode, post=post)
    shape.post_above = post_above
    return shape


def _analyze_dist_window(plan, post, top_window,
                         session) -> Optional[_DistTileShape]:
    """Window stack, distributed: phase one is the per-segment
    external-sort stream grouped by the stack's common partition keys;
    phase two runs whole-partition chunks through the ORIGINAL plan
    (gathers lower as identity on pooled host rows) on one device —
    chunks are independent, so no mesh is needed above the stream."""
    for nd in post:
        if isinstance(nd, N.PMotion) and nd.kind == "gather":
            continue
        if isinstance(nd, N.PProject) and all(
                isinstance(e, ex.ColumnRef) for _, e in nd.exprs):
            continue
        return None
    node = top_window
    bottom = node
    common = None
    while isinstance(node, N.PWindow):
        bottom = node
        here = {repr(pk): pk for pk in node.partition_keys}
        common = here if common is None else \
            {k: v for k, v in common.items() if k in here}
        node = node.child
    if not common:
        return None
    below = bottom.child
    while isinstance(below, N.PMotion) and below.kind == "gather":
        below = below.child
    spine_res = _walk_spine(below, session)
    if spine_res is None:
        return None
    spine, stream, builds, stream_rows = spine_res
    ckeys = list(common.values())
    srt = N.PSort(below, [(ck, True) for ck in ckeys])
    srt.fields = list(below.fields)
    shape = _DistTileShape(
        root=plan, replace_node=bottom.child, partial_plan=below,
        merge_motion=None, final_agg=None, spine=spine, stream=stream,
        builds=builds, stream_rows=stream_rows, mode="window",
        sortnode=srt, post=post)
    shape.winnode = bottom
    shape.n_ckeys = len(ckeys)
    return shape


def _walk_spine(top: N.PlanNode, session):
    """Descend the probe path: filters/projections/runtime filters/joins/
    redistribute motions down to a partitioned scan (the stream)."""
    spine: list[N.PlanNode] = []
    builds: list[N.PlanNode] = []
    seen: set[int] = set()
    cur = top
    # graftlint: ignore[seam-loop] bounded plan-tree descent (one step per node; catalog lookups only) — terminates with the tree, never a tile/retry loop
    while True:
        if isinstance(cur, (N.PFilter, N.PProject)):
            spine.append(cur)
            cur = cur.child
        elif isinstance(cur, N.PRuntimeFilter):
            spine.append(cur)
            if id(cur.build) not in seen:
                seen.add(id(cur.build))
                builds.append(cur.build)
            cur = cur.child
        elif isinstance(cur, N.PMotion) and cur.kind == "redistribute":
            cur._orig_bucket_cap = cur.bucket_cap
            spine.append(cur)
            cur = cur.child
        elif isinstance(cur, N.PJoin):
            if cur.kind == "full":
                return None  # unmatched-BUILD emission is once-per-stmt
            spine.append(cur)
            if id(cur.build) not in seen:
                seen.add(id(cur.build))
                builds.append(cur.build)
            cur = cur.probe
        elif isinstance(cur, N.PScan) and cur.table_name != "$dual":
            try:
                t = session.catalog.table(cur.table_name)
            except KeyError:
                return None
            if t.policy.kind == "replicated":
                return None  # stream the partitioned side only
            st = session.sharded_table(cur.table_name)
            rows = int(st.counts.max()) if len(st.counts) else 0
            return spine, cur, builds, max(rows, 1)
        else:
            return None


def _retile_dist(shape: _DistTileShape, tile_rows: int, nseg: int) -> None:
    """Re-derive spine capacities for one tile per segment. Redistribute
    buckets are clamped to the per-tile send bound (a source segment's tile
    holds at most ``cap`` rows, so no destination bucket can exceed it);
    expansion joins keep the NDV pair-estimate floor scaled to the tile
    fraction, and runtime-grown buffers (_min_out_cap) never shrink."""
    frac = tile_rows / max(shape.stream_rows, 1)
    shape.stream.capacity = tile_rows
    shape.stream.num_rows = -2
    cap = tile_rows
    for node in reversed(shape.spine):
        if isinstance(node, N.PMotion):  # redistribute (walk guarantees)
            node.bucket_cap = max(min(node._orig_bucket_cap, cap), 8,
                                  getattr(node, "_min_bucket_cap", 0))
            node.out_capacity = node.bucket_cap * nseg
            cap = node.out_capacity
        elif isinstance(node, N.PJoin):
            bcap = _out_cap(node.build)
            est = getattr(node, "_est_pairs", None)
            floor = int(2 * est / nseg * min(frac, 1.0)) + 8 if est else 0
            floor = max(floor, getattr(node, "_min_out_cap", 0))
            if node.residual is not None:
                node.out_capacity = max(bcap + cap, floor)
            elif not node.unique_build:
                node.out_capacity = max(bcap + cap, floor)
                cap = node.out_capacity
    if shape.mode == "agg":
        shape.partial_plan.capacity = min(shape.g_cap, max(cap, 1)) \
            if shape.group_names else 1


def _finalize_bytes(shape: _DistTileShape, nseg: int) -> int:
    """Working set of the one-shot finalize program per segment: the merge
    motion's receive buffer and final aggregation both hold up to
    nseg·g_cap accumulator rows (one g_cap block from every segment); the
    colocated one-stage case never leaves the segment. topn finalize
    gathers every segment's accumulator for the global sort."""
    if shape.mode == "topn":
        rows = shape.g_cap * nseg
    else:
        rows = shape.g_cap * (nseg if shape.merge_motion is not None
                              else 1)
    return 3 * rows * _acc_width(shape)


def _choose_tile_dist(shape: _DistTileShape, budget: int,
                      nseg: int) -> Optional[int]:
    if _finalize_bytes(shape, nseg) > budget:
        return None  # no tile size can shrink the finalize program
    t = _MAX_TILE
    while t >= _MIN_TILE:
        _retile_dist(shape, t, nseg)
        est = estimate_plan_memory(shape.partial_plan).peak_bytes
        if est + _merge_bytes(shape) <= budget:
            return t
        t >>= 1
    return None


# --------------------------------------------------------------- lowerers


class _DistReplacingLowerer(DistLowerer):
    """DistLowerer with a node-identity substitution table (prelude-computed
    builds; the finalize accumulator)."""

    def __init__(self, tables, nseg: int, replace: dict, **kw):
        super().__init__(tables, nseg, **kw)
        self._replace = replace

    def lower(self, node: N.PlanNode):
        hit = self._replace.get(id(node))
        if hit is not None:
            return hit
        return super().lower(node)


class _DistTileLowerer(_DistReplacingLowerer):
    """Step-program lowerer: the stream scan reads this segment's tile."""

    def __init__(self, tables, nseg: int, stream: N.PScan, tile_n,
                 replace: dict, **kw):
        super().__init__(tables, nseg, replace, **kw)
        self._stream = stream
        self._tile_n = tile_n

    def scan(self, node: N.PScan):
        if node is not self._stream:
            return super().scan(node)
        tile = self.tables["$tile"]
        cols = {}
        for phys, out in node.column_map.items():
            cols[out] = tile[phys]
        for phys, out in node.mask_map.items():
            cols[out] = tile[f"$nn:{phys}"]
        sel = jnp.arange(node.capacity) < self._tile_n
        return cols, sel


# --------------------------------------------------------------- execution


def _strip_seg(tree):
    """Per-segment block view inside shard_map: drop the leading (1,) axis
    every sharded leaf carries."""
    return jax.tree_util.tree_map(lambda a: a[0], tree)


def _add_seg(tree):
    return jax.tree_util.tree_map(lambda a: a[None], tree)


def _reduce_checks(checks: dict) -> dict:
    """Replicated any-segment-tripped scalars — readable on every host."""
    return {k: jax.lax.psum(jnp.asarray(v).astype(jnp.int32), SEG_AXIS) > 0
            for k, v in checks.items()}


def _motion_stats(low, motions, nseg: int):
    """Per-motion (required-bucket scalar, per-destination row vector)
    pairs off the lowerer's replicated stats channel
    (dist_executor.DistLowerer.motion psums/pmaxes them) — zeros when a
    motion lowered without the bucketed path. The skew sentinel
    (exec/tiled.py SkewSentinel) accumulates these host-side across
    tiles; the end-of-run fold publishes them to the feedback store."""
    return tuple(
        (low.stats.get(f"required bucket (node {id(m)})",
                       jnp.zeros((), jnp.int32)),
         low.stats.get(f"seg rows (node {id(m)})",
                       jnp.zeros((nseg,), jnp.int32)))
        for m in motions)


class DistTiledExecutable(AdaptiveTiledMixin):
    """Compiled distributed tiled statement: prelude (once) → step (per
    tile, lock-step across segments) → finalize. ``report`` records the
    spill decision for tests/EXPLAIN."""

    _what = "distributed tiled execution"

    def __init__(self, shape: _DistTileShape, session, tile_rows: int,
                 budget: int):
        self.shape = shape
        self.session = session
        self.nseg = session.config.n_segments
        self.tile_rows = tile_rows
        self.budget = budget
        self._use_pallas = session.config.exec.use_pallas
        # the step programs' spine motions AND the finalize merge motion
        # share the packed wire format (kernels.wire_layout) — per-tile
        # redistributes are one collective each too
        self._packed = session.config.interconnect.packed_wire
        self._compiled = None
        self._run_lock = threading.Lock()
        self._refresh_report()

    def _refresh_report(self) -> None:
        shape = self.shape
        _retile_dist(shape, self.tile_rows, self.nseg)
        est = estimate_plan_memory(shape.partial_plan).peak_bytes
        self.report = {
            "tiled": True,
            "distributed": True,
            "n_segments": self.nseg,
            # the topology epoch this executable was (re)built under
            # (parallel/topology.py): a report whose epoch differs from
            # the statement's pinned one is the cross-epoch-resume case
            "topology_epoch": _topology_token(self.session),
            "stream_table": shape.stream.table_name,
            "tile_rows": self.tile_rows,
            "acc_capacity": shape.g_cap,
            "est_step_bytes": est + _merge_bytes(shape),
            "est_finalize_bytes": _finalize_bytes(shape, self.nseg),
            # scan-pipeline staging charge (exec/scanpipe.py) plus the
            # dispatch window's extra in-flight (nseg, tile_rows) tiles
            # (exec/tilepipe.py) — obs/capacity.record_tiled adds both
            # to the statement's observed peak
            "est_pipeline_bytes": SP.queue_charge_bytes(
                shape.stream, self.tile_rows, self.session.config,
                nseg=self.nseg)
            + TP.window_charge_bytes(
                shape.stream, self.tile_rows, self.session.config,
                jax.default_backend(), nseg=self.nseg),
            # buffer-pool residency for the streamed table's packed
            # feed tiles (exec/bufferpool.py; host-side here —
            # shard_map owns device placement on the distributed path)
            "est_bufpool_bytes": _bufpool_charge_dist(
                self.session, shape.stream.table_name),
            "budget_bytes": self.budget,
        }

    def _over_budget(self) -> bool:
        return (self.report["est_step_bytes"] > self.budget
                or self.report["est_finalize_bytes"] > self.budget)

    def _groups_ceiling(self) -> int:
        return self.shape.max_groups

    # ------------------------------------------------------------ programs

    def _whole_plan(self) -> N.PlanNode:
        return self.shape.partial_plan

    def _resident_names(self) -> list[str]:
        return sorted({s.table_name
                       for s in X.scans_of(self.shape.partial_plan)
                       if s is not self.shape.stream})

    def _compile(self):
        if self._compiled is not None:
            return self._compiled
        shape = self.shape
        nseg = self.nseg
        live_ids = getattr(self.session, "_live_device_ids", None)
        mesh = segment_mesh(nseg, live_ids)
        from cloudberry_tpu.parallel.transport import (hier_topology,
                                                       make_transport)

        ic = self.session.config.interconnect
        # the tiled program must run the SAME motion semantics as the
        # in-memory dist path: a plan whose motions carry two-level
        # stamps (host_combine grew the rungs) would otherwise pay the
        # padding while shipping flat — the regression, not the win
        topo = hier_topology(self.session.config, nseg, live_ids)
        tx = make_transport(ic.backend, nseg, chunks=ic.ring_chunks,
                            topo=topo)
        names = self._resident_names()
        _, res_specs = prepare_dist_inputs(None, self.session, names=names)

        def prelude_seg(tables):
            low = DistLowerer(tables, nseg, use_pallas=self._use_pallas,
                              tx=tx, packed=self._packed)
            outs = [_add_seg(low.lower_shared(b)) for b in shape.builds]
            return outs, _reduce_checks(low.checks)

        prelude_fn = jax.jit(_shard_map(
            prelude_seg, mesh, (res_specs,), (P(SEG_AXIS), P())))

        step_fn = self._make_step(mesh, tx, res_specs)

        def finalize_seg(acc):
            acc_cols, acc_sel = _strip_seg(tuple(acc))
            low = _DistReplacingLowerer(
                {}, nseg, {id(shape.replace_node): (acc_cols, acc_sel)},
                use_pallas=self._use_pallas, tx=tx, packed=self._packed)
            cols, sel = low.lower(shape.root)
            out = {f.name: cols[f.name][None] for f in shape.root.fields}
            return out, sel[None], _reduce_checks(low.checks)

        finalize_fn = jax.jit(_shard_map(
            finalize_seg, mesh, (P(SEG_AXIS),),
            (P(SEG_AXIS), P(SEG_AXIS), P())))

        self._compiled = (prelude_fn, step_fn, finalize_fn)
        return self._compiled

    def _stat_motions(self):
        """The step program's redistribute motions, in deterministic
        traversal order — the skew sentinel (exec/tiled.py) watches
        their psum'd per-destination row counts, and the end-of-run
        fold publishes the cumulative observations to the feedback
        store (plan/feedback.py)."""
        return tuple(n for n in X.all_nodes(self.shape.partial_plan)
                     if isinstance(n, N.PMotion)
                     and n.kind == "redistribute")

    def _make_step(self, mesh, tx, res_specs):
        shape = self.shape
        nseg = self.nseg
        group_names = list(shape.group_names)
        specs = shape.merge_specs
        pallas, plat = self._use_pallas, jax.default_backend()
        stat_motions = self._stat_motions()

        def step_seg(resident, prelude, tile, tile_n, acc):
            tables = dict(resident)
            tables["$tile"] = _strip_seg(tile)
            plocal = _strip_seg(prelude)
            replace = {id(b): tuple(plocal[i])
                       for i, b in enumerate(shape.builds)}
            low = _DistTileLowerer(tables, nseg, shape.stream,
                                   tile_n.reshape(()), replace,
                                   use_pallas=self._use_pallas, tx=tx,
                                   packed=self._packed)
            pcols, psel = low.lower(shape.partial_plan)
            checks = dict(low.checks)
            srows = _motion_stats(low, stat_motions, nseg)
            acc_cols, acc_sel = _strip_seg(tuple(acc))
            g_cap = shape.g_cap
            if group_names:
                key_cols = {n: jnp.concatenate([acc_cols[n], pcols[n]])
                            for n in group_names}
                agg_vals = {s.out_name: jnp.concatenate(
                    [acc_cols[s.out_name], pcols[s.out_name]])
                    for s in specs}
                sel = jnp.concatenate([acc_sel, psel])
                # same fused-or-XLA dispatch as the one-shot executor:
                # eligible int sums are bit-identical on either side
                ok, oa, osel, n_groups = X.merge_group_aggregate(
                    key_cols, agg_vals, specs, sel, g_cap, pallas, plat)
                checks["tile merge overflow: more groups than capacity "
                       f"{g_cap}; raise the aggregation capacity"] = \
                    n_groups > g_cap
                return _add_seg(({**ok, **oa}, osel)), \
                    _reduce_checks(checks), srows
            agg_vals = {s.out_name: jnp.concatenate(
                [acc_cols[s.out_name], pcols[s.out_name]])
                for s in specs}
            sel = jnp.concatenate([acc_sel, psel])
            out = K.global_aggregate(agg_vals, specs, sel)
            return _add_seg((out, jnp.ones((1,), dtype=jnp.bool_))), \
                _reduce_checks(checks), srows

        return self._jit_step(step_seg, mesh, res_specs)

    def _jit_step(self, step_seg, mesh, res_specs):
        step_in = (res_specs, P(SEG_AXIS), P(SEG_AXIS), P(SEG_AXIS),
                   P(SEG_AXIS))
        donate = TP.step_donation(jax.default_backend())
        # third output: per-motion (required-bucket, per-destination
        # rows) telemetry pairs — psum/pmax replicated, so P() like the
        # checks; the skew sentinel consumes them host-side
        return jax.jit(_shard_map(step_seg, mesh, step_in,
                                  (P(SEG_AXIS), P(), P())),
                       donate_argnums=donate)

    def _refinalize(self) -> None:
        """Size the merge boundary for the accumulator: a segment's acc has
        at most g_cap rows, so a redistribute bucket (all of one source's
        acc to one destination) is bounded by g_cap, and the final
        aggregation sees at most nseg·g_cap rows."""
        shape = self.shape
        if shape.merge_motion is not None:
            if shape.merge_motion.kind == "redistribute":
                shape.merge_motion.bucket_cap = shape.g_cap
            shape.merge_motion.out_capacity = shape.g_cap * self.nseg
        if shape.final_agg is not None:
            shape.final_agg.capacity = max(shape.g_cap * self.nseg, 1)

    def _init_acc(self):
        shape = self.shape
        g_cap = shape.g_cap
        cols = {}
        if shape.group_names:
            for f in shape.partial_plan.fields:
                cols[f.name] = np.zeros((self.nseg, g_cap),
                                        dtype=f.type.np_dtype)
            return cols, np.zeros((self.nseg, g_cap), dtype=np.bool_)
        for f, spec in zip(shape.partial_plan.fields, shape.merge_specs):
            dt = f.type.np_dtype
            if spec.func == "min":
                ident = np.array(
                    np.finfo(dt).max if np.issubdtype(dt, np.floating)
                    else np.iinfo(dt).max, dtype=dt)
            elif spec.func == "max":
                ident = np.array(
                    np.finfo(dt).min if np.issubdtype(dt, np.floating)
                    else np.iinfo(dt).min, dtype=dt)
            else:
                ident = np.zeros((), dtype=dt)
            cols[f.name] = np.full((self.nseg, 1), ident)
        # identity row stays unselected: min/max identities must not leak
        return cols, np.zeros((self.nseg, 1), dtype=np.bool_)

    # ----------------------------------------------------------------- run

    def run(self) -> ColumnBatch:
        with self._run_lock:
            return self._run_adaptive()

    def _run_once(self) -> ColumnBatch:
        from cloudberry_tpu.exec import recovery as R

        # mid-statement recovery (exec/recovery.py): the prepare step may
        # grow g_cap for re-sharded partials, so it runs BEFORE the
        # retile/refinalize/compile chain fixes the program shapes
        ctx = R.begin(self, dist=True)
        if ctx is not None:
            ctx.prepare_dist()
        _retile_dist(self.shape, self.tile_rows, self.nseg)
        self._refinalize()
        prelude_fn, step_fn, finalize_fn = self._compile()
        resident, _ = prepare_dist_inputs(
            None, self.session, names=self._resident_names())
        if self.shape.builds:
            prelude, pchecks = prelude_fn(resident)
            X.raise_checks(pchecks)
        else:
            prelude, pchecks = [], {}

        acc = self._init_acc()
        if ctx is not None:
            acc = ctx.restore_acc(acc)
        feed = (ctx.feed() if ctx is not None else None) \
            or _dist_tile_feed(self.shape.stream, self.session,
                               self.tile_rows)
        n_base = ctx.tiles_base if ctx is not None else 0
        n_local = 0
        from cloudberry_tpu.exec.tiled import SkewSentinel, _TileTimer

        timer = _TileTimer(self.session)
        tracker = _dist_progress_tracker(self, feed, n_base)
        sentinel = SkewSentinel(self, self._stat_motions(), ctx)
        pipe = TP.TilePipe(self.session, TP.effective_window(
            self.session.config, jax.default_backend()))
        # prefetch pipeline over the per-segment feed (exec/scanpipe.py:
        # host staging only — shard_map owns device placement); the
        # tracker/checkpoint math reads the UNWRAPPED feed above, and
        # progress counts consumed tiles, never staged ones
        stream = SP.maybe_pipeline(iter(feed), self.session.config,
                                   min_depth=pipe.window)
        n_sub = 0

        def _verified(d):
            # host effects for one drained-clean tile, in stream order
            nonlocal n_local
            tile_k, staged, srows = d.payload
            n_local = tile_k
            if srows is not None:
                sentinel.observe(srows)
            tracker.step(tile_k)
            if ctx is not None:
                ctx.tick(tile_k, staged if staged is not None
                         else (lambda: R.acc_payload(acc)))

        def _settle():
            # drain every dispatched tile so the replan snapshot's acc
            # (the newest) matches the settled tile count
            for d in pipe.drain_all():
                _verified(d)
            return n_sub

        try:
            for tile, tile_ns in stream:
                fault_point("tile_step_dist")
                fault_point("tile_device_lost")
                n_sub += 1
                stage = (ctx is not None and pipe.window > 1
                         and ctx.snapshot_due(n_sub))
                with timer.step(n_base + n_sub - 1):
                    acc, checks, srows = step_fn(resident, prelude, tile,
                                                 tile_ns, acc)
                    staged = TP.stage_checkpoint(acc) if stage else None
                    drained = pipe.submit(
                        n_base + n_sub - 1, checks,
                        (n_sub, staged,
                         srows if sentinel.collect else None))
                for d in drained:
                    _verified(d)
                # AFTER the cadence tick: an alarm at a tick tile reuses
                # that snapshot instead of saving twice
                sentinel.maybe_replan(n_local,
                                      lambda: R.acc_payload(acc),
                                      settle=_settle)
            for d in pipe.drain_all():
                _verified(d)
            if pipe.window > 1:
                # the tail's observes may alarm after the feed ended
                sentinel.maybe_replan(n_local,
                                      lambda: R.acc_payload(acc))
        finally:
            if pipe.deferred_fail:
                self._deferred_fail = True
            SP.close_feed(stream)
        SP.stamp_report(self.report, stream)
        timer.stamp(self.report)
        pipe.stamp(self.report)
        sentinel.fold_final()
        n_tiles = n_base + n_local
        if n_tiles == 0:  # empty stream: one all-masked tile seeds the acc
            tile, _ = _empty_dist_tile(self.shape.stream, self.tile_rows,
                                       self.nseg)
            zeros = np.zeros((self.nseg,), dtype=np.int64)
            acc, checks, _ = step_fn(resident, prelude, tile, zeros, acc)
            _raise_tile_checks(checks, 0)
            n_tiles = 1

        # cancel seam before the finalize motions (the merge collective):
        # the per-tile checks bound the stream, this bounds the tail
        from cloudberry_tpu.lifecycle import check_cancel

        check_cancel()
        cols, sel, fchecks = finalize_fn(acc)
        X.raise_checks(fchecks)
        self.report["n_tiles"] = n_tiles
        if ctx is not None:
            ctx.stamp_report(self.report)
        self._publish_report()
        host_cols = {k: _local_row(v) for k, v in cols.items()}
        return X.make_batch(self.shape.root, host_cols, _local_row(sel))


class DistTopNTiledExecutable(DistTiledExecutable):
    """Distributed tiled statement with per-segment bounded top-N row
    accumulators (tiled.py TopNTiledExecutable on the mesh): each
    segment's step merges its tile through one LOCAL bounding sort — no
    collectives beyond the spine's own motions — and finalize re-runs
    the original plan (pre-gather compaction, gather, global sort,
    LIMIT) over the accumulators."""

    _what = "distributed top-N tiled execution"

    def _groups_ceiling(self) -> int:
        return self.shape.g_cap  # fixed: LIMIT itself bounds the acc

    def _refresh_report(self) -> None:
        super()._refresh_report()
        self.report["mode"] = "topn"

    def _refinalize(self) -> None:
        # finalize re-runs the original post chain over m-row
        # accumulators: gather receive buffers were sized for the full
        # stream, shrink them to nseg·m
        shape = self.shape
        for node in shape.post:
            if isinstance(node, N.PMotion):
                node.out_capacity = shape.g_cap * self.nseg

    def _init_acc(self):
        shape = self.shape
        cols = {f.name: np.zeros((self.nseg, shape.g_cap),
                                 dtype=f.type.np_dtype)
                for f in shape.partial_plan.fields}
        return cols, np.zeros((self.nseg, shape.g_cap), dtype=np.bool_)

    def _make_step(self, mesh, tx, res_specs):
        from cloudberry_tpu.exec.tiled import _AccLeaf

        shape = self.shape
        nseg = self.nseg
        m = shape.g_cap
        mleaf = _AccLeaf()
        mleaf.fields = list(shape.partial_plan.fields)
        msort = N.PSort(mleaf, list(shape.sortnode.keys))
        msort.fields = list(mleaf.fields)
        names = [f.name for f in shape.partial_plan.fields]
        stat_motions = self._stat_motions()

        def step_seg(resident, prelude, tile, tile_n, acc):
            tables = dict(resident)
            tables["$tile"] = _strip_seg(tile)
            plocal = _strip_seg(prelude)
            replace = {id(b): tuple(plocal[i])
                       for i, b in enumerate(shape.builds)}
            low = _DistTileLowerer(tables, nseg, shape.stream,
                                   tile_n.reshape(()), replace,
                                   use_pallas=self._use_pallas, tx=tx,
                                   packed=self._packed)
            pcols, psel = low.lower(shape.partial_plan)
            checks = dict(low.checks)
            srows = _motion_stats(low, stat_motions, nseg)
            acc_cols, acc_sel = _strip_seg(tuple(acc))
            ccols = {n: jnp.concatenate([acc_cols[n], pcols[n]])
                     for n in names}
            csel = jnp.concatenate([acc_sel, psel])
            low2 = _DistReplacingLowerer(
                {}, nseg, {id(mleaf): (ccols, csel)},
                use_pallas=self._use_pallas, tx=tx, packed=self._packed)
            scols, ssel = low2.lower(msort)
            checks.update(low2.checks)
            return _add_seg(({n: scols[n][:m] for n in names},
                             ssel[:m])), _reduce_checks(checks), srows

        return self._jit_step(step_seg, mesh, res_specs)


class DistSortTiledExecutable(DistTiledExecutable):
    """Distributed external sort (tiled.py SortTiledExecutable on the
    mesh): each step is one shard_map program — every segment streams a
    tile of ITS shard through the spine (per-tile collectives included)
    and emits surviving rows plus order-normalized u64 keys. The host
    pools all segments' rows (subsuming the plan's gather), one stable
    key sort is the merge pass, and the chain above the sort applies
    host-side."""

    _what = "distributed external-sort tiled execution"

    def _groups_ceiling(self) -> int:
        return 0  # no accumulator exists to grow

    def _refresh_report(self) -> None:
        super()._refresh_report()
        self.report["mode"] = "sort"

    def _compile(self):
        if self._compiled is not None:
            return self._compiled
        shape = self.shape
        nseg = self.nseg
        live_ids = getattr(self.session, "_live_device_ids", None)
        mesh = segment_mesh(nseg, live_ids)
        from cloudberry_tpu.parallel.transport import (hier_topology,
                                                       make_transport)

        ic = self.session.config.interconnect
        # same two-level selection as the in-memory dist path (see the
        # agg-mode _compile above): stamped motions keep their semantics
        topo = hier_topology(self.session.config, nseg, live_ids)
        tx = make_transport(ic.backend, nseg, chunks=ic.ring_chunks,
                            topo=topo)
        rnames = self._resident_names()
        _, res_specs = prepare_dist_inputs(None, self.session,
                                           names=rnames)

        def prelude_seg(tables):
            low = DistLowerer(tables, nseg, use_pallas=self._use_pallas,
                              tx=tx, packed=self._packed)
            outs = [_add_seg(low.lower_shared(b)) for b in shape.builds]
            return outs, _reduce_checks(low.checks)

        prelude_fn = jax.jit(_shard_map(
            prelude_seg, mesh, (res_specs,), (P(SEG_AXIS), P())))

        sort = shape.sortnode
        kchild = sort.child
        names = [f.name for f in shape.partial_plan.fields]

        def step_seg(resident, prelude, tile, tile_n):
            tables = dict(resident)
            tables["$tile"] = _strip_seg(tile)
            plocal = _strip_seg(prelude)
            replace = {id(b): tuple(plocal[i])
                       for i, b in enumerate(shape.builds)}
            low = _DistTileLowerer(tables, nseg, shape.stream,
                                   tile_n.reshape(()), replace,
                                   use_pallas=self._use_pallas, tx=tx,
                                   packed=self._packed)
            pcols, psel = low.lower(shape.partial_plan)
            n = psel.shape[0]
            keys = []
            for e, asc in sort.keys:
                arr = X._as_column(X._sortable(e, kchild, pcols), n)
                u = K.sort_key_u64(arr)
                keys.append(u if asc else ~u)
            out = {nm: X._as_column(pcols[nm], n) for nm in names}
            return _add_seg((out, psel, keys)), _reduce_checks(low.checks)

        step_fn = jax.jit(_shard_map(
            step_seg, mesh,
            (res_specs, P(SEG_AXIS), P(SEG_AXIS), P(SEG_AXIS)),
            (P(SEG_AXIS), P())))
        self._compiled = (prelude_fn, step_fn)
        return self._compiled

    def _stream_sorted(self):
        """Per-segment tile stream + host merge; returns (sorted child
        columns, sorted normalized keys, n_tiles, recovery ctx) as host
        arrays."""
        from cloudberry_tpu.exec import recovery as R

        ctx = R.begin(self, dist=True)
        if ctx is not None:
            ctx.prepare_dist()
        prelude_fn, step_fn = self._compile()
        shape = self.shape
        resident, _ = prepare_dist_inputs(
            None, self.session, names=self._resident_names())
        if shape.builds:
            prelude, pchecks = prelude_fn(resident)
            X.raise_checks(pchecks)
        else:
            prelude = []
        names = [f.name for f in shape.partial_plan.fields]
        runs: dict[str, list] = {nm: [] for nm in names}
        key_runs: list[list] = [[] for _ in shape.sortnode.keys]
        if ctx is not None:
            runs, key_runs = ctx.restore_runs(runs, key_runs)
        feed = (ctx.feed() if ctx is not None else None) \
            or _dist_tile_feed(shape.stream, self.session, self.tile_rows)
        n_base = ctx.tiles_base if ctx is not None else 0
        n_local = 0
        from cloudberry_tpu.exec.tiled import _TileTimer

        timer = _TileTimer(self.session)
        tracker = _dist_progress_tracker(self, feed, n_base)
        pipe = TP.TilePipe(self.session, TP.effective_window(
            self.session.config, jax.default_backend()))
        # same pipeline wrap as the agg-mode loop: staging off the
        # critical path, consumed-tile accounting unchanged
        stream = SP.maybe_pipeline(iter(feed), self.session.config,
                                   min_depth=pipe.window)
        n_sub = 0

        def _verified(d):
            # materialize one drained-clean tile's run slices, in
            # stream order (the async D2H started at submit); host runs
            # are exactly as-of the drained tile, so no staging needed
            nonlocal n_local
            tile_k, pcols, psel, keys = d.payload
            n_local = tile_k
            tracker.step(tile_k)
            selnp = np.asarray(psel)
            for s in range(self.nseg):
                m = selnp[s]
                for nm in names:
                    runs[nm].append(np.asarray(pcols[nm][s])[m])
                for i, k in enumerate(keys):
                    key_runs[i].append(np.asarray(k[s])[m])
            if ctx is not None:
                ctx.tick(tile_k,
                         lambda: R.runs_payload(runs, key_runs))

        try:
            for tile, tile_ns in stream:
                fault_point("tile_step_dist")
                fault_point("tile_device_lost")
                n_sub += 1
                with timer.step(n_base + n_sub - 1):
                    (pcols, psel, keys), checks = step_fn(
                        resident, prelude, tile, tile_ns)
                    drained = pipe.submit(n_base + n_sub - 1, checks,
                                          (n_sub, pcols, psel, keys))
                for d in drained:
                    _verified(d)
            for d in pipe.drain_all():
                _verified(d)
        finally:
            if pipe.deferred_fail:
                self._deferred_fail = True
            SP.close_feed(stream)
        SP.stamp_report(self.report, stream)
        timer.stamp(self.report)
        pipe.stamp(self.report)
        from cloudberry_tpu.exec.tiled import merge_sorted_runs

        cols, karr = merge_sorted_runs(runs, key_runs,
                                       shape.partial_plan.fields,
                                       len(shape.sortnode.keys))
        return cols, karr, max(n_base + n_local, 1), ctx

    def _run_once(self) -> ColumnBatch:
        _retile_dist(self.shape, self.tile_rows, self.nseg)
        shape = self.shape
        cols, _karr, n_tiles, ctx = self._stream_sorted()
        from cloudberry_tpu.exec.tiled import host_apply_post

        cols = host_apply_post(shape.post_above, cols)
        n_out = len(next(iter(cols.values()))) if cols else 0
        self.report["n_tiles"] = n_tiles
        if ctx is not None:
            ctx.stamp_report(self.report)
        self._publish_report()
        out_node = shape.post_above[0] if shape.post_above \
            else shape.sortnode
        return X.make_batch(out_node, cols,
                            np.ones((n_out,), dtype=bool))


class DistWindowTiledExecutable(DistSortTiledExecutable):
    """Distributed window spill: phase one is the per-segment
    external-sort stream grouped by the stack's common partition keys;
    phase two packs whole partitions into fixed chunks and runs the
    ORIGINAL plan above the stream on ONE device per chunk (gather
    motions lower as identity over the pooled host rows; chunks are
    independent so no mesh is needed)."""

    _what = "distributed windowed tiled execution"

    def _refresh_report(self) -> None:
        super()._refresh_report()
        self.report["mode"] = "window"

    def _chunk_fn(self):
        if getattr(self, "_chunk_compiled", None) is not None:
            return self._chunk_compiled
        from cloudberry_tpu.exec.tiled import _ReplacingLowerer

        shape = self.shape
        cap = self.tile_rows
        pallas = self._use_pallas
        plat = jax.default_backend()

        def run_chunk(chunk_cols, n_valid):
            sel = jnp.arange(cap) < n_valid
            low = _ReplacingLowerer(
                {}, {id(shape.replace_node): (chunk_cols, sel)},
                platform=plat, use_pallas=pallas)
            cols, osel = low.lower(shape.root)
            out = {f.name: cols[f.name] for f in shape.root.fields}
            return out, osel, low.checks

        self._chunk_compiled = jax.jit(run_chunk)
        return self._chunk_compiled

    def _run_once(self) -> ColumnBatch:
        from cloudberry_tpu.exec.tiled import window_chunk_pass

        _retile_dist(self.shape, self.tile_rows, self.nseg)
        shape = self.shape
        self._chunk_compiled = None  # capacity may have changed
        cols, karr, n_tiles, ctx = self._stream_sorted()
        names = [f.name for f in shape.partial_plan.fields]
        final, n_chunks = window_chunk_pass(
            self._chunk_fn(), shape.root, names, cols, karr,
            shape.n_ckeys, self.tile_rows)
        n_out = len(next(iter(final.values()))) if final else 0
        self.report["n_tiles"] = n_tiles
        self.report["n_chunks"] = n_chunks
        if ctx is not None:
            ctx.stamp_report(self.report)
        self._publish_report()
        return X.make_batch(shape.root, final,
                            np.ones((n_out,), dtype=bool))


# -------------------------------------------------------------- tile feed


def _empty_dist_tile(scan: N.PScan, tile_rows: int, nseg: int):
    t = {}
    for phys in scan.column_map:
        t[phys] = np.zeros((nseg, tile_rows), dtype=np.int64)
    for phys in scan.mask_map:
        t[f"$nn:{phys}"] = np.zeros((nseg, tile_rows), dtype=np.bool_)
    return t, np.zeros((nseg,), dtype=np.int64)


def _dist_progress_tracker(exe, feed, n_base: int):
    """Live-progress feeder for a distributed tile loop
    (obs/progress.py): one lane per segment — the loop runs lock-step,
    so the longest shard sets the tile count. A resumed feed
    (_ResumedDistFeed) contributes its remaining per-shard counts and
    the consumed-mask population as the base; the fresh feed derives
    lanes from the counts-only shard layout."""
    from cloudberry_tpu.obs.progress import TileTracker, stream_rows

    session = exe.session
    total = stream_rows(exe.shape.stream, session)
    base_rows = 0
    if hasattr(feed, "counts") and hasattr(feed, "base_mask"):
        lanes = np.asarray(feed.counts)
        base_rows = int(np.asarray(feed.base_mask).sum())
    else:
        try:
            lanes = np.asarray(session.shard_counts(
                exe.shape.stream.table_name))
        except KeyError:
            lanes = np.asarray([total])
    return TileTracker(lanes, exe.tile_rows, n_base=n_base,
                       base_rows=base_rows, rows_total=total)


def _bufpool_charge_dist(session, table: str) -> int:
    bpool = BUF.pool_for(session)
    return bpool.table_bytes(table) if bpool is not None else 0


def _dist_tile_feed(scan: N.PScan, session, tile_rows: int):
    """Yield (tile dict of (nseg, tile_rows) arrays, per-segment valid
    counts). All segments step in lock-step; a segment whose shard ran dry
    contributes masked rows — the SPMD analog of a QE sending EOS while
    its peers still stream. Packed feed tiles resident in the buffer
    pool (exec/bufferpool.py, keyed by tile offset + the shared-tier
    content/epoch tokens) skip the slice-pad-copy work; the pool holds
    HOST arrays on this path — shard_map owns device placement, exactly
    like the pipeline's host-only staging."""
    st = session.sharded_table(scan.table_name)
    nseg, shard_cap = len(st.counts), st.capacity
    bpool = BUF.pool_for(session)
    cols_key = (tuple(sorted(scan.column_map)),
                tuple(sorted(scan.mask_map)))
    log = getattr(session, "stmt_log", None)
    counts = np.asarray(st.counts)
    cols: Optional[dict] = None  # built lazily: an all-hit feed never
    max_rows = int(st.counts.max()) if len(st.counts) else 0
    for off in range(0, max(max_rows, 0), tile_rows):
        n = min(tile_rows, max_rows - off)
        tile_ns = np.clip(counts - off, 0, tile_rows)
        key = None
        if bpool is not None:
            try:
                key = BUF.dist_tile_key(session, scan.table_name,
                                        cols_key, nseg, tile_rows, off)
            except KeyError:  # table dropped mid-plan: fall through
                key = None
        if key is not None:
            ent = bpool.lookup(key, log)
            if ent is not None:
                yield ent["tile"], tile_ns
                continue
        if cols is None:
            cols = {}
            for phys in scan.column_map:
                cols[phys] = np.asarray(st.columns[phys])
            for phys in scan.mask_map:
                vm = st.columns.get(f"$nn:{phys}")
                cols[f"$nn:{phys}"] = (
                    np.asarray(vm) if vm is not None
                    else np.ones((nseg, shard_cap), dtype=np.bool_))
        tile = {}
        for name, arr in cols.items():
            sl = arr[:, off:off + n]
            if n < tile_rows:
                sl = np.concatenate(
                    [sl, np.zeros((nseg, tile_rows - n), dtype=arr.dtype)],
                    axis=1)
            tile[name] = np.ascontiguousarray(sl)
        if key is not None:
            bpool.offer(key, {"tile": tile}, table=scan.table_name,
                        log=log, device=False)
        yield tile, tile_ns
