"""Set-returning table functions — the Function Scan / TableFunction
node analog (reference: src/backend/executor/nodeFunctionscan.c, the
TableFunction executor node).

A table function evaluates HOST-SIDE at bind time — its arguments are
constants, because the one-XLA-program model has no per-row function
scans — and materializes as a TRANSIENT replicated table: every segment
sees the full rows, the General locus the reference gives function
scans, so joins against it need no motion. Rows refresh at every
referencing statement (the FDW re-fetch discipline, storage/fdw.py), so
non-deterministic functions always show current output and the
statement cache invalidates itself through the table version.

``register_table_function(name, fn)`` is the extension hook (with
``register_fdw``, the CustomScan-style surface): fn is any callable
``(*args) -> dict[str, np.ndarray]`` — or a bare ndarray, which names
its single column after the function. Strings may come as object
arrays; they dictionary-encode here.

Built-ins: ``generate_series(start, stop [, step])`` (inclusive stop,
PG semantics).
"""

from __future__ import annotations

from typing import Callable

import numpy as np

from cloudberry_tpu import types as T
from cloudberry_tpu.columnar.dictionary import StringDictionary
from cloudberry_tpu.types import Schema

_FUNCS: dict[str, Callable] = {}


def register_table_function(name: str, fn: Callable) -> None:
    _FUNCS[name.lower()] = fn


def lookup(name: str):
    return _FUNCS.get(name.lower())


def known_functions() -> list[str]:
    return sorted(_FUNCS)


def _field_type(name: str, arr: np.ndarray):
    k = arr.dtype.kind
    if k == "b":
        return T.BOOL
    if k in "iu":
        return T.INT64 if arr.dtype.itemsize > 4 else T.INT32
    if k == "f":
        return T.FLOAT64
    if k in "OU":
        return T.STRING
    raise ValueError(
        f"table function column {name!r}: unsupported dtype {arr.dtype}")


# bind-time materialization guards: the binder runs BEFORE admission,
# so table functions get their own host-memory cap and a bounded pool
# of transient tables (module attrs — adjustable by embedders)
MAX_RESULT_BYTES = 1 << 30
MAX_TRANSIENT_TABLES = 16


def begin_statement(catalog) -> None:
    """Reset the CURRENT THREAD's per-statement pin set. Tables
    materialized while one statement binds must survive until that
    statement plans — FIFO eviction alone would drop an early function
    table when a single query references >= MAX_TRANSIENT_TABLES
    distinct calls, leaving a later scan pointing at a removed catalog
    entry. Pins are keyed by thread because a shared-session server
    binds concurrent SELECTs on one catalog under a shared read lock;
    a global set would let statement B's reset unpin statement A's
    tables mid-bind. Entries for finished threads are pruned here so a
    dead thread's pins cannot exhaust the pool."""
    import threading

    pins = catalog.__dict__.setdefault("_tf_pinned", {})
    live = {t.ident for t in threading.enumerate()}
    for tid in list(pins):
        if tid not in live:
            pins.pop(tid, None)
    pins[threading.get_ident()] = set()


def _pin(catalog, tname: str) -> None:
    import threading

    pins = catalog.__dict__.setdefault("_tf_pinned", {})
    pins.setdefault(threading.get_ident(), set()).add(tname)


def _evict_transients(catalog) -> None:
    pins = getattr(catalog, "_tf_pinned", None) or {}
    # honor EVERY live statement's pins, not just this thread's
    pinned = set().union(*pins.values()) if pins else frozenset()
    total = sum(1 for n in catalog.tables if n.startswith("$tf_"))
    tfs = [n for n in catalog.tables
           if n.startswith("$tf_") and n not in pinned]
    while total >= MAX_TRANSIENT_TABLES:
        if not tfs:
            raise ValueError(
                "statement references more than "
                f"{MAX_TRANSIENT_TABLES} distinct table-function "
                "results (the transient-table pool size); raise "
                "cloudberry_tpu.exec.tablefunc.MAX_TRANSIENT_TABLES")
        # FIFO (dict preserves insertion order). No SQL name can spell a
        # $-prefixed table, so direct removal needs no ddl bump
        del catalog.tables[tfs.pop(0)]
        total -= 1


def materialize(catalog, fname: str, fn: Callable, vals: list) -> str:
    """Run the function and (re)materialize its transient table; returns
    the catalog name."""
    from cloudberry_tpu.catalog.catalog import DistributionPolicy

    fname = fname.lower()
    cols = fn(*vals)
    if isinstance(cols, np.ndarray):
        cols = {fname: cols}
    # SQL identifiers lowercase in the lexer: an uppercase column name
    # would be unreachable from any query
    cols = {k.lower(): np.asarray(v) for k, v in cols.items()}
    if not cols:
        raise ValueError(f"table function {fname!r} returned no columns")
    ns = {len(v) for v in cols.values()}
    if len(ns) != 1:
        raise ValueError(
            f"table function {fname!r}: ragged column lengths {sorted(ns)}")
    total = 0
    for v in cols.values():
        if v.dtype.kind in "OU":
            # object arrays report pointer size as nbytes; measure the
            # actual string payload, stopping once the cap is blown
            for x in v:
                total += len(str(x))
                if total > MAX_RESULT_BYTES:
                    break
        else:
            total += v.nbytes
        if total > MAX_RESULT_BYTES:
            raise ValueError(
                f"table function {fname!r}: result exceeds the "
                f"{MAX_RESULT_BYTES >> 20} MiB cap — function rows "
                "materialize host-side at bind time")

    data: dict[str, np.ndarray] = {}
    dicts: dict[str, StringDictionary] = {}
    fields = []
    for cname, arr in cols.items():
        t = _field_type(cname, arr)
        if t is T.STRING:
            d = StringDictionary()
            data[cname] = d.encode(arr.astype(object))
            dicts[cname] = d
        else:
            data[cname] = arr
        fields.append((cname, t))

    tname = "$tf_" + fname + "_" + format(
        abs(hash((fname,) + tuple(map(repr, vals)))) % (1 << 40), "x")
    schema = Schema.of(**dict(fields))
    t = catalog.tables.get(tname)
    if t is not None and [(f.name, f.type) for f in t.schema.fields] != \
            [(f.name, f.type) for f in schema.fields]:
        # the function was re-registered with a different output shape:
        # the old transient table's schema would lie to the scan
        del catalog.tables[tname]
        t = None
    if t is not None:
        # refresh the FIFO position: a reused table must not be the next
        # eviction victim while the current statement still binds it
        catalog.tables[tname] = catalog.tables.pop(tname)
    if t is None:
        _evict_transients(catalog)
        t = catalog.create_table(tname, schema,
                                 DistributionPolicy.replicated(),
                                 durable=False, bump=False)
        # statements over function rows never enter the statement cache
        # (session._any_external): the function re-runs per statement,
        # like a foreign table's re-fetch
        t._tablefunc = True
    _pin(catalog, tname)  # current statement's bind must not evict it
    t._loading = True  # ephemeral: function rows never persist
    try:
        t.set_data(data, dicts)
    finally:
        t._loading = False
    return tname


_SERIES_CAP = 100_000_000


def generate_series(start, stop, step=1):
    if start is None or stop is None or step is None:
        # strict function, NULL argument -> zero rows (PG semantics)
        return {"generate_series": np.zeros(0, dtype=np.int64)}
    for v in (start, stop, step):
        if float(v) != int(v):
            raise ValueError("generate_series: integer arguments required")
    start, stop, step = int(start), int(stop), int(step)
    if step == 0:
        raise ValueError("generate_series: step must not be zero")
    count = max(0, (stop - start) // step + 1)
    if count > _SERIES_CAP:
        raise ValueError(
            f"generate_series: {count} rows exceeds the cap {_SERIES_CAP}")
    end = stop + (1 if step > 0 else -1)
    return {"generate_series": np.arange(start, end, step,
                                         dtype=np.int64)}


register_table_function("generate_series", generate_series)
