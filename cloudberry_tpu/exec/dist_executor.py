"""Distributed executor: the plan as ONE SPMD program over the segment mesh.

The reference executes a distributed plan as N OS processes per slice wired
by a socket interconnect (gangs + cdbmotion + ic_udpifc); here the whole
multi-segment plan is a single ``shard_map`` program over a
``jax.sharding.Mesh`` — each mesh slot is a segment, and Motion lowers to
XLA collectives on the ``seg`` axis:

- GATHER / BROADCAST → ``lax.all_gather``  (BROADCAST motion)
- HASH (redistribute) → on-device bucketing + ``lax.all_to_all``, with
  per-destination bucket capacity as flow control (ic_udpifc.c:3018 analog):
  bucket overflow is a detected error, not a drop.

Routing uses jump_consistent_hash over the same column hash as load-time
placement (session.sharded_table), so scan-colocated joins need no motion at
all — the planner relies on that (plan/distribute.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from cloudberry_tpu.columnar.batch import ColumnBatch
from cloudberry_tpu.exec import executor as X
from cloudberry_tpu.exec import kernels as K
from cloudberry_tpu.exec.expr_compile import compile_expr
from cloudberry_tpu.parallel.mesh import SEG_AXIS, segment_mesh
from cloudberry_tpu.plan import nodes as N
from cloudberry_tpu.utils import hashing
from cloudberry_tpu.utils.faultinject import fault_point


def prepare_dist_inputs(plan: N.PlanNode, session, names=None):
    """(inputs, in_specs) for every scanned table: partitioned columns as
    (nseg, cap) arrays split on the seg axis, replicated tables whole.
    ``names`` overrides the table set (tiled execution keeps the streamed
    table out of the resident inputs)."""
    inputs = {}
    in_specs = {}
    if plan is not None:
        # cached sorted-build join indexes ride as extra program inputs
        # (exec/joinindex.py): 'shard'-mode arrays split on the segment
        # axis, whole-table/gathered ones replicated. Tiled callers pass
        # plan=None and the join lowering falls back to its in-program
        # argsort.
        from cloudberry_tpu.exec.joinindex import dist_join_index_inputs

        jix_in, jix_specs = dist_join_index_inputs(plan, session)
        inputs.update(jix_in)
        in_specs.update(jix_specs)
    if names is None:
        names = sorted({s.table_name for s in X.scans_of(plan)})
    for name in names:
        st = session.sharded_table(name)
        if st.replicated:
            inputs[name] = {"$cols": dict(st.columns),
                            "$nrows": np.full(1, st.counts[0])}
            in_specs[name] = {"$cols": {c: P() for c in st.columns},
                              "$nrows": P()}
        else:
            inputs[name] = {"$cols": dict(st.columns),
                            "$nrows": st.counts}
            in_specs[name] = {"$cols": {c: P(SEG_AXIS, None)
                                        for c in st.columns},
                              "$nrows": P(SEG_AXIS)}
    return inputs, in_specs


def compile_distributed(plan: N.PlanNode, session, param_keys=None,
                        instrument=False):
    """Build the jitted SPMD program once; reusable across calls (the
    prepared-statement analog — inputs are re-prepared per call from the
    session's sharded-table cache). ``param_keys`` (generic plans,
    sched/paramplan.py) adds a replicated "$params" input: "$prm<slot>"
    scalars every segment reads identically, so literal rebinding never
    retraces the SPMD program. ``instrument=True`` (EXPLAIN ANALYZE's
    pipeline path) records per-node row counts into the existing
    replicated stats channel — partitioned-node counts psum across
    segments, replicated nodes report segment 0's — so the instrumented
    program is this same entry point's program, not a side path's."""
    from cloudberry_tpu.parallel.transport import (hier_topology,
                                                   make_transport)

    nseg = session.config.n_segments
    live_ids = getattr(session, "_live_device_ids", None)
    mesh = segment_mesh(nseg, live_ids)
    ic = session.config.interconnect
    # topology-aware two-level motion: the host topology re-derives from
    # the LIVE device list here, so an epoch flip (expand/shrink/
    # failover) re-splits collectives the moment the new epoch's first
    # program compiles — and the shared cache tier keys programs by
    # topology epoch, so a stale split can never serve post-cutover
    topo = hier_topology(session.config, nseg, live_ids)
    tx = make_transport(ic.backend, nseg, chunks=ic.ring_chunks,
                        topo=topo)
    packed = ic.packed_wire
    _, in_specs = prepare_dist_inputs(plan, session)
    if param_keys:
        in_specs["$params"] = {k: P() for k in param_keys}
    X.count_compile(session)
    lowerer_cls = _InstrumentedDistLowerer if instrument else DistLowerer

    def seg_fn(tables):
        low = lowerer_cls(tables, nseg, tx=tx, packed=packed,
                          params=tables.get("$params"))
        cols, sel = low.lower(plan)
        out = {f.name: cols[f.name][None] for f in plan.fields}
        # reduce checks to replicated scalars (any segment tripped) so
        # every HOST can read them — per-seg shards are not addressable
        # across processes on a multi-host mesh
        checks = {
            k: tx.psum(jnp.asarray(v).astype(jnp.int32), SEG_AXIS) > 0
            for k, v in low.checks.items()}
        # motion stats (already pmax-reduced, replicated): the observed
        # per-destination bucket demand each redistribute actually saw —
        # the capacity-ladder promotion reads these host-side
        return out, sel[None], checks, dict(low.stats)

    return jax.jit(_shard_map(seg_fn, mesh, (in_specs,),
                              _out_specs_like(plan)))


def stat_node_ids(plan: N.PlanNode) -> tuple:
    """Ordered ids of the plan's stats-bearing nodes (redistributes,
    then runtime filters, document order). The rung-program cache stores
    the TRACED plan's tuple so that a signature-equal plan reusing the
    compiled program can alias its own nodes onto the stats keys — the
    telemetry keys embed trace-time node ids, and without the alias a
    cache hit would silently drop the feedback loop's observations."""
    red = tuple(id(n) for n in X.all_nodes(plan)
                if isinstance(n, N.PMotion) and n.kind == "redistribute")
    rf = tuple(id(n) for n in X.all_nodes(plan)
               if isinstance(n, N.PRuntimeFilter))
    return (red, rf)


def record_motion_stats(plan: N.PlanNode, stats: dict,
                        session=None) -> None:
    """Pin each redistribute's observed global bucket demand onto its
    motion node (``_observed_bucket``): on overflow the retry promotes
    straight to the rung that fits instead of probing rung by rung.
    Runtime-filter row counts pin the same way (``_jf_pre``/``_jf_post``),
    and the per-destination demand vector pins as ``_seg_rows`` with its
    derived max/mean ``_skew_ratio`` — the skew telemetry EXPLAIN
    ANALYZE's motion annotations render. With a ``session``, skew also
    feeds the engine registry (obs histograms + the ``skew_events``
    counter past ``config.obs.skew_ratio``). Engine-counter accumulation
    for join filters lives in record_jf_counters — called separately,
    only once raise_checks passed."""
    import re

    # redistribute-only by construction; the kind filter also guards the
    # stale-id aliasing hazard when the program came from a rung-cached
    # executable of an equivalent, since-collected plan (same guard as
    # grow_expansion's id-match path)
    motions = {id(n): n for n in X.all_nodes(plan)
               if isinstance(n, N.PMotion) and n.kind == "redistribute"}
    filters = {id(n): n for n in X.all_nodes(plan)
               if isinstance(n, N.PRuntimeFilter)}
    # program reused from an equivalent traced plan (_rung_executable):
    # admit the TRACED ids as aliases for this plan's same-ordered nodes.
    # A live id is never overwritten — if a traced id happens to collide
    # with a current node's id, the kind filter + first-writer-wins keeps
    # the pre-existing aliasing guarantee.
    alias = getattr(plan, "_stat_id_alias", None)
    if alias:
        for old, new in alias.items():
            if new in motions and old not in motions:
                motions[old] = motions[new]
            elif new in filters and old not in filters:
                filters[old] = filters[new]
    for key, v in stats.items():
        m = re.search(r"required bucket \(node (\d+)\)", key)
        if m is not None:
            node = motions.get(int(m.group(1)))
            if node is not None:
                node._observed_bucket = int(np.asarray(v))
            continue
        m = re.search(r"required host bucket \(node (\d+)\)", key)
        if m is not None:
            node = motions.get(int(m.group(1)))
            if node is not None:
                node._observed_host_bucket = int(np.asarray(v))
            continue
        m = re.search(r"seg rows \(node (\d+)\)", key)
        if m is not None:
            node = motions.get(int(m.group(1)))
            if node is not None:
                node._seg_rows = np.asarray(v).astype(np.int64)
            continue
        m = re.search(r"join_filter (pre|post) \(node (\d+)\)", key)
        if m is not None:
            node = filters.get(int(m.group(2)))
            if node is not None:
                which = "_jf_pre" if m.group(1) == "pre" else "_jf_post"
                setattr(node, which, int(np.asarray(v)))
    _record_skew(motions.values(), session)


def _record_skew(motions, session) -> None:
    """Per-motion skew observability (the capacity plane, ISSUE 12):
    from each redistribute's per-destination demand vector derive the
    max/mean skew ratio, record rows-per-segment and wire-bytes-per-
    segment histograms, and bump ``skew_events`` when a shuffle crosses
    ``config.obs.skew_ratio`` — hot destinations are the binding
    constraint the rung ladder pays for, and they must be visible in
    ``meta "metrics"`` before they become overflow retries."""
    from cloudberry_tpu.obs.capacity import _wire_row_bytes

    log = getattr(session, "stmt_log", None) if session is not None \
        else None
    threshold = float(session.config.obs.skew_ratio) \
        if session is not None else 0.0
    for node in motions:
        rows = getattr(node, "_seg_rows", None)
        if rows is None:
            continue
        total = int(rows.sum())
        if total <= 0 or rows.shape[0] == 0:
            node._skew_ratio = None
            continue
        mean = total / rows.shape[0]
        ratio = float(rows.max() / mean)
        node._skew_ratio = ratio
        # per-HOST skew next to per-segment: a host-skewed shuffle is
        # exactly the case the two-level exchange makes WORSE (one host
        # pair's block rung pads every host pair), so it must alarm in
        # the same place segment skew does
        hrows = _host_rows(rows, session)
        if hrows is not None:
            node._host_rows = hrows
            node._host_skew_ratio = float(
                hrows.max() / (total / hrows.shape[0]))
        else:
            node._host_skew_ratio = None
        if log is None or not log.obs_enabled:
            continue
        reg = log.registry
        reg.observe("motion_skew_ratio", ratio)
        reg.observe("motion_seg_rows_max", int(rows.max()))
        reg.observe("motion_seg_wire_bytes_max",
                    int(rows.max()) * _wire_row_bytes(node))
        if threshold > 0 and ratio >= threshold:
            log.bump("skew_events")
        if node._host_skew_ratio is not None:
            reg.observe("motion_host_skew_ratio", node._host_skew_ratio)
            reg.observe("motion_host_rows_max", int(hrows.max()))
            if threshold > 0 and node._host_skew_ratio >= threshold:
                log.bump("host_skew_events")


def _host_rows(seg_rows: np.ndarray, session) -> np.ndarray | None:
    """Per-destination-HOST row demand from the per-segment vector —
    None on single-host (or host-ambiguous) meshes. Uses the same
    HostTopology derivation the motion layer splits over (including the
    CBTPU_FORCE_HOSTS simulation), so the telemetry describes the links
    the bytes would actually cross."""
    from cloudberry_tpu.parallel.mesh import host_topology

    try:
        topo = host_topology(
            seg_rows.shape[0],
            getattr(session, "_live_device_ids", None)
            if session is not None else None)
    except Exception:
        return None
    if topo.n_hosts < 2:
        return None
    out = np.zeros(topo.n_hosts, dtype=np.int64)
    for h, segs in enumerate(topo.segs_by_host):
        out[h] = sum(int(seg_rows[s]) for s in segs
                     if s < seg_rows.shape[0])
    return out


def record_jf_counters(stats: dict, log) -> None:
    """Accumulate runtime-filter row counts on the engine counters
    (jf_rows_in / jf_rows_out) — the observed-reduction observability
    bench.py and ic_bench --join-filter read. Call AFTER raise_checks:
    an overflowed attempt that grow_expansion retries must not count its
    probe rows twice."""
    import re

    if log is None:
        return
    for key, v in stats.items():
        m = re.search(r"join_filter (pre|post)", key)
        if m is not None:
            log.bump("jf_rows_in" if m.group(1) == "pre"
                     else "jf_rows_out", int(np.asarray(v)))


def execute_distributed(plan: N.PlanNode, session,
                        fn=None) -> ColumnBatch:
    if fn is None:
        fn = compile_distributed(plan, session)
    inputs, _ = prepare_dist_inputs(plan, session)
    fault_point("dist_execute_start")
    from cloudberry_tpu.obs import trace as OT

    with OT.span("launch", mode="dist"), \
            OT.device_annotation("launch-dist"):
        cols, sel, checks, stats = fn(inputs)
    record_motion_stats(plan, stats, session=session)
    X.raise_checks(checks)
    record_jf_counters(stats, getattr(session, "stmt_log", None))
    from cloudberry_tpu.plan.feedback import fold_plan

    fold_plan(session, plan)
    # every segment computed the (gathered) final result; read the first
    # shard THIS HOST can address (on a multi-host mesh, segment 0 may
    # live on another process — any local copy is identical post-gather)
    host_cols = {k: _local_row(v) for k, v in cols.items()}
    host_sel = _local_row(sel)
    return X.make_batch(plan, host_cols, host_sel)


def _local_row(v) -> np.ndarray:
    if hasattr(v, "is_fully_addressable") and not v.is_fully_addressable:
        shards = v.addressable_shards
        if not shards:  # guarded up front by segment_mesh's host check
            raise X.ExecError(
                "this host owns no segment in the mesh and cannot read "
                "the result")
        return np.asarray(shards[0].data)[0]
    return np.asarray(v)[0]


def _out_specs_like(plan: N.PlanNode):
    cols_spec = {f.name: P(SEG_AXIS) for f in plan.fields}
    # checks and motion stats reduce to replicated scalars (P()) —
    # readable on every host
    return (cols_spec, P(SEG_AXIS), P(), P())


def _shard_map(f, mesh, in_specs, out_specs):
    """shard_map across jax versions (module move + check_rep rename)."""
    try:
        sm = jax.shard_map
    except AttributeError:
        from jax.experimental.shard_map import shard_map as sm
    for kw in ({"check_vma": False}, {"check_rep": False}, {}):
        try:
            return sm(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)
        except TypeError:
            continue
    raise RuntimeError("no compatible shard_map signature")


class DistLowerer(X.Lowerer):
    def __init__(self, tables, nseg: int, platform: str | None = None,
                 use_pallas: bool = False, tx=None, packed: bool = True,
                 params=None):
        super().__init__(tables, platform=platform, use_pallas=use_pallas,
                         params=params)
        self.nseg = nseg
        # motion transport (ic_modules.c vtable analog): XLA-native
        # collectives or ppermute ring compositions
        if tx is None:
            from cloudberry_tpu.parallel.transport import XlaCollectives

            tx = XlaCollectives()
        self.tx = tx
        # packed wire format (kernels.wire_layout): one collective per
        # motion; False = legacy one-collective-per-column (parity path)
        self.packed = packed

    def scan(self, node: N.PScan):
        if node.table_name == "$dual":
            return {}, jnp.ones((1,), dtype=jnp.bool_)
        t = self.tables[node.table_name]
        cols = {}
        for phys, out in list(node.column_map.items()) + [
                (f"$nn:{p}", o) for p, o in node.mask_map.items()]:
            arr = t["$cols"][phys]
            if arr.ndim == 2:      # partitioned: (1, cap) block inside smap
                arr = arr[0]
            if arr.shape[0] < node.capacity:
                arr = jnp.zeros((node.capacity,), dtype=arr.dtype)
            cols[out] = arr
        n = t["$nrows"].reshape(())
        sel = jnp.arange(node.capacity) < n
        return cols, sel

    def global_any(self, x):
        local = jnp.any(x).astype(jnp.int32)
        return self.tx.psum(local, SEG_AXIS) > 0

    def runtime_filter(self, node):
        """Semi-join pushdown (nodeRuntimeFilter.c analog) before the
        probe's redistribute. mode='exact': all-gather the PACKED u64
        build keys (keys only — the cheapest complete collective) and
        sorted-membership-test the probe rows. mode='digest': all-gather
        only a per-key u64 min/max + bloom-bitmap digest (one tiny
        collective regardless of build size; bloom false positives let
        extra rows through, the join stays exact). Packing ranges reduce
        globally so every segment packs identically."""
        if getattr(node, "mode", "exact") == "digest":
            return self._digest_filter(node)
        pcols, psel = self.lower(node.child)
        bcols, bsel = self.lower_shared(node.build)
        bkeys = [self.expr(k, bcols) for k in node.build_keys]
        pkeys = [self.expr(k, pcols) for k in node.probe_keys]
        ranges = []
        for k in bkeys:
            u = K.sort_key_u64(k)
            lo = jnp.min(jnp.where(bsel, u, K._U64_MAX))
            hi = jnp.max(jnp.where(bsel, u, jnp.uint64(0)))
            lo = jnp.min(self.tx.all_gather(lo[None], SEG_AXIS))
            hi = jnp.max(self.tx.all_gather(hi[None], SEG_AXIS))
            span = jnp.maximum(hi - lo, jnp.uint64(0)) + jnp.uint64(1)
            ranges.append((lo, span))
        kb = jnp.where(bsel, K.pack_with_ranges(bkeys, ranges), K._U64_MAX)
        kp = K.pack_with_ranges(pkeys, ranges)
        big = K._U64_MAX
        if node.pack_bits == 32:
            # stats-proven narrow keys halve the all-gathered bytes too
            kb, kp, big = K.downcast32(kb), K.downcast32(kp), K._U32_MAX
        kb_all = self.tx.all_gather(kb, SEG_AXIS)
        kb_sorted = jnp.sort(kb_all)
        pos = jnp.clip(jnp.searchsorted(kb_sorted, kp), 0,
                       kb_sorted.shape[0] - 1)
        hit = (kb_sorted[pos] == kp) & (kp != big)
        self._filter_stats(node, psel, psel & hit)
        return pcols, psel & hit

    def _digest_filter(self, node):
        """Digest-mode runtime filter: each segment builds a local digest
        — per key column the u64 [lo, hi] (as u32 word pairs) plus the
        bloom bitmap words — ships it in ONE all_gather, then reduces
        (min/max/OR) so every segment holds the GLOBAL digest. Probe rows
        outside any key's range or absent from the bloom drop before the
        shuffle; min/max is exact, bloom errs only toward keeping rows."""
        pcols, psel = self.lower(node.child)
        bcols, bsel = self.lower_shared(node.build)
        bus = [K.sort_key_u64(self.expr(k, bcols))
               for k in node.build_keys]
        pus = [K.sort_key_u64(self.expr(k, pcols))
               for k in node.probe_keys]
        bits = K.bloom_bits_pow2(node.bloom_bits)
        kk = max(node.bloom_k, 1)

        def u64_words(x):
            return jnp.stack([(x & jnp.uint64(0xFFFFFFFF)),
                              (x >> jnp.uint64(32))]).astype(jnp.uint32)

        parts = []
        for u in bus:
            lo = jnp.min(jnp.where(bsel, u, K._U64_MAX))
            hi = jnp.max(jnp.where(bsel, u, jnp.uint64(0)))
            parts += [u64_words(lo), u64_words(hi)]
        parts.append(K.bloom_build(bus, bsel, bits, kk))
        digest = jnp.concatenate(parts)            # (4·nkeys + bits/32,)
        D = digest.shape[0]
        topo = getattr(self.tx, "hier_topo", None)
        if topo is not None and self.nseg // topo.n_hosts > 1:
            # two-level digest: fold the HOST's digests locally (min/
            # max/OR are order-insensitive-exact, so the fold is
            # bit-identical to the flat reduction) and exchange ONE
            # combined digest per host over DCN — the "one partial per
            # host instead of one per segment" motion for digests
            S = self.nseg // topo.n_hosts
            local = self.tx.intra_all_gather(digest, SEG_AXIS) \
                .reshape(S, D)
            host_digest = _digest_fold(local, len(bus))
            gathered = self.tx.host_ring_exchange(host_digest, SEG_AXIS)
        else:
            # ONE tiny collective for the whole digest (tiled all_gather
            # concatenates: reshape back to per-segment rows)
            gathered = self.tx.all_gather(digest, SEG_AXIS) \
                .reshape(self.nseg, D)

        def seg_u64(col0):
            w = gathered[:, col0:col0 + 2].astype(jnp.uint64)
            return w[:, 0] | (w[:, 1] << jnp.uint64(32))

        hit = psel
        for i, u in enumerate(pus):
            glo = jnp.min(seg_u64(4 * i))
            ghi = jnp.max(seg_u64(4 * i + 2))
            hit = hit & (u >= glo) & (u <= ghi)
        off = 4 * len(bus)
        bloom = gathered[0, off:]
        for s in range(1, int(gathered.shape[0])):
            bloom = bloom | gathered[s, off:]
        hit = hit & K.bloom_test(bloom, pus, bits, kk)
        self._filter_stats(node, psel, psel & hit)
        return pcols, psel & hit

    def _filter_stats(self, node, pre, post):
        """Replicated observability: global probe rows before/after the
        filter (psum over segments) — the host pins them on the plan node
        (record_motion_stats) for EXPLAIN ANALYZE consumers, bench.py's
        join_filter record, and ic_bench --join-filter."""
        self.stats[f"join_filter pre (node {id(node)})"] = self.tx.psum(
            jnp.sum(pre.astype(jnp.int32)), SEG_AXIS)
        self.stats[f"join_filter post (node {id(node)})"] = self.tx.psum(
            jnp.sum(post.astype(jnp.int32)), SEG_AXIS)

    def motion(self, node: N.PMotion):
        cols, sel = self.lower_shared(node.child)
        if node.pre_compact:
            cols, sel, n = K.compact(cols, sel, node.pre_compact)
            self.checks[
                f"pre-gather compaction truncated rows (node {id(node)}): "
                "local top-N emitted more than its limit"] = \
                n > node.pre_compact
        if node.kind in ("gather", "broadcast"):
            if self.packed and cols:
                # one collective for the whole row set: every column plus
                # the validity mask rides ONE (cap, W) uint32 buffer
                layout = K.wire_layout({n: c.dtype
                                        for n, c in cols.items()})
                buf = K.pack_wire(cols, sel, layout)
                recv = self.tx.all_gather(buf, SEG_AXIS)
                return K.unpack_wire(recv, layout)
            out = {n: self.tx.all_gather(c, SEG_AXIS)
                   for n, c in cols.items()}
            osel = self.tx.all_gather(sel, SEG_AXIS)
            return out, osel
        if node.kind == "redistribute":
            return self._redistribute(node, cols, sel)
        raise X.ExecError(f"motion kind {node.kind}")

    def _use_hier(self, node: N.PMotion) -> bool:
        """Two-level exchange for this redistribute? Needs the
        hierarchical transport (topology gate passed at compile), the
        planner's host stamps, agreement between the stamped and live
        host grouping (an epoch flip between plan and compile replans —
        this is the belt-and-braces), the packed wire, and u32-address-
        able slots (the route-word contract)."""
        topo = getattr(self.tx, "hier_topo", None)
        return (self.packed and topo is not None
                and node.host_bucket_cap > 0
                and node.hier_hosts == topo.n_hosts
                and self.nseg % topo.n_hosts == 0
                and self.nseg * node.bucket_cap < 1 << 31)

    def _host_combine(self, node: N.PMotion, cols, sel):
        """Host-local combine of pre-aggregable motion inputs (agg
        partials): gather the HOST's rows over ICI (packed wire), merge
        partials by group key with the stamped order-insensitive-exact
        merge funcs, and keep the combined rows on ONE segment per host
        — the following exchange then ships one partial per (host,
        group) over DCN instead of one per (segment, group). Every
        segment of the host computes the identical combine; the lane-0
        selection mask is what de-duplicates, so no extra collective."""
        key_names, merges = node.combine_spec
        layout = K.wire_layout({n: c.dtype for n, c in cols.items()})
        buf = K.pack_wire(cols, sel, layout)
        hb = self.tx.intra_all_gather(buf, SEG_AXIS)     # (S*cap, W)
        hcols, hsel = K.unpack_wire(hb, layout)
        specs = [K.AggSpec(func, name) for name, func in merges]
        vals = {name: hcols[name] for name, _ in merges}
        out_keys, out_aggs, out_sel, _ = K.group_aggregate(
            {k: hcols[k] for k in key_names}, vals, specs, hsel,
            out_capacity=hb.shape[0])
        out = dict(out_keys)
        out.update(out_aggs)
        # group_aggregate widens some outputs (counts to int64); the
        # motion's schema is the contract — restore each column's dtype
        out = {n: v.astype(cols[n].dtype) for n, v in out.items()}
        S = self.nseg // node.hier_hosts
        t = jax.lax.axis_index(SEG_AXIS) % S
        return out, out_sel & (t == 0)

    def _redistribute(self, node: N.PMotion, cols, sel):
        if self._use_hier(node) and node.host_combine \
                and node.combine_spec and cols:
            cols, sel = self._host_combine(node, cols, sel)
        nseg, B = self.nseg, node.bucket_cap
        keys = [compile_expr(k)(cols) for k in node.hash_keys]
        h = hashing.hash_columns_jnp(keys)
        dest = hashing.jump_consistent_hash_jnp(h, nseg)
        dest = jnp.where(sel, dest, nseg)  # invalid rows → dropped bucket

        counts = jax.ops.segment_sum(sel.astype(jnp.int32), dest,
                                     num_segments=nseg + 1)[:nseg]
        self.checks[
            f"redistribute overflow: a destination bucket exceeded capacity "
            f"{B} (node {id(node)}); raise "
            f"config.interconnect.capacity_factor"] = (counts > B).any()
        # observed global bucket demand (replicated): the host reads it
        # after the run so an overflow promotes DIRECTLY to the capacity
        # rung that fits — one retry, not a probe up the ladder
        self.stats[f"required bucket (node {id(node)})"] = \
            self.tx.pmax(jnp.max(counts), SEG_AXIS)
        # per-destination GLOBAL demand (replicated vector): the same
        # psum the rung adaptation rides, promoted to skew telemetry —
        # the host derives rows-per-segment / wire-bytes-per-segment
        # skew ratios (max/mean) from it (record_motion_stats)
        self.stats[f"seg rows (node {id(node)})"] = \
            self.tx.psum(counts, SEG_AXIS)

        order = jnp.argsort(dest)
        sorted_dest = dest[order]
        start = jnp.searchsorted(sorted_dest, jnp.arange(nseg))
        rank = jnp.arange(dest.shape[0]) - start[
            jnp.clip(sorted_dest, 0, nseg - 1)]
        valid = (sorted_dest < nseg) & (rank < B)
        slot = jnp.where(valid, sorted_dest * B + rank, nseg * B)

        if self.packed and cols:
            # pack once, scatter rows into their destination buckets,
            # ship ONE (nseg, B, W) buffer; unfilled slots stay all-zero,
            # which unpacks as invalid — the validity mask needs no
            # separate collective
            layout = K.wire_layout({n: c.dtype for n, c in cols.items()})
            pbuf = K.pack_wire(cols, sel, layout)
            buf = jnp.zeros((nseg * B, layout.width), dtype=jnp.uint32)
            buf = buf.at[slot].set(pbuf[order], mode="drop")
            if self._use_hier(node):
                # two-level exchange: intra-host re-bucket by dest host,
                # ONE aggregated DCN hop at the host rung, intra-host
                # scatter — bit-identical recv buffer by construction
                HB = node.host_bucket_cap
                recv, hostdem = self.tx.hier_all_to_all(
                    buf.reshape(nseg, B, layout.width), SEG_AXIS, HB)
                self.checks[
                    f"host bucket overflow: a host-pair block exceeded "
                    f"capacity {HB} (node {id(node)}); the two-level "
                    "retry promotes the host rung"] = (hostdem > HB).any()
                # observed host-pair demand (replicated): the host rung
                # ladder's one-retry promotion feed, like bucket_cap's
                self.stats[f"required host bucket (node {id(node)})"] = \
                    self.tx.pmax(jnp.max(hostdem), SEG_AXIS)
            else:
                recv = self.tx.all_to_all(
                    buf.reshape(nseg, B, layout.width), SEG_AXIS)
            return K.unpack_wire(recv.reshape(nseg * B, layout.width),
                                 layout)

        out = {}
        for name, c in cols.items():
            buf = jnp.zeros((nseg * B,), dtype=c.dtype)
            buf = buf.at[slot].set(c[order], mode="drop")
            shaped = buf.reshape(nseg, B)
            recv = self.tx.all_to_all(shaped, SEG_AXIS)
            out[name] = recv.reshape(nseg * B)
        selbuf = jnp.zeros((nseg * B,), dtype=jnp.bool_)
        selbuf = selbuf.at[slot].set(valid, mode="drop")
        recv_sel = self.tx.all_to_all(selbuf.reshape(nseg, B),
                                      SEG_AXIS)
        return out, recv_sel.reshape(nseg * B)


def _digest_fold(rows: "jnp.ndarray", nkeys: int) -> "jnp.ndarray":
    """Combine (P, D) stacked runtime-filter digests into one (D,)
    digest: per key the u64 [lo, hi] fold (min/max over the u32 word
    pairs) and the bitwise OR of the bloom words. Order-insensitive and
    exact — the host-local fold produces the same global digest the
    flat per-segment reduction would."""
    def col_u64(c0):
        w = rows[:, c0:c0 + 2].astype(jnp.uint64)
        return w[:, 0] | (w[:, 1] << jnp.uint64(32))

    def u64_words(x):
        return jnp.stack([(x & jnp.uint64(0xFFFFFFFF)),
                          (x >> jnp.uint64(32))]).astype(jnp.uint32)

    parts = []
    for i in range(nkeys):
        parts.append(u64_words(jnp.min(col_u64(4 * i))))
        parts.append(u64_words(jnp.max(col_u64(4 * i + 2))))
    off = 4 * nkeys
    bloom = rows[0, off:]
    for p in range(1, int(rows.shape[0])):
        bloom = bloom | rows[p, off:]
    parts.append(bloom)
    return jnp.concatenate(parts)


class _InstrumentedDistLowerer(DistLowerer):
    """EXPLAIN ANALYZE's per-node row counts over the SAME distributed
    lowering (instrument.py run_pipeline): each node's selected-row
    count rides the existing replicated stats channel — the global sum
    for partitioned nodes and segment 0's count for replicated ones
    (post-gather nodes must count once, not nseg times)."""

    def lower(self, node):
        cols, sel = super().lower(node)
        cnt = jnp.sum(sel.astype(jnp.int64))
        is_seg0 = jnp.equal(jax.lax.axis_index(SEG_AXIS), 0)
        self.stats[f"node_rows_sum (node {id(node)})"] = \
            self.tx.psum(cnt, SEG_AXIS)
        self.stats[f"node_rows_one (node {id(node)})"] = \
            self.tx.psum(jnp.where(is_seg0, cnt, 0), SEG_AXIS)
        return cols, sel


def instrument_counts(plan: N.PlanNode, stats: dict) -> dict:
    """Host-side per-node counts from an instrumented program's stats:
    pick the cross-segment sum for partitioned nodes, segment 0's count
    for replicated ones (the same rule the legacy instrumented path
    applies to its per-seg arrays)."""
    import re

    sums, ones = {}, {}
    for key, v in stats.items():
        m = re.search(r"node_rows_(sum|one) \(node (\d+)\)", key)
        if m is None:
            continue
        (sums if m.group(1) == "sum" else ones)[int(m.group(2))] = \
            int(np.asarray(v))
    nodes = {id(n): n for n in X.all_nodes(plan)}
    out = {}
    for nid, n in nodes.items():
        if nid not in sums:
            continue
        if n.sharding is not None and n.sharding.is_partitioned:
            out[nid] = sums[nid]
        else:
            out[nid] = ones.get(nid, sums[nid])
    return out
