"""Scalar user-defined functions — the procedural-language seam.

The reference ships whole PL runtimes (src/pl/plpgsql, plpython, plperl)
running per-tuple inside the executor. A per-row Python callback has no
place in a one-XLA-program executor, so the extension seam offers the
three shapes that DO compile (mirroring how the built-in string
machinery already works):

- **constant folding**: immutable functions over constant arguments
  evaluate host-side at bind time (the preprocess_expression /
  eval_const_expressions role);
- **dictionary rewrite**: a function over ONE dictionary-encoded string
  column evaluates host-side over the dictionary's VALUES (small), and
  the per-row work compiles to a gather through the result table — the
  same machinery LIKE/substring predicates use (plan/binder.py
  DictLookup). Any Python callable works, string→string or
  string→scalar, at full distributed speed;
- **traced functions** (``jit=True``): the callable takes/returns
  jax arrays and traces INTO the compiled program — a TPU-native UDF
  (the reference's C-language function analog, minus the FFI).

``register_function(name, fn, arg_types, ret)`` is the CREATE FUNCTION
analog; the registry is process-global like the FDW/table-function
hooks (storage/fdw.py, exec/tablefunc.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

from cloudberry_tpu import types as T
from cloudberry_tpu.types import SqlType


@dataclass(frozen=True)
class Udf:
    name: str
    fn: Callable
    arg_types: tuple
    ret: SqlType
    volatility: str = "immutable"   # immutable | volatile
    jit: bool = False               # fn is jax-traceable


_UDFS: dict[str, Udf] = {}
# bumped on every (un)registration: UDF results bake into plans at bind
# time (constant fold, dictionary tables), so cached statements must
# invalidate when a function changes — the CREATE OR REPLACE semantics
_VERSION = 0


def registry_version() -> int:
    return _VERSION


def register_function(name: str, fn: Callable, arg_types, ret: SqlType,
                      volatility: str = "immutable",
                      jit: bool = False) -> None:
    """CREATE FUNCTION analog. ``arg_types``/``ret`` are
    cloudberry_tpu.types SQL types; ``jit=True`` promises fn maps jax
    arrays to a jax array (it will be traced into the program);
    ``volatility='volatile'`` disables constant folding AND the
    dictionary rewrite (both evaluate fewer times than once-per-row)."""
    global _VERSION

    if volatility not in ("immutable", "volatile"):
        raise ValueError(f"unknown volatility {volatility!r}")
    _UDFS[name.lower()] = Udf(name.lower(), fn, tuple(arg_types), ret,
                              volatility, jit)
    _VERSION += 1


def unregister_function(name: str) -> None:
    global _VERSION

    if _UDFS.pop(name.lower(), None) is not None:
        _VERSION += 1


def lookup(name: str) -> Optional[Udf]:
    return _UDFS.get(name.lower())


def known_functions() -> list[str]:
    return sorted(_UDFS)


def py_value(value, dtype: SqlType):
    """Literal payload → the Python value the function sees (decimals
    are stored as scaled ints; strings arrive as str)."""
    if dtype.base == T.DType.DECIMAL and value is not None:
        return value / 10 ** dtype.scale
    return value


def encode_result(value, dtype: SqlType):
    """Function result → literal payload (rescale decimals, validate)."""
    if value is None:
        return None
    if dtype.base == T.DType.DECIMAL:
        return int(round(float(value) * 10 ** dtype.scale))
    if dtype.base in (T.DType.INT32, T.DType.INT64, T.DType.DATE):
        return int(value)
    if dtype.base == T.DType.FLOAT64:
        return float(value)
    if dtype.base == T.DType.BOOL:
        return bool(value)
    if dtype.base == T.DType.STRING:
        return str(value)
    raise ValueError(f"UDF return type {dtype} unsupported")
