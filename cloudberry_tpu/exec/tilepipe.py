"""Windowed in-flight tile dispatch — keep the accelerator queue full.

The tiled streaming loops (exec/tiled.py, exec/tiled_dist.py) are the
engine's out-of-core hot path, and before this module every tile
round-tripped the device: ``step_fn`` launches, then
``_raise_tile_checks``/``sentinel.observe`` immediately force the tiny
per-tile check/stat scalars to host, so the device queue drains to
empty between tiles and the scan pipeline's staged tiles wait on a
stalled consumer. The discipline here is the one Theseus (PAPERS.md)
states for GPU MPP engines and every training input pipeline applies:
never synchronize the accelerator on per-batch control scalars — keep
a bounded window of W steps in flight, start the device→host copy of
each step's control scalars the moment it is dispatched, and only
block when the OLDEST in-flight tile's scalars are genuinely not ready.

``TilePipe`` is that window. The loop calls ``submit(idx, checks,
payload)`` right after dispatching tile ``idx``'s step; submit starts
async host copies for the checks and payload, then drains the oldest
entries until at most ``window-1`` remain in flight, returning the
drained entries so the caller runs their host-side effects (progress,
run appends, checkpoint ticks, sentinel folds) in stream order.
``drain_all()`` flushes the tail after the feed ends.

Correctness rules:

- **Deferred failure, bounded by W.** A capacity-overflow check or skew
  alarm for tile k is observed at most W tiles late, while tiles
  k+1..k+W-1 may already be dispatched. The checkpoint tick for a tile
  only happens when that tile has DRAINED CLEAN, so the last durable
  checkpoint never includes a failed tile's state: the adaptive retry
  (or device-loss resume) rewinds through the recovery store and
  replays ≤ W+K tiles at the grown rung — bit-identical to the
  synchronous path by construction, since tile order, kernel programs,
  and merge semantics are unchanged; only when the host *learns* of a
  failure moves.
- **Checkpoint payloads stage at submit.** On accelerators the carried
  accumulator is donated to the next step, so a drain-time snapshot
  could not read it; ``stage_checkpoint`` makes a device-side copy and
  starts its async D2H copy at submit time (decided by
  ``RecoveryCtx.snapshot_due``), and the drain-time tick materializes
  the staged copy without blocking the window.
- **Cancellation still polls per drained tile.** Every drain routes
  through ``_raise_tile_checks`` (the ``check_cancel`` seam), so
  cancellation latency is bounded by W in-flight launches instead of
  one — the graftlint seam-loop pass accepts ``drain_one``/
  ``drain_all`` as cancel polls for exactly this reason.
- **``inflight_tiles=1`` is the legacy loop, exactly.** submit drains
  the just-submitted tile immediately: checks force right after the
  step inside the same timer window, host effects run in the same
  order, no staging copies are made. That is the CPU-backend default
  (``effective_window``): a single-threaded host gains nothing from
  in-flight depth, accelerators default to a window of 4.

Telemetry: ``drain_stall_s`` (host seconds blocked forcing drained
scalars), ``inflight_depth`` (window high-water mark) stamp the tiled
run report for EXPLAIN ANALYZE's trailer and the bench ladder; the
``tile_inflight`` gauge and the ``tile_deferred_overflows``/
``tile_window_replays`` counters ride the engine registry. The window's
extra in-flight device tiles are charged into the statement's capacity
estimate (``window_charge_bytes`` → est_pipeline_bytes).
"""

from __future__ import annotations

import time
from collections import deque
from typing import NamedTuple

import jax
import jax.numpy as jnp

from cloudberry_tpu.utils.faultinject import fault_point

# Auto window depth on accelerator backends (TPU/GPU): deep enough to
# overlap D2H of tile k's scalars + H2D of tile k+2's data with tile
# k+1's compute, shallow enough that a deferred overflow replays only a
# few tiles past the last checkpoint.
_AUTO_ACCEL_WINDOW = 4
_MAX_WINDOW = 64


def step_donation(platform: str, argnum: int = 4) -> tuple:
    """The accumulator-donation rule every tiled step program shares
    (agg + topn, single-node and distributed — including the top-N
    heap carry, whose donation is legal because the bounding sort's
    first g_cap positions match the (g_cap,) input acc shape exactly):
    donate the carried accumulator argument so the step updates it in
    place on device and the sequential dependency never leaves HBM.
    CPU XLA can't always honor donation and warns — skip it there."""
    return () if platform == "cpu" else (argnum,)


def effective_window(config, platform: str) -> int:
    """The in-flight tile window for this run. ``inflight_tiles <= 0``
    means auto: 1 on the CPU backend (the legacy loop, exactly — a
    single-threaded host has nothing to overlap), ``_AUTO_ACCEL_WINDOW``
    on accelerators."""
    tp = getattr(config, "tile_pipeline", None)
    if tp is None or not tp.enabled:
        return 1
    w = int(tp.inflight_tiles)
    if w <= 0:
        w = 1 if platform == "cpu" else _AUTO_ACCEL_WINDOW
    return max(1, min(w, _MAX_WINDOW))


def window_charge_bytes(scan, tile_rows: int, config, platform: str,
                        nseg: int = 1) -> int:
    """Capacity-plane charge for the dispatch window: beyond the first
    tile (already counted in est_step_bytes), each additional in-flight
    tile pins one tile's working set on device until its scalars
    drain."""
    w = effective_window(config, platform)
    if w <= 1:
        return 0
    from cloudberry_tpu.exec import scanpipe as SP

    return (w - 1) * SP.tile_host_bytes(scan, tile_rows, nseg)


def _host_async(tree) -> None:
    """Start async device→host copies for every jax leaf of ``tree`` —
    advisory: a leaf that cannot stage just blocks at materialization,
    which is the pre-pipeline behavior, never an error."""
    for leaf in jax.tree_util.tree_leaves(tree):
        fn = getattr(leaf, "copy_to_host_async", None)
        if fn is not None:
            try:
                fn()
            except Exception:  # noqa: BLE001 — staging is best-effort
                pass


def _own_copy(x):
    # device-side defensive copy: the ORIGINAL buffer is donated to the
    # next step on accelerators, the copy is ours to read at drain time
    return jnp.copy(x) if isinstance(x, jax.Array) else x


def stage_checkpoint(acc):
    """Checkpoint staging for a windowed submit (window > 1 only): copy
    the carried accumulator ON DEVICE before the next step donates the
    original, start the copy's async D2H, and return the zero-arg
    payload builder ``RecoveryCtx.tick`` runs at drain time — by then
    the transfer has usually landed, so the tick never stalls the
    window."""
    from cloudberry_tpu.exec import recovery as R

    cp = jax.tree_util.tree_map(_own_copy, acc)
    _host_async(cp)
    return lambda: R.acc_payload(cp)


class Drained(NamedTuple):
    """One verified tile, handed back to the loop in stream order."""

    idx: int        # global tile index (n_base + local ordinal)
    payload: object  # whatever the loop attached at submit


class _InFlight(NamedTuple):
    idx: int
    checks: dict
    payload: object


class TilePipe:
    """Bounded window of in-flight tile steps whose control scalars
    drain late. Single-threaded by design: the statement thread owns
    both ends (JAX's async dispatch IS the concurrency), so there is no
    lock and no reader to leak — an abandoned pipe (error unwind) just
    drops its entries and the device launches complete into garbage-
    collected buffers; the feed's ``finally`` close is unchanged."""

    def __init__(self, session, window: int):
        self.window = max(int(window), 1)
        self._log = getattr(session, "stmt_log", None)
        self._q: deque = deque()
        self.max_depth = 0       # in-flight high-water mark
        self.drained = 0         # tiles verified
        self.drain_stall_s = 0.0  # host blocked forcing drained scalars
        self.deferred_fail = False  # a check fired with newer tiles live

    # ------------------------------------------------------------- submit

    def submit(self, idx: int, checks: dict, payload=None) -> list:
        """Enqueue tile ``idx``'s just-dispatched control scalars; start
        their async host copies; drain until at most ``window-1``
        entries remain in flight. Returns the drained entries (possibly
        empty) in stream order — at window=1 that is always exactly the
        submitted tile, forced synchronously like the legacy loop."""
        fault_point("tile_enqueue")
        _host_async((checks, payload))
        self._q.append(_InFlight(idx, checks, payload))
        # high-water mark only — the ``tile_inflight`` gauge is written
        # from obs/capacity.record_tiled off the stamped report, where
        # every other point-in-time gauge lives
        self.max_depth = max(self.max_depth, len(self._q))
        out = []
        while len(self._q) >= self.window:
            out.append(self.drain_one())
        return out

    # -------------------------------------------------------------- drain

    def drain_one(self) -> Drained:
        """Force the OLDEST in-flight tile's checks (the per-tile cancel
        poll rides ``_raise_tile_checks``) and hand it back. A check
        that fires here may be up to ``window`` tiles late — when newer
        tiles were already dispatched the failure is *deferred* and the
        adaptive retry replays from the last drained checkpoint."""
        from cloudberry_tpu.exec.tiled import _raise_tile_checks

        entry = self._q.popleft()
        fault_point("tile_drain")
        t0 = time.perf_counter()
        try:
            _raise_tile_checks(entry.checks, entry.idx)
        except Exception:
            if self._q:
                self.deferred_fail = True
                if self._log is not None:
                    self._log.bump("tile_deferred_overflows")
            raise
        self.drain_stall_s += time.perf_counter() - t0
        self.drained += 1
        return Drained(entry.idx, entry.payload)

    def drain_all(self) -> list:
        """Flush the window after the feed ends (or before an action
        that needs every dispatched tile verified, e.g. the skew
        sentinel's settle before a mid-statement replan snapshot)."""
        out = []
        while self._q:
            out.append(self.drain_one())
        return out

    # ---------------------------------------------------------- telemetry

    def stamp(self, report: dict) -> None:
        report["tile_window"] = self.window
        report["inflight_depth"] = self.max_depth
        report["drain_stall_s"] = round(self.drain_stall_s, 6)
