"""HBM-resident buffer pool — hot scans served at device bandwidth.

Every scan used to stream micro-partitions host→device per statement:
even a repeat aggregate over a hot table paid read + decode + transfer
again, and the scan pipeline (exec/scanpipe.py) can only HIDE that host
work, not remove it. The reference engine keeps hot blocks in a shared
buffer pool next to the executor; the TPU-native analog is device
residency (the near-data-processing thesis of Taurus and the
device-residency argument of the GPU-augmented OLAP engine, PAPERS.md):
decoded, packed columnar partition chunks stay in HBM across
statements, so a hot scan's feed starts from on-chip arrays and the
host never touches the partition files at all.

Design:

- **Entries are decoded partition chunks**, exactly the dict the cold
  feed builds after ``TableStore.read_partitions`` (post-delete-filter
  columns, ``cols``/``validity`` split) — the canonical unit both
  consumers re-assemble from, so pooled and cold reads are bit-identical
  by construction (``read_partitions`` concatenates per-part chunks in
  part order; serving one chunk from the pool is the same arithmetic).
  Single-node entries are committed to the device (``jax.device_put``;
  HBM on real hardware); distributed tile entries stay host-side —
  shard_map owns placement there, exactly like the pipeline's
  ``device_stage=False`` feed.
- **Keys carry the shared-cache-tier tokens** (sched/sharedcache.py):
  table name, store version, partition file, column set, nseg/tile
  coordinates for the distributed path, the TOPOLOGY EPOCH and the
  CONFIG epoch uid. A VERSION bump, a with_overrides config swap, or an
  epoch cutover therefore invalidates by construction — a stale entry's
  key can never be asked for again (and ``TopologyManager._adopt``
  additionally drops the resident bytes eagerly).
- **Admission by observed scan frequency**: every lookup counts a scan
  of that partition (the obs-plane per-partition frequency signal);
  ``offer`` admits only once the count reaches
  ``config.bufferpool.admit_min_scans`` — a one-off table scan never
  displaces the working set.
- **Eviction is LRU-by-bytes under ``config.bufferpool.max_bytes``**,
  with REFUSAL-over-evicting-hotter (the RecoveryStore byte-budget
  discipline): an oversize chunk is refused, and a candidate never
  evicts a victim that is scanned more frequently than itself.
- Lock discipline: ``BufferPool._lock`` is an innermost leaf
  (lint/config.py WITNESS_ORDER rank 4) — nothing is called while it is
  held; counter bumps and the ``bufpool_admit``/``bufpool_evict`` fault
  seams run OUTSIDE it (faultinject._lock shares the leaf rank).

Capacity plane: resident bytes are charged next to
``est_pipeline_bytes`` (``est_bufpool_bytes`` in the tiled reports,
obs/capacity.record_tiled) and surface as ``mem_bufpool_*`` gauges in
``meta "metrics"`` (obs/capacity.refresh_gauges).
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from cloudberry_tpu.utils.faultinject import fault_point

# per-partition scan-frequency sketch bound: far above any realistic
# working set; overflow drops the oldest observation (FIFO), which only
# biases a cold key back toward not-yet-admitted
_FREQ_MAX = 65536


def _value_nbytes(value: dict) -> int:
    """Bytes one entry pins: the nested cols/validity arrays."""
    total = 0
    for v in value.values():
        if isinstance(v, dict):
            total += _value_nbytes(v)
        else:
            nb = getattr(v, "nbytes", None)
            if nb is not None:
                total += int(nb)
    return total


def _commit(value: dict, device: bool) -> dict:
    """Copy an entry for residency. ``device=True`` commits every numpy
    leaf via jax.device_put (HBM on real hardware — the single-node
    path); ``device=False`` keeps host arrays (the distributed tile
    path: shard_map owns device placement)."""
    if not device:
        return {k: (dict(v) if isinstance(v, dict) else v)
                for k, v in value.items()}
    import jax

    def put(v):
        return jax.device_put(v) if isinstance(v, np.ndarray) else v

    return {k: ({c: put(a) for c, a in v.items()}
                if isinstance(v, dict) else put(v))
            for k, v in value.items()}


class BufferPool:
    """Engine-wide device-side micro-partition cache. One per cache
    scope (sched/sharedcache.py) — sessions over the same store root
    share it; storeless sessions get a private one. All shared state
    lives under ``_lock`` (a leaf: nothing is called while held)."""

    def __init__(self, max_bytes: int, admit_min_scans: int = 2):
        self._lock = threading.Lock()
        # key -> (value dict, nbytes, table name); dict order IS the
        # LRU order (lookup pops and reinserts, eviction takes the head)
        self._entries: dict = {}
        # observed per-partition scan counts (the admission signal)
        self._freq: dict = {}
        self.bytes = 0
        self.max_bytes = int(max_bytes)
        self.admit_min_scans = max(int(admit_min_scans), 1)
        # telemetry mirrors for snapshot() (the engine counters are
        # bumped by callers' StatementLog outside the lock)
        self.hits = 0
        self.misses = 0
        self.admits = 0
        self.evictions = 0
        self.refusals = 0

    # ------------------------------------------------------------- lookup

    def lookup(self, key, log=None) -> Optional[dict]:
        """The resident entry for ``key`` (LRU-touched), or None. Every
        call counts one observed scan of the partition — the admission
        frequency ``offer`` consults."""
        with self._lock:
            self._freq[key] = self._freq.get(key, 0) + 1
            while len(self._freq) > _FREQ_MAX:
                self._freq.pop(next(iter(self._freq)))
            ent = self._entries.pop(key, None)
            if ent is not None:
                self._entries[key] = ent  # LRU touch
                self.hits += 1
            else:
                self.misses += 1
        if log is not None:
            log.bump("bufpool_hits" if ent is not None
                     else "bufpool_misses")
        return ent[0] if ent is not None else None

    # ---------------------------------------------------------- admission

    def offer(self, key, value: dict, table: str = "", log=None,
              device: bool = True) -> bool:
        """Admit one decoded chunk if it is hot enough and fits. Returns
        True when the entry became resident. The fault seams and counter
        bumps run OUTSIDE the pool lock (they take leaf locks of the
        same witness rank)."""
        with self._lock:
            cap = self.max_bytes
            admit_min = self.admit_min_scans
            known = key in self._entries
            freq = self._freq.get(key, 0)
        if known or cap <= 0 or freq < admit_min:
            return False
        nb = _value_nbytes(value)
        if nb <= 0:
            return False
        if nb > cap:
            # oversize: refuse rather than flush the whole pool for one
            # chunk (the RecoveryStore ckpt_oversize_refused discipline)
            with self._lock:
                self.refusals += 1
            if log is not None:
                log.bump("bufpool_refusals")
            return False
        if fault_point("bufpool_admit"):
            return False  # 'skip' arm: suppress admission
        with self._lock:
            will_evict = self.bytes + nb > cap and bool(self._entries)
        if will_evict and fault_point("bufpool_evict"):
            return False  # 'skip' arm: refuse rather than evict
        held = _commit(value, device)
        evicted = 0
        refused = False
        admitted = False
        with self._lock:
            cap = self.max_bytes
            if key not in self._entries:
                while self.bytes + nb > cap and self._entries:
                    vk = next(iter(self._entries))
                    if self._freq.get(vk, 0) > freq:
                        # refusal-over-evicting-hotter: never displace
                        # a more-frequently-scanned partition for a
                        # colder candidate — refuse the candidate
                        refused = True
                        break
                    _, vnb, _ = self._entries.pop(vk)
                    self.bytes -= vnb
                    evicted += 1
                if not refused and self.bytes + nb <= cap:
                    self._entries[key] = (held, nb, table)
                    self.bytes += nb
                    self.admits += 1
                    admitted = True
                else:
                    refused = True
                if refused:
                    self.refusals += 1
                if evicted:
                    self.evictions += evicted
        if log is not None:
            if evicted:
                log.bump("bufpool_evictions", evicted)
            if admitted:
                log.bump("bufpool_admits")
            if refused:
                log.bump("bufpool_refusals")
        return admitted

    # ------------------------------------------------------- invalidation

    def sweep(self, pred) -> int:
        """Drop every entry whose KEY satisfies ``pred`` (a pure
        function over the key tuple — called under the lock, so it must
        not acquire anything). Returns the count dropped."""
        with self._lock:
            dead = [k for k in self._entries if pred(k)]
            for k in dead:
                _, nb, _ = self._entries.pop(k)
                self.bytes -= nb
        return len(dead)

    def clear(self) -> int:
        """Drop everything (topology cutover / scope invalidation —
        stale keys could never serve anyway, but the resident HBM bytes
        are freed eagerly). The frequency sketch clears too: the old
        placement's heat is not evidence about the new one."""
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self._freq.clear()
            self.bytes = 0
        return n

    # ------------------------------------------------------ observability

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self.bytes,
                "max_bytes": self.max_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "admits": self.admits,
                "evictions": self.evictions,
                "refusals": self.refusals,
                "tracked_keys": len(self._freq),
            }

    def table_bytes(self, table: str) -> int:
        """Resident bytes attributable to one table — the capacity-plane
        charge the tiled reports stamp as ``est_bufpool_bytes``."""
        with self._lock:
            return sum(nb for _, nb, t in self._entries.values()
                       if t == table)

    def grow(self, max_bytes: int) -> None:
        """Grow-only budget update: a second session in the scope with a
        larger configured pool raises the cap; a smaller one never
        shrinks it under a peer (the decode-pool grow discipline)."""
        with self._lock:
            if int(max_bytes) > self.max_bytes:
                self.max_bytes = int(max_bytes)


# -------------------------------------------------------------- wiring

_create_lock = threading.Lock()


def pool_for(session) -> Optional[BufferPool]:
    """The session's buffer pool, anchored on its cache scope
    (sched/sharedcache.py — per store root when shared, per session
    otherwise), lazily created. None when config.bufferpool disables
    it: every consumer then takes its pre-pool path unchanged. Bare
    test-double sessions without a config degrade the same way."""
    bp = getattr(getattr(session, "config", None), "bufferpool", None)
    if bp is None or not bp.enabled or bp.max_bytes <= 0:
        return None
    from cloudberry_tpu.sched import sharedcache

    scope = sharedcache.scope_for(session)
    pool = getattr(scope, "bufferpool", None)
    if pool is None:
        with _create_lock:
            pool = getattr(scope, "bufferpool", None)
            if pool is None:
                pool = BufferPool(bp.max_bytes, bp.admit_min_scans)
                scope.bufferpool = pool
    else:
        pool.grow(bp.max_bytes)
    return pool


def partition_key(session, table: str, part: dict, columns: tuple):
    """Key for one decoded single-node partition chunk. The store
    version pins content (manifests are immutable — a commit publishes
    a new version, including delete-vector changes); the topology and
    config tokens are the shared-tier epoch discipline."""
    from cloudberry_tpu.sched import sharedcache

    return ("part", table,
            session.catalog.store.effective_version(table),
            part["file"], columns,
            sharedcache.topology_token(session),
            sharedcache.config_uid(session.config))


def dist_tile_key(session, table: str, columns: tuple, nseg: int,
                  tile_rows: int, off: int):
    """Key for one packed (nseg, tile_rows) distributed feed tile.
    ``table_key`` pins the content (store version, or object uid +
    version for RAM tables); nseg/tile geometry pins the packing."""
    from cloudberry_tpu.sched import sharedcache

    return ("dtile", sharedcache.table_key(session, table), columns,
            int(nseg), int(tile_rows), int(off),
            sharedcache.topology_token(session),
            sharedcache.config_uid(session.config))
