"""Pallas TPU kernels for the two hottest executor ops.

1. Grouped aggregation over a scan (Q1's shape: 6M rows → 6 cells ×
~8 aggregates). The XLA formulation (exec/kernels.group_aggregate_dense)
is a chain of masked reductions; ``dense_agg_pallas`` fuses the whole
thing into ONE pass over HBM:

  per row-tile (grid is sequential on TPU, so accumulating into the output
  block is safe):
      onehot = (gid == cell_ids) & sel          # (cells, TILE) in VMEM
      counts += sum(onehot, axis=1)
      sums   += values @ onehot.T               # (K, cells) on the MXU

The matmul accumulates in float32 on the MXU; exact int64-cent money sums
keep the XLA path for the AGG (decimal sums through this kernel round to
float32 — approximate analytics, not money reconciliation).

2. Probe-side join against a SMALL unique build (the nodeHash.c probe
loop's role; every dim join in TPC-H's star shapes). The XLA
formulation sorts the build and binary-searches every probe key;
``probe_join_pallas`` instead streams probe tiles once and, per tile,
compare-alls the (whole, VMEM-resident) build keys on the VPU and
gathers the payload with ONE one-hot matmul on the MXU:

      eq = (bkeys[:, None] == pkeys[None, :]) & bsel & psel  # (B, TILE)
      matched = sum(eq, axis=0)            # 0/1 (unique build); >1 = dup
      gathered = payload @ eq              # (P, TILE) on the MXU

Payload transport is EXACT for integers: the caller splits each int64
column into three 21/21/22-bit limbs, each an integer ≤ 2^22 that f32
represents exactly; a matched row gathers exactly one source, so limb
recombination reproduces the original bits (two's complement via the
uint64 round trip). That is the TPU-native answer to "hash-join gather"
— no scatter, no pointer chase, the MXU does the routing.

Both kernels are gated by ``config.exec.use_pallas`` (wired through
Lowerer), default off until re-measured on hardware (the dev TPU relay
has been wedged; see bench.py's BENCH_PALLAS env knob for the A/B harness).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _dense_agg_kernel(gid_ref, vals_ref, sel_ref, out_ref, *, n_cells: int):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    g = gid_ref[:]                       # (TILE,)
    s = sel_ref[:]                       # (TILE,)
    v = vals_ref[:]                      # (K, TILE)
    cells = jax.lax.broadcasted_iota(jnp.int32, (n_cells, g.shape[0]), 0)
    onehot = (g[None, :] == cells) & s[None, :]          # (cells, TILE)
    oh_f = onehot.astype(jnp.float32)
    counts = jnp.sum(oh_f, axis=1)                       # (cells,)
    sums = jnp.dot(v, oh_f.T,
                   preferred_element_type=jnp.float32,
                   precision=jax.lax.Precision.HIGHEST)  # (K, cells) on MXU
    out_ref[0, :] += counts
    out_ref[1:, :] += sums


@functools.partial(jax.jit, static_argnames=("n_cells", "tile", "interpret"))
def dense_agg_pallas(gid: jnp.ndarray, vals: jnp.ndarray, sel: jnp.ndarray,
                     n_cells: int, tile: int = 2048,
                     interpret: bool = False):
    """Fused one-pass grouped sum+count for a small static cell domain.

    gid: int32[N] cell per row; vals: float32[K, N]; sel: bool[N].
    Returns (counts f32[cells], sums f32[K, cells]).
    N must be a multiple of ``tile`` (caller pads; sel masks padding).
    """
    k, n = vals.shape
    assert n % tile == 0, "pad rows to a tile multiple"
    grid = (n // tile,)
    out = pl.pallas_call(
        functools.partial(_dense_agg_kernel, n_cells=n_cells),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile,), lambda i: (i,)),
            pl.BlockSpec((k, tile), lambda i: (0, i)),
            pl.BlockSpec((tile,), lambda i: (i,)),
        ],
        out_specs=pl.BlockSpec((k + 1, n_cells), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((k + 1, n_cells), jnp.float32),
        interpret=interpret,
    )(gid, vals, sel)
    return out[0], out[1:]

def _probe_join_kernel(bkeys_ref, bsel_ref, pkeys_ref, psel_ref, pay_ref,
                       out_ref):
    bk = bkeys_ref[:]                       # (B,)
    bs = bsel_ref[:]                        # (B,)
    pk = pkeys_ref[:]                       # (TILE,)
    ps = psel_ref[:]                        # (TILE,)
    pay = pay_ref[:]                        # (P, B)
    eq = (bk[:, None] == pk[None, :]) & bs[:, None] & ps[None, :]
    eqf = eq.astype(jnp.float32)            # (B, TILE)
    matched = jnp.sum(eqf, axis=0)          # 0/1; >1 flags a dup build
    # HIGHEST precision is REQUIRED for exactness: default MXU matmul
    # decomposes f32 into bf16 passes, which would truncate 21/22-bit
    # limbs before the gather
    gathered = jnp.dot(pay, eqf,
                       preferred_element_type=jnp.float32,
                       precision=jax.lax.Precision.HIGHEST)  # MXU
    out_ref[0, :] = matched
    out_ref[1:, :] = gathered


@functools.partial(jax.jit,
                   static_argnames=("tile", "interpret"))
def probe_join_pallas(bkeys: jnp.ndarray, bsel: jnp.ndarray,
                      pkeys: jnp.ndarray, psel: jnp.ndarray,
                      payload: jnp.ndarray, tile: int = 1024,
                      interpret: bool = False):
    """Fused probe join against a small unique build.

    bkeys: u32[B] packed build keys (B caller-padded; bsel masks pads);
    pkeys: u32[N] packed probe keys (N a multiple of ``tile``);
    payload: f32[P, B] limb-encoded build payload.
    Returns (match f32[N] — 0/1, >1 ⇒ duplicate build keys;
    gathered f32[P, N])."""
    p, b = payload.shape
    n = pkeys.shape[0]
    assert n % tile == 0, "pad probe rows to a tile multiple"
    grid = (n // tile,)
    out = pl.pallas_call(
        _probe_join_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((b,), lambda i: (0,)),
            pl.BlockSpec((b,), lambda i: (0,)),
            pl.BlockSpec((tile,), lambda i: (i,)),
            pl.BlockSpec((tile,), lambda i: (i,)),
            pl.BlockSpec((p, b), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((p + 1, tile), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((p + 1, n), jnp.float32),
        interpret=interpret,
    )(bkeys, bsel, pkeys, psel, payload)
    return out[0], out[1:]


# 21/21/22-bit limb split: every limb is an integer < 2^22, exactly
# representable in float32 — the one-hot matmul then transports int64
# payloads losslessly (exactly one source row per matched column).
_LIMB_BITS = (21, 21, 22)
_LIMB_SHIFTS = (0, 21, 42)


def int64_to_limbs(col: jnp.ndarray) -> list:
    """int64 → three f32 limb rows (two's complement via uint64)."""
    u = col.astype(jnp.int64).view(jnp.uint64)
    out = []
    for bits, shift in zip(_LIMB_BITS, _LIMB_SHIFTS):
        mask = jnp.uint64((1 << bits) - 1)
        out.append(((u >> jnp.uint64(shift)) & mask).astype(jnp.float32))
    return out


def limbs_to_int64(l0: jnp.ndarray, l1: jnp.ndarray,
                   l2: jnp.ndarray) -> jnp.ndarray:
    """Inverse of int64_to_limbs (rounding to nearest undoes the f32
    transport exactly because every limb is an integer < 2^24)."""
    u = (jnp.round(l2).astype(jnp.uint64) << jnp.uint64(42)) \
        | (jnp.round(l1).astype(jnp.uint64) << jnp.uint64(21)) \
        | jnp.round(l0).astype(jnp.uint64)
    return u.view(jnp.int64)
