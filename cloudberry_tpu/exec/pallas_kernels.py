"""Pallas TPU kernels for the three hottest executor ops.

1. Grouped aggregation over a scan (Q1's shape: 6M rows → 6 cells ×
~8 aggregates). The XLA formulation (exec/kernels.group_aggregate_dense)
is a chain of masked reductions; ``dense_agg_tiles_pallas`` fuses the
whole thing into ONE pass over HBM:

  per row-tile:
      onehot = (gid == cell_ids) & sel          # (cells, TILE) in VMEM
      counts = sum(onehot, axis=1)
      sums   = values @ onehot.T                # (K, cells) on the MXU

Each grid step writes ITS OWN partial block (n_tiles, K+1, cells); the
caller combines per-tile partials outside the kernel. That split is what
makes int64-cent money sums EXACT through the f32 MXU: the caller splits
each int64 column into five 13-bit limbs (``int64_to_agg_limbs``). Every
per-tile dot-product partial sum is then an integer below
TILE × 2^13 = 2^24, which f32 represents exactly regardless of the MXU's
accumulation order — so each tile's limb sums are exact integers, the
cross-tile combine runs in int64, and carry propagation between limbs
happens once at the end (``agg_limbs_to_int64``). SUM/AVG over DECIMAL
(int64 cents) and BIGINT therefore reproduce the XLA path bit for bit;
float sums ride a single f32 row (approximate, as before).

1b. Mid-cardinality grouped aggregation (``sorted_segment_aggregate``):
between the tiny static cell domain above and the generic XLA sort path
there was no fused kernel. This one reuses ``kernels.sort_indices`` to
order rows by key, then streams tiles through ``_sorted_seg_kernel``: a
carried (last-gid, partial-accumulator) pair lives in SMEM, each tile
runs one segmented Hillis–Steele scan on the VPU, and a completed
group's total is flushed at the row where the NEXT group begins. Sums
accumulate in int32 over 8-bit limbs (group totals stay below 2^31 for
up to 2^23 rows), so int64/DECIMAL sums are exact here too. Group count
is bounded only by the agg capacity — far beyond any one-hot domain.

2. Probe-side join against a SMALL unique build (the nodeHash.c probe
loop's role; every dim join in TPC-H's star shapes). The XLA
formulation sorts the build and binary-searches every probe key;
``probe_join_pallas`` instead streams probe tiles once and, per tile,
compare-alls the (whole, VMEM-resident) build keys on the VPU and
gathers the payload with ONE one-hot matmul on the MXU:

      eq = (bkeys[:, None] == pkeys[None, :]) & bsel & psel  # (B, TILE)
      matched = sum(eq, axis=0)            # 0/1 (unique build); >1 = dup
      gathered = payload @ eq              # (P, TILE) on the MXU

Payload transport is EXACT for integers: the caller splits each int64
column into three 21/21/22-bit limbs, each an integer ≤ 2^22 that f32
represents exactly; a matched row gathers exactly one source, so limb
recombination reproduces the original bits (two's complement via the
uint64 round trip). That is the TPU-native answer to "hash-join gather"
— no scatter, no pointer chase, the MXU does the routing.

All kernels are gated by ``config.exec.use_pallas`` (wired through
Lowerer), default off until re-measured on hardware (the dev TPU relay
has been wedged; see bench.py's BENCH_PALLAS env knob for the A/B harness).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _dense_agg_kernel(gid_ref, vals_ref, sel_ref, out_ref, *, n_cells: int):
    g = gid_ref[:]                       # (TILE,)
    s = sel_ref[:]                       # (TILE,)
    v = vals_ref[:]                      # (K, TILE)
    cells = jax.lax.broadcasted_iota(jnp.int32, (n_cells, g.shape[0]), 0)
    onehot = (g[None, :] == cells) & s[None, :]          # (cells, TILE)
    oh_f = onehot.astype(jnp.float32)
    counts = jnp.sum(oh_f, axis=1)                       # (cells,)
    sums = jnp.dot(v, oh_f.T,
                   preferred_element_type=jnp.float32,
                   precision=jax.lax.Precision.HIGHEST)  # (K, cells) on MXU
    out_ref[0, 0, :] = counts
    out_ref[0, 1:, :] = sums


@functools.partial(jax.jit, static_argnames=("n_cells", "tile", "interpret"))
def dense_agg_tiles_pallas(gid: jnp.ndarray, vals: jnp.ndarray,
                           sel: jnp.ndarray, n_cells: int, tile: int = 2048,
                           interpret: bool = False):
    """Fused one-pass grouped sum+count, PER-TILE partials.

    gid: int32[N] cell per row; vals: float32[K, N]; sel: bool[N].
    Returns f32[n_tiles, K+1, cells] — row 0 of each tile block is the
    tile's counts, rows 1.. its sums. N must be a multiple of ``tile``
    (caller pads; sel masks padding). Each per-tile partial is a sum of
    at most ``tile`` values; with limb-encoded inputs (< 2^13) every
    partial stays below 2^24 and the f32 transport is exact — the caller
    combines tiles in int64 (``agg_limbs_to_int64``)."""
    k, n = vals.shape
    assert n % tile == 0, "pad rows to a tile multiple"
    grid = (n // tile,)
    return pl.pallas_call(
        functools.partial(_dense_agg_kernel, n_cells=n_cells),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile,), lambda i: (i,)),
            pl.BlockSpec((k, tile), lambda i: (0, i)),
            pl.BlockSpec((tile,), lambda i: (i,)),
        ],
        out_specs=pl.BlockSpec((1, k + 1, n_cells), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n // tile, k + 1, n_cells),
                                       jnp.float32),
        interpret=interpret,
    )(gid, vals, sel)


def dense_agg_pallas(gid: jnp.ndarray, vals: jnp.ndarray, sel: jnp.ndarray,
                     n_cells: int, tile: int = 2048,
                     interpret: bool = False):
    """Fused grouped sum+count for a small static cell domain.

    Returns (counts f32[cells], sums f32[K, cells]); the per-tile
    partials of ``dense_agg_tiles_pallas`` combined in f32 — the
    float-valued convenience wrapper (exact integer sums go through the
    limb path in the executor instead)."""
    out = jnp.sum(dense_agg_tiles_pallas(gid, vals, sel, n_cells,
                                         tile=tile, interpret=interpret),
                  axis=0)
    return out[0], out[1:]

def _probe_join_kernel(bkeys_ref, bsel_ref, pkeys_ref, psel_ref, pay_ref,
                       out_ref):
    bk = bkeys_ref[:]                       # (B,)
    bs = bsel_ref[:]                        # (B,)
    pk = pkeys_ref[:]                       # (TILE,)
    ps = psel_ref[:]                        # (TILE,)
    pay = pay_ref[:]                        # (P, B)
    eq = (bk[:, None] == pk[None, :]) & bs[:, None] & ps[None, :]
    eqf = eq.astype(jnp.float32)            # (B, TILE)
    matched = jnp.sum(eqf, axis=0)          # 0/1; >1 flags a dup build
    # HIGHEST precision is REQUIRED for exactness: default MXU matmul
    # decomposes f32 into bf16 passes, which would truncate 21/22-bit
    # limbs before the gather
    gathered = jnp.dot(pay, eqf,
                       preferred_element_type=jnp.float32,
                       precision=jax.lax.Precision.HIGHEST)  # MXU
    out_ref[0, :] = matched
    out_ref[1:, :] = gathered


@functools.partial(jax.jit,
                   static_argnames=("tile", "interpret"))
def probe_join_pallas(bkeys: jnp.ndarray, bsel: jnp.ndarray,
                      pkeys: jnp.ndarray, psel: jnp.ndarray,
                      payload: jnp.ndarray, tile: int = 1024,
                      interpret: bool = False):
    """Fused probe join against a small unique build.

    bkeys: u32[B] packed build keys (B caller-padded; bsel masks pads);
    pkeys: u32[N] packed probe keys (N a multiple of ``tile``);
    payload: f32[P, B] limb-encoded build payload.
    Returns (match f32[N] — 0/1, >1 ⇒ duplicate build keys;
    gathered f32[P, N])."""
    p, b = payload.shape
    n = pkeys.shape[0]
    assert n % tile == 0, "pad probe rows to a tile multiple"
    grid = (n // tile,)
    out = pl.pallas_call(
        _probe_join_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((b,), lambda i: (0,)),
            pl.BlockSpec((b,), lambda i: (0,)),
            pl.BlockSpec((tile,), lambda i: (i,)),
            pl.BlockSpec((tile,), lambda i: (i,)),
            pl.BlockSpec((p, b), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((p + 1, tile), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((p + 1, n), jnp.float32),
        interpret=interpret,
    )(bkeys, bsel, pkeys, psel, payload)
    return out[0], out[1:]


# 21/21/22-bit limb split: every limb is an integer < 2^22, exactly
# representable in float32 — the one-hot matmul then transports int64
# payloads losslessly (exactly one source row per matched column).
_LIMB_BITS = (21, 21, 22)
_LIMB_SHIFTS = (0, 21, 42)


def int64_to_limbs(col: jnp.ndarray) -> list:
    """int64 → three f32 limb rows (two's complement via uint64)."""
    u = col.astype(jnp.int64).view(jnp.uint64)
    out = []
    for bits, shift in zip(_LIMB_BITS, _LIMB_SHIFTS):
        mask = jnp.uint64((1 << bits) - 1)
        out.append(((u >> jnp.uint64(shift)) & mask).astype(jnp.float32))
    return out


def limbs_to_int64(l0: jnp.ndarray, l1: jnp.ndarray,
                   l2: jnp.ndarray) -> jnp.ndarray:
    """Inverse of int64_to_limbs (rounding to nearest undoes the f32
    transport exactly because every limb is an integer < 2^24)."""
    u = (jnp.round(l2).astype(jnp.uint64) << jnp.uint64(42)) \
        | (jnp.round(l1).astype(jnp.uint64) << jnp.uint64(21)) \
        | jnp.round(l0).astype(jnp.uint64)
    return u.view(jnp.int64)


# --------------------------------------------------------------------------
# Aggregation limb schemes. The probe join's 21/21/22 split transports ONE
# value per matched row; aggregation SUMS limbs, so the width must leave
# headroom for the accumulation:
#
# - dense (MXU, f32): 5×13-bit limbs. A per-tile dot-product partial sum is
#   ≤ TILE(2048) × (2^13−1) < 2^24, so every f32 add in the MXU reduction
#   is exact; tiles combine in int64 outside the kernel.
# - sorted-segment (VPU, int32): 8×8-bit limbs. A group total is
#   ≤ 2^23 rows × (2^8−1) < 2^31, so int32 never overflows for streams up
#   to MAX_SEG_ROWS; limbs recombine in uint64 with two's-complement wrap,
#   exactly like the probe join's scheme.
# --------------------------------------------------------------------------

AGG_LIMB_BITS = (13, 13, 13, 13, 12)
SEG_LIMB_BITS = (8,) * 8
MAX_SEG_ROWS = 1 << 23  # 2^23 × (2^8−1) < 2^31: int32 accumulator proof


def _limb_shifts(bits):
    shifts, acc = [], 0
    for b in bits:
        shifts.append(acc)
        acc += b
    return shifts


def _split_limbs(col: jnp.ndarray, bits, dtype) -> list:
    """int64 → limb rows of ``bits`` widths in ``dtype`` (two's
    complement via uint64 — the recombine side is limb_sums_to_int64)."""
    u = col.astype(jnp.int64).view(jnp.uint64)
    out = []
    for b, sh in zip(bits, _limb_shifts(bits)):
        mask = jnp.uint64((1 << b) - 1)
        out.append(((u >> jnp.uint64(sh)) & mask).astype(dtype))
    return out


def int64_to_agg_limbs(col: jnp.ndarray) -> list:
    """int64 → five f32 13-bit limb rows (the dense MXU scheme)."""
    return _split_limbs(col, AGG_LIMB_BITS, jnp.float32)


def int64_to_seg_limbs(col: jnp.ndarray) -> list:
    """int64 → eight int32 8-bit limb rows (the sorted-segment scheme)."""
    return _split_limbs(col, SEG_LIMB_BITS, jnp.int32)


def limb_sums_to_int64(totals, bits) -> jnp.ndarray:
    """Recombine per-limb int64 SUM totals into the exact int64 sum.

    Each total is Σ rows of one limb — nonnegative, far below 2^63. The
    recombination Σ_l total_l << shift_l runs mod 2^64 (uint64), which
    equals the true int64 sum mod 2^64 — i.e. exactly the same value
    (and the same wraparound behavior) int64 addition produces."""
    u = jnp.zeros_like(totals[0], dtype=jnp.uint64)
    for t, sh in zip(totals, _limb_shifts(bits)):
        u = u + (t.astype(jnp.uint64) << jnp.uint64(sh))
    return u.view(jnp.int64)


def agg_limbs_to_int64(totals) -> jnp.ndarray:
    return limb_sums_to_int64(totals, AGG_LIMB_BITS)


# --------------------------------------------------------------------------
# Sorted-segment grouped aggregation (mid-cardinality): rows arrive sorted
# by group id; each tile runs one segmented scan with a carried
# (last-gid, partial-accumulator) pair in SMEM, flushing a group's total
# at the row where the NEXT group starts.
# --------------------------------------------------------------------------

_SEG_SENTINEL = 2147483647  # int32 max: gid of unselected / padded rows


def _shift1(x, d: int):
    """Shift right by ``d`` along the last axis, zero-filling — pad+slice
    (no wraparound gather), which lowers to cheap lane shifts on TPU."""
    widths = [(0, 0)] * (x.ndim - 1) + [(d, 0)]
    return jnp.pad(x, widths)[..., :x.shape[-1]]


def _sorted_seg_kernel(gid_ref, vals_ref, out_ref, carry_ref, lastg_ref):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        carry_ref[:] = jnp.zeros_like(carry_ref)
        lastg_ref[0] = jnp.int32(-1)

    g = gid_ref[:]                           # (T,) int32, nondecreasing
    v = vals_ref[:]                          # (R, T) int32, masked rows = 0
    t = g.shape[0]
    pos = jax.lax.broadcasted_iota(jnp.int32, (t, 1), 0)[:, 0]
    first = pos == 0
    gprev = jnp.where(first, lastg_ref[0], _shift1(g, 1))
    nb = g != gprev                          # segment-start flags (T,)
    carry = carry_ref[:]                     # (R, 1) running group partial

    # segmented inclusive scan (Hillis–Steele, log2 T static steps):
    # acc[r, j] = sum of v[r] over the current group's rows within this
    # tile, seeded with the carried partial when the first group continues
    # from the previous tile.
    acc = v + jnp.where((first & ~nb[0])[None, :], carry, 0)
    flg = nb
    d = 1
    while d < t:
        flg_s = jnp.pad(flg, (d, 0), constant_values=True)[:t]
        acc = acc + jnp.where(flg[None, :], 0, _shift1(acc, d))
        flg = flg | flg_s
        d *= 2

    # flush: at a segment start, emit the PREVIOUS group's completed
    # total (its running sum at the row before — the carry itself when
    # the boundary is the tile's first row).
    prev_acc = jnp.where(first[None, :], carry, _shift1(acc, 1))
    out_ref[:] = jnp.where(nb[None, :], prev_acc, 0)

    carry_ref[:] = acc[:, t - 1:t]
    lastg_ref[0] = g[t - 1]


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def sorted_seg_pallas(gid: jnp.ndarray, vals: jnp.ndarray,
                      tile: int = 2048, interpret: bool = False):
    """Tile-streamed segmented sum over SORTED group ids.

    gid: int32[N] nondecreasing (unselected/pad rows = sentinel);
    vals: int32[R, N] with masked rows zeroed. Returns flush int32[R, N]:
    column j holds the completed total of the group ENDING at row j-1
    wherever gid[j] != gid[j-1], else 0. The caller guarantees at least
    one trailing sentinel row so the last real group flushes."""
    r, n = vals.shape
    assert n % tile == 0, "pad rows to a tile multiple"
    grid = (n // tile,)
    from jax.experimental.pallas import tpu as pltpu

    return pl.pallas_call(
        _sorted_seg_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile,), lambda i: (i,)),
            pl.BlockSpec((r, tile), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((r, tile), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((r, n), jnp.int32),
        scratch_shapes=[pltpu.VMEM((r, 1), jnp.int32),
                        pltpu.SMEM((1,), jnp.int32)],
        interpret=interpret,
    )(gid, vals)


def sorted_segment_eligible(aggs, agg_values, n_rows: int) -> bool:
    """Shape/dtype gate for the fused sorted-segment path: SUM/AVG over
    integer-carried values (BIGINT, DECIMAL cents, INT) plus COUNT, at
    most MAX_SEG_ROWS input rows (the int32-accumulator proof). MIN/MAX,
    BOOL and float sums keep the XLA path."""
    if n_rows > MAX_SEG_ROWS:
        return False
    for spec in aggs:
        if spec.func == "count":
            continue
        if spec.func not in ("sum", "avg"):
            return False
        v = agg_values.get(spec.out_name)
        if v is None or not jnp.issubdtype(v.dtype, jnp.integer):
            return False
    return True


def sorted_segment_aggregate(key_cols, agg_values, aggs, sel,
                             out_capacity: int, tile: int = 2048,
                             interpret: bool = False):
    """Drop-in for kernels.group_aggregate on an eligible agg: same sort
    and boundary discipline, but ALL accumulations run in one fused
    Pallas pass (count row + 8-bit limb rows per sum, int32 exact).

    Returns (out_key_cols, out_agg_cols, out_sel, n_groups) with the
    XLA path's exact contract: groups in ascending key order, int sums
    bit-identical, avg the same f64 division of the same exact ints."""
    from cloudberry_tpu.exec import kernels as K

    # the sort/boundary/compaction scaffolding is SHARED with the XLA
    # path (kernels.group_layout) — the two aggregations must stay
    # bit-identical by contract, so the grouping rules live once
    lay = K.group_layout(key_cols, sel, out_capacity)
    gid = jnp.where(lay.s_sel,
                    jnp.cumsum(lay.new_grp.astype(jnp.int32)) - 1,
                    _SEG_SENTINEL)

    # value rows: count first, then 8 limb rows per sum/avg argument
    rows = [lay.s_sel.astype(jnp.int32)]
    layout = []  # (spec, first limb row, arg dtype)
    for spec in aggs:
        if spec.func == "count":
            continue
        v = agg_values[spec.out_name][lay.perm]
        v = jnp.where(lay.s_sel, v, jnp.zeros((), dtype=v.dtype))
        layout.append((spec, len(rows), v.dtype))
        rows.extend(int64_to_seg_limbs(v))
    vals = jnp.stack(rows)

    # pad to a tile multiple PLUS one whole sentinel tile: the boundary
    # at the first sentinel row flushes the last real group.
    pad = (-gid.shape[0]) % tile + tile
    gid_p = jnp.concatenate(
        [gid, jnp.full((pad,), _SEG_SENTINEL, jnp.int32)])
    vals_p = jnp.pad(vals, ((0, 0), (0, pad)))
    flush = sorted_seg_pallas(gid_p, vals_p, tile=tile,
                              interpret=interpret)

    # a group's total flushes at the row where the NEXT group begins —
    # lay.ends + 1, which for the last group is n_sel: always a real
    # position thanks to the sentinel tile
    n_groups, valid = lay.n_groups, lay.valid
    flushpos = jnp.where(valid, lay.ends + 1, 0)
    out_keys = lay.out_keys

    fg = flush[:, flushpos]  # (R, out_capacity) int32
    counts = jnp.where(valid, fg[0].astype(jnp.int64), 0)
    out_aggs = {}
    for spec, row0, dt in layout:
        totals = [fg[row0 + i].astype(jnp.int64)
                  for i in range(len(SEG_LIMB_BITS))]
        ssum = jnp.where(valid, limb_sums_to_int64(totals, SEG_LIMB_BITS),
                         0)
        if spec.func == "avg":
            out_aggs[spec.out_name] = ssum.astype(jnp.float64) \
                / jnp.maximum(counts, 1)
        else:
            out_aggs[spec.out_name] = ssum.astype(dt)
    for spec in aggs:
        if spec.func == "count":
            out_aggs[spec.out_name] = counts

    out_sel = jnp.arange(out_capacity) < n_groups
    return out_keys, out_aggs, out_sel, n_groups
