"""Pallas TPU kernels for the hottest executor op.

The single hottest loop in the engine is grouped aggregation over a scan
(Q1's shape: 6M rows → 6 cells × ~8 aggregates). The XLA formulation
(exec/kernels.group_aggregate_dense) is a chain of masked reductions; this
Pallas kernel fuses the whole thing into ONE pass over HBM:

  per row-tile (grid is sequential on TPU, so accumulating into the output
  block is safe):
      onehot = (gid == cell_ids) & sel          # (cells, TILE) in VMEM
      counts += sum(onehot, axis=1)
      sums   += values @ onehot.T               # (K, cells) on the MXU

The matmul accumulates in float32 on the MXU; exact int64-cent money sums
keep the XLA path. Gated by ``config.exec.use_pallas`` (wired through
Lowerer._dense_agg_pallas), default off until re-measured on hardware — the
dev TPU tunnel died mid-session. Decimal sums through this path round to
float32: acceptable for approximate analytics, not for money reconciliation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _dense_agg_kernel(gid_ref, vals_ref, sel_ref, out_ref, *, n_cells: int):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    g = gid_ref[:]                       # (TILE,)
    s = sel_ref[:]                       # (TILE,)
    v = vals_ref[:]                      # (K, TILE)
    cells = jax.lax.broadcasted_iota(jnp.int32, (n_cells, g.shape[0]), 0)
    onehot = (g[None, :] == cells) & s[None, :]          # (cells, TILE)
    oh_f = onehot.astype(jnp.float32)
    counts = jnp.sum(oh_f, axis=1)                       # (cells,)
    sums = jnp.dot(v, oh_f.T,
                   preferred_element_type=jnp.float32)   # (K, cells) on MXU
    out_ref[0, :] += counts
    out_ref[1:, :] += sums


@functools.partial(jax.jit, static_argnames=("n_cells", "tile", "interpret"))
def dense_agg_pallas(gid: jnp.ndarray, vals: jnp.ndarray, sel: jnp.ndarray,
                     n_cells: int, tile: int = 2048,
                     interpret: bool = False):
    """Fused one-pass grouped sum+count for a small static cell domain.

    gid: int32[N] cell per row; vals: float32[K, N]; sel: bool[N].
    Returns (counts f32[cells], sums f32[K, cells]).
    N must be a multiple of ``tile`` (caller pads; sel masks padding).
    """
    k, n = vals.shape
    assert n % tile == 0, "pad rows to a tile multiple"
    grid = (n // tile,)
    out = pl.pallas_call(
        functools.partial(_dense_agg_kernel, n_cells=n_cells),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile,), lambda i: (i,)),
            pl.BlockSpec((k, tile), lambda i: (0, i)),
            pl.BlockSpec((tile,), lambda i: (i,)),
        ],
        out_specs=pl.BlockSpec((k + 1, n_cells), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((k + 1, n_cells), jnp.float32),
        interpret=interpret,
    )(gid, vals, sel)
    return out[0], out[1:]
