"""Relational kernels over fixed-capacity column batches — all jittable.

Design discipline (SURVEY.md §7.3): XLA requires static shapes, so
- filters AND into the selection mask (no compaction);
- group-by is sort-based: lexsort → boundary flags → segment reductions.
  Exact (no hash collisions), and sort/scan map well onto the VPU;
- joins are "sorted-build lookup": sort the unique (PK) side, binary-search
  probes with ``searchsorted``, gather payloads. This covers every PK–FK join
  shape in TPC-H; a many-to-many expansion kernel is planned separately.

These replace the reference's per-tuple executor nodes: nodeAgg.c,
nodeHash.c/nodeHashjoin.c, nodeSort.c, nodeLimit.c — pointer-chasing hash
tables have no TPU analog, sort+segment ops are the native formulation.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

Columns = dict[str, jnp.ndarray]

# --------------------------------------------------------------------------
# key normalization: every key column becomes a sortable uint64 whose order
# matches SQL order (ints/dates: offset; floats: IEEE total-order trick;
# strings: rank table gathered by caller).
# --------------------------------------------------------------------------

_SIGN64 = jnp.uint64(1) << jnp.uint64(63)


def sort_key_u64(col: jnp.ndarray) -> jnp.ndarray:
    """Map a column to uint64 preserving SQL ascending order."""
    if col.dtype == jnp.bool_:
        return col.astype(jnp.uint64)
    if col.dtype == jnp.float32:
        # IEEE total-order trick in 32 bits, then widen — avoids the f64
        # bitcast that the TPU backend cannot compile.
        bits = col.view(jnp.uint32)
        mask = jnp.where(bits >> jnp.uint32(31) != 0,
                         jnp.uint32(0xFFFFFFFF), jnp.uint32(1) << jnp.uint32(31))
        return (bits ^ mask).astype(jnp.uint64)
    if col.dtype == jnp.float64:
        # The TPU backend cannot compile a direct f64→u64 bitcast, but it
        # CAN bitcast f64 to two u32 words (bitcast_convert_type to a
        # narrower type appends a minor dimension, index 0 = least
        # significant word — XLA semantics). Reassemble the IEEE bits
        # with u64 shifts (u64 ARITHMETIC is supported/emulated), then
        # apply the same total-order mask as f32.
        words = jax.lax.bitcast_convert_type(col, jnp.uint32)
        bits = (words[..., 1].astype(jnp.uint64) << jnp.uint64(32)) \
            | words[..., 0].astype(jnp.uint64)
        mask = jnp.where(bits >> jnp.uint64(63) != 0,
                         jnp.uint64(0xFFFFFFFFFFFFFFFF), _SIGN64)
        return bits ^ mask
    return col.astype(jnp.int64).view(jnp.uint64) ^ _SIGN64


_U64_MAX = jnp.uint64(0xFFFFFFFFFFFFFFFF)


def key_ranges(
    keys: Sequence[jnp.ndarray], sel: jnp.ndarray
) -> list[tuple[jnp.ndarray, jnp.ndarray]]:
    """Per-column (lo, span) over the SELECTED rows, in u64 key space."""
    out = []
    for k in keys:
        u = sort_key_u64(k)
        lo = jnp.min(jnp.where(sel, u, _U64_MAX))
        hi = jnp.max(jnp.where(sel, u, jnp.uint64(0)))
        span = jnp.maximum(hi - lo, jnp.uint64(0)) + jnp.uint64(1)
        out.append((lo, span))
    return out


def pack_with_ranges(
    keys: Sequence[jnp.ndarray],
    ranges: Sequence[tuple[jnp.ndarray, jnp.ndarray]],
) -> jnp.ndarray:
    """Pack key columns into ONE order-preserving uint64 using given ranges.

    Exact when the product of spans fits 64 bits (always true for TPC-H key
    columns). Values outside a range pack to the all-ones sentinel, which
    never equals an in-range pack — so cross-side packing (join probe against
    build-side ranges) stays exact rather than aliasing.
    """
    packed = jnp.zeros(keys[0].shape, dtype=jnp.uint64)
    oob = jnp.zeros(keys[0].shape, dtype=jnp.bool_)
    for k, (lo, span) in zip(keys, ranges):
        u = sort_key_u64(k)
        oob = oob | (u < lo) | (u - lo >= span)
        packed = packed * span + jnp.clip(u - lo, jnp.uint64(0), span - jnp.uint64(1))
    return jnp.where(oob, _U64_MAX, packed)


def pack_keys(keys: Sequence[jnp.ndarray], sel: jnp.ndarray) -> jnp.ndarray:
    """Pack multiple key columns of one batch into order-preserving uint64
    (selected rows are in-range by construction; others → sentinel)."""
    return pack_with_ranges(keys, key_ranges(keys, sel))


_U32_MAX = jnp.uint32(0xFFFFFFFF)


def downcast32(packed: jnp.ndarray) -> jnp.ndarray:
    """Narrow packed u64 keys to u32 when the PLANNER proved (from table
    min/max statistics) that every in-range pack fits 32 bits — TPU sorts
    and searches run ~2× faster on 32-bit lanes. The u64 sentinel maps to
    the u32 sentinel; real packs are < 2^32-1 by the planner's proof, so
    no aliasing is possible."""
    return jnp.where(packed == _U64_MAX, _U32_MAX,
                     packed.astype(jnp.uint32))


def sort_indices(
    keys: Sequence[jnp.ndarray],
    sel: jnp.ndarray,
    descending: Sequence[bool] | None = None,
) -> jnp.ndarray:
    """Permutation putting selected rows first, ordered by keys (lexsort).

    keys[0] is the PRIMARY key (SQL ORDER BY first column)."""
    n = sel.shape[0]
    desc = list(descending) if descending is not None else [False] * len(keys)
    cols = []
    for k, d in zip(keys, desc):
        u = sort_key_u64(k)
        cols.append(~u if d else u)
    # lexsort: LAST key is primary ⇒ reverse; unselected rows go last.
    order = jnp.lexsort(tuple(reversed(cols)) + (~sel,))
    return order


# --------------------------------------------------------------------------
# group-by
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class AggSpec:
    """One aggregate: func ∈ {sum,count,min,max,avg}; count with arg=None is
    COUNT(*). ``values`` are pre-evaluated argument arrays (None for *)."""
    func: str
    out_name: str


@dataclass
class GroupLayout:
    """Sorted-group scaffolding shared by the XLA sort-based aggregation
    and the fused Pallas sorted-segment kernel — ONE implementation of
    the sort, boundary detection, and start compaction, so the two paths
    cannot diverge on a grouping rule (their bit-identity is a contract:
    the bench A/B gate and the tiled-merge parity both rely on it)."""

    names: list
    perm: jnp.ndarray        # sort permutation (selected rows first)
    s_sel: jnp.ndarray       # selection in sorted order
    s_keys: Columns          # key columns in sorted order
    new_grp: jnp.ndarray     # group-start flags over sorted selected rows
    n_groups: jnp.ndarray
    n_sel: jnp.ndarray
    starts: jnp.ndarray      # per output slot: group start row (0 pad)
    ends: jnp.ndarray        # per output slot: group end row (0 pad)
    valid: jnp.ndarray       # slot < n_groups
    out_keys: Columns        # compacted key columns (zeros on pad)


def group_layout(key_cols: Columns, sel: jnp.ndarray,
                 out_capacity: int) -> GroupLayout:
    names = list(key_cols)
    key_list = [key_cols[n] for n in names]
    perm = sort_indices(key_list, sel)
    s_sel = sel[perm]
    s_keys = {n: key_cols[n][perm] for n in names}

    new_grp = jnp.zeros_like(s_sel)
    for n in names:
        k = s_keys[n]
        new_grp = new_grp | (k != jnp.roll(k, 1))
    new_grp = new_grp.at[0].set(True)
    new_grp = new_grp & s_sel

    n_groups = jnp.sum(new_grp.astype(jnp.int32))
    n_sel = jnp.sum(s_sel.astype(jnp.int32))

    # boundary positions compact to the front via a stable bool argsort
    starts_all = jnp.argsort(~new_grp, stable=True)
    g = jnp.arange(out_capacity)
    starts = starts_all[jnp.clip(g, 0, starts_all.shape[0] - 1)]
    next_start = starts_all[jnp.clip(g + 1, 0, starts_all.shape[0] - 1)]
    valid = g < n_groups
    ends = jnp.where(g + 1 < n_groups, next_start - 1, n_sel - 1)
    starts = jnp.where(valid, starts, 0)
    ends = jnp.where(valid, ends, 0)

    out_keys: Columns = {}
    for n in names:
        out_keys[n] = jnp.where(valid, s_keys[n][starts],
                                jnp.zeros((), dtype=s_keys[n].dtype))
    return GroupLayout(names, perm, s_sel, s_keys, new_grp, n_groups,
                       n_sel, starts, ends, valid, out_keys)


def group_aggregate(
    key_cols: Columns,
    agg_values: dict[str, Optional[jnp.ndarray]],
    aggs: Sequence[AggSpec],
    sel: jnp.ndarray,
    out_capacity: int,
) -> tuple[Columns, Columns, jnp.ndarray, jnp.ndarray]:
    """Sort-based grouped aggregation (nodeAgg.c analog).

    Returns (out_key_cols, out_agg_cols, out_sel, n_groups); groups are
    emitted in ascending key order (a free ORDER BY for the common agg→sort
    pattern). ``n_groups`` is the TRUE group count — the executor must check
    it against out_capacity after the run: groups beyond capacity are clipped
    into the last slot, so n_groups > out_capacity means wrong results and is
    an error, never silent (the capacity-flow-control discipline of
    ic_udpifc.c:3018 applied to shapes).

    Scatter-free segmented reduction (TPU serializes big scatters): every
    per-group aggregate is a cumulative-sum DIFFERENCE between consecutive
    group boundaries — pure sort/scan/gather, the VPU formulation.
    """
    lay = group_layout(key_cols, sel, out_capacity)
    names, key_list = lay.names, [key_cols[n] for n in lay.names]
    perm, s_sel = lay.perm, lay.s_sel
    n_groups, n_sel = lay.n_groups, lay.n_sel
    starts, ends, valid = lay.starts, lay.ends, lay.valid
    out_keys = lay.out_keys

    def seg_sum(vals):
        csum = jnp.cumsum(vals)
        c0 = jnp.concatenate([jnp.zeros((1,), dtype=csum.dtype), csum])
        return jnp.where(valid, c0[ends + 1] - c0[starts], 0)

    counts = jnp.where(valid, (ends - starts + 1), 0).astype(jnp.int64)

    extreme_perm_cache: dict[bool, jnp.ndarray] = {}

    def seg_extreme(v_unpermuted, want_max: bool):
        # re-sort with the value as the last key: each group's extreme lands
        # on its boundary row (one extra sort only when min/max is used)
        if want_max not in extreme_perm_cache:
            extreme_perm_cache[want_max] = sort_indices(
                key_list + [v_unpermuted], sel,
                descending=[False] * len(key_list) + [want_max])
        p2 = extreme_perm_cache[want_max]
        return v_unpermuted[p2][starts]

    out_aggs: Columns = {}
    for spec in aggs:
        v = agg_values.get(spec.out_name)
        if spec.func == "count":
            out = counts
        elif spec.func == "count_nn":
            out = seg_sum((s_sel & v[perm]).astype(jnp.int64))
        elif spec.func == "sum":
            out = seg_sum(jnp.where(s_sel, v[perm], 0))
        elif spec.func == "min":
            ident = _dtype_max(v.dtype)
            out = jnp.where(valid & (counts > 0),
                            seg_extreme(v, want_max=False), ident)
        elif spec.func == "max":
            ident = _dtype_min(v.dtype)
            out = jnp.where(valid & (counts > 0),
                            seg_extreme(v, want_max=True), ident)
        elif spec.func == "avg":
            # integer-carried values (BIGINT, DECIMAL cents) sum EXACTLY
            # in int64 before the f64 division — an f64 cumsum rounds
            # once prefixes pass 2^53, and the fused Pallas path (which
            # divides the exact int64 sum) must stay bit-identical. The
            # widen matters for INT32/DATE too: cumsum keeps the input
            # dtype, so an un-widened int32 numerator would wrap at 2^31.
            masked = jnp.where(s_sel, v[perm], 0).astype(
                jnp.int64 if jnp.issubdtype(v.dtype, jnp.integer)
                else jnp.float64)
            out = seg_sum(masked).astype(jnp.float64) \
                / jnp.maximum(counts, 1)
        else:
            raise NotImplementedError(spec.func)
        out_aggs[spec.out_name] = out

    out_sel = jnp.arange(out_capacity) < n_groups
    return out_keys, out_aggs, out_sel, n_groups


def group_aggregate_dense(
    gid: jnp.ndarray,
    n_cells: int,
    agg_values: dict[str, Optional[jnp.ndarray]],
    aggs: Sequence[AggSpec],
    sel: jnp.ndarray,
    strategy: str = "reduce",
) -> tuple[Columns, jnp.ndarray]:
    """Perfect-hash grouped aggregation for small, statically-known key
    domains (e.g. dictionary-coded strings: Q1's returnflag × linestatus).

    strategy='reduce' (TPU): unrolled per-cell masked tree-reductions.
    strategy='segment' (CPU): scatter-based segment ops.

    No sort and — crucially — no scatter: XLA lowers large scatters to a
    serialized update loop on TPU (measured ~150ms per 1.8M-row segment_sum),
    while an unrolled per-cell masked tree-reduction is a fused VPU sweep.
    Exact for int64 (tree reduction of exact adds). Returns (agg columns
    indexed by cell id, occupancy mask); key reconstruction from cell id is
    the caller's job.
    """
    gid = jnp.where(sel, jnp.clip(gid, 0, n_cells - 1), n_cells)
    out: Columns = {}
    if strategy == "segment":
        # scatter-based: best on CPU, where XLA emits a tight update loop
        counts = jax.ops.segment_sum(sel.astype(jnp.int64), gid,
                                     num_segments=n_cells + 1)[:n_cells]
        seg = lambda vv: jax.ops.segment_sum(
            vv, gid, num_segments=n_cells + 1)[:n_cells]
        smin = lambda vv: jax.ops.segment_min(
            vv, gid, num_segments=n_cells + 1)[:n_cells]
        smax = lambda vv: jax.ops.segment_max(
            vv, gid, num_segments=n_cells + 1)[:n_cells]
        for spec in aggs:
            v = agg_values.get(spec.out_name)
            if spec.func == "count":
                out[spec.out_name] = counts
            elif spec.func == "count_nn":
                out[spec.out_name] = seg((sel & v).astype(jnp.int64))
            elif spec.func == "sum":
                out[spec.out_name] = seg(jnp.where(sel, v, 0))
            elif spec.func == "min":
                out[spec.out_name] = smin(jnp.where(sel, v, _dtype_max(v.dtype)))
            elif spec.func == "max":
                out[spec.out_name] = smax(jnp.where(sel, v, _dtype_min(v.dtype)))
            elif spec.func == "avg":
                # int64 widen: segment_sum keeps the input dtype, so an
                # int32 numerator would wrap (see group_aggregate)
                masked = jnp.where(sel, v, 0).astype(
                    jnp.int64 if jnp.issubdtype(v.dtype, jnp.integer)
                    else jnp.float64)
                out[spec.out_name] = seg(masked).astype(jnp.float64) \
                    / jnp.maximum(counts, 1)
            else:
                raise NotImplementedError(spec.func)
        return out, counts > 0
    cell_masks = [gid == c for c in range(n_cells)]
    counts = jnp.stack([m.sum(dtype=jnp.int64) for m in cell_masks])
    for spec in aggs:
        v = agg_values.get(spec.out_name)
        if spec.func == "count":
            out[spec.out_name] = counts
        elif spec.func == "count_nn":
            out[spec.out_name] = jnp.stack(
                [(m & v).sum(dtype=jnp.int64) for m in cell_masks])
        elif spec.func == "sum":
            out[spec.out_name] = jnp.stack(
                [jnp.where(m, v, 0).sum() for m in cell_masks])
        elif spec.func == "min":
            big = _dtype_max(v.dtype)
            out[spec.out_name] = jnp.stack(
                [jnp.where(m, v, big).min() for m in cell_masks])
        elif spec.func == "max":
            small = _dtype_min(v.dtype)
            out[spec.out_name] = jnp.stack(
                [jnp.where(m, v, small).max() for m in cell_masks])
        elif spec.func == "avg":
            # exact int64 numerator for integer values (see group_aggregate)
            acc_dt = jnp.int64 if jnp.issubdtype(v.dtype, jnp.integer) \
                else jnp.float64
            s = jnp.stack([jnp.where(m, v, 0).sum(dtype=acc_dt)
                           for m in cell_masks])
            out[spec.out_name] = s.astype(jnp.float64) \
                / jnp.maximum(counts, 1)
        else:
            raise NotImplementedError(spec.func)
    return out, counts > 0


def global_aggregate(
    agg_values: dict[str, Optional[jnp.ndarray]],
    aggs: Sequence[AggSpec],
    sel: jnp.ndarray,
) -> Columns:
    """Ungrouped aggregation → one-row columns (shape (1,))."""
    out: Columns = {}
    for spec in aggs:
        v = agg_values.get(spec.out_name)
        if spec.func == "count":
            out[spec.out_name] = jnp.sum(sel.astype(jnp.int64))[None]
        elif spec.func == "count_nn":
            out[spec.out_name] = jnp.sum((sel & v).astype(jnp.int64))[None]
        elif spec.func == "sum":
            out[spec.out_name] = jnp.sum(jnp.where(sel, v, 0))[None]
        elif spec.func == "min":
            out[spec.out_name] = jnp.min(
                jnp.where(sel, v, _dtype_max(v.dtype)))[None]
        elif spec.func == "max":
            out[spec.out_name] = jnp.max(
                jnp.where(sel, v, _dtype_min(v.dtype)))[None]
        elif spec.func == "avg":
            # exact int64 numerator for integer values (see group_aggregate)
            masked = jnp.where(sel, v, 0).astype(
                jnp.int64 if jnp.issubdtype(v.dtype, jnp.integer)
                else jnp.float64)
            s = jnp.sum(masked).astype(jnp.float64)
            c = jnp.sum(sel.astype(jnp.int64))
            out[spec.out_name] = (s / jnp.maximum(c, 1))[None]
        else:
            raise NotImplementedError(spec.func)
    return out


def _dtype_max(dt):
    return jnp.asarray(jnp.finfo(dt).max if jnp.issubdtype(dt, jnp.floating)
                       else jnp.iinfo(dt).max, dtype=dt)


def _dtype_min(dt):
    return jnp.asarray(jnp.finfo(dt).min if jnp.issubdtype(dt, jnp.floating)
                       else jnp.iinfo(dt).min, dtype=dt)


# --------------------------------------------------------------------------
# join: sorted-build lookup (PK–FK)
# --------------------------------------------------------------------------


def build_sort(
    build_key: Sequence[jnp.ndarray],
    build_sel: jnp.ndarray,
    bits: int = 64,
) -> tuple[jnp.ndarray, jnp.ndarray, list]:
    """The build side's sort scaffolding: (order, sorted packed keys,
    packing ranges). ONE implementation shared by the in-program joins
    and the host-side join-index cache (exec/joinindex.py mirrors it in
    numpy) — the two must agree bit-for-bit, including stable tie order,
    for cached indexes to be drop-in replacements."""
    ranges = key_ranges(list(build_key), build_sel)
    kb = pack_with_ranges(list(build_key), ranges)
    big = _U32_MAX if bits == 32 else _U64_MAX
    if bits == 32:
        kb = downcast32(kb)
    kb_masked = jnp.where(build_sel, kb, big)
    order = jnp.argsort(kb_masked)
    return order, kb_masked[order], ranges


def dup_check(kb_sorted: jnp.ndarray, bits: int = 64) -> jnp.ndarray:
    """Duplicate build keys, for free off the already-sorted keys (the
    sentinel — unselected/out-of-range rows — never counts)."""
    big = _U32_MAX if bits == 32 else _U64_MAX
    if kb_sorted.shape[0] <= 1:
        return jnp.asarray(False)
    return ((kb_sorted[1:] == kb_sorted[:-1])
            & (kb_sorted[1:] != big)).any()


def join_lookup_sorted(
    order: jnp.ndarray,
    kb_sorted: jnp.ndarray,
    ranges: Sequence[tuple[jnp.ndarray, jnp.ndarray]],
    probe_key: Sequence[jnp.ndarray],
    probe_sel: jnp.ndarray,
    bits: int = 64,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """join_lookup against a PRE-SORTED build (computed in-program or fed
    from the session join-index cache): probe packing + binary search
    only, no argsort."""
    kp = pack_with_ranges(list(probe_key), ranges)
    big = _U64_MAX
    if bits == 32:
        kp, big = downcast32(kp), _U32_MAX
    pos = jnp.searchsorted(kb_sorted, kp)
    pos_c = jnp.clip(pos, 0, kb_sorted.shape[0] - 1)
    # kp == sentinel marks out-of-range probes; excluding it also makes the
    # empty-build case (kb_sorted all sentinel) correctly match nothing.
    matched = (kb_sorted[pos_c] == kp) & probe_sel & (kp != big)
    build_row = order[pos_c].astype(jnp.int32)
    return build_row, matched, dup_check(kb_sorted, bits)


def join_lookup(
    build_key: Sequence[jnp.ndarray],
    build_sel: jnp.ndarray,
    probe_key: Sequence[jnp.ndarray],
    probe_sel: jnp.ndarray,
    bits: int = 64,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """For each probe row: index of the matching build row, and a match mask.

    Requires the build side unique on the key (the planner puts the PK side
    here — same choice nodeHash.c makes for the hash side). Exact: compares
    packed keys, and packing is order-preserving/injective for in-range ints.
    ``bits=32`` (planner-proven via table stats) narrows the packed keys so
    the sort/search run on 32-bit lanes. Returns (build_row_idx
    int32[cap_p], matched bool[cap_p], has_dup scalar bool — duplicate
    build keys detected, for free off the already-sorted keys).
    """
    order, kb_sorted, ranges = build_sort(build_key, build_sel, bits)
    return join_lookup_sorted(order, kb_sorted, ranges, probe_key,
                              probe_sel, bits)


def gather_payload(cols: Columns, idx: jnp.ndarray, matched: jnp.ndarray) -> Columns:
    """Gather build-side payload columns to probe rows (0 where unmatched)."""
    out = {}
    for name, c in cols.items():
        g = jnp.take(c, idx, axis=0)
        out[name] = jnp.where(matched, g, jnp.zeros((), dtype=c.dtype))
    return out


def join_expand(
    build_key: Sequence[jnp.ndarray],
    build_sel: jnp.ndarray,
    probe_key: Sequence[jnp.ndarray],
    probe_sel: jnp.ndarray,
    out_capacity: int,
    bits: int = 64,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Many-to-many join: emit ONE OUTPUT ROW PER MATCH PAIR.

    Sorted-build range lookup: probe row i matches the build range
    [start_i, end_i); match pairs are laid out consecutively by probe row
    (offsets = cumsum of per-probe match counts), and output slot j maps back
    to (probe row, k-th match) by binary search on the offsets — fully
    vectorized, no data-dependent shapes. Total matches beyond
    ``out_capacity`` are reported, never silently dropped.

    Returns (probe_row[out_cap], build_row[out_cap], out_sel[out_cap],
             matched[probe_cap] (per-probe any-match, for outer joins),
             total_matches scalar).
    """
    order, kb_sorted, ranges = build_sort(build_key, build_sel, bits)
    return join_expand_sorted(order, kb_sorted, ranges, probe_key,
                              probe_sel, out_capacity, bits)


def join_expand_sorted(
    order: jnp.ndarray,
    kb_sorted: jnp.ndarray,
    ranges: Sequence[tuple[jnp.ndarray, jnp.ndarray]],
    probe_key: Sequence[jnp.ndarray],
    probe_sel: jnp.ndarray,
    out_capacity: int,
    bits: int = 64,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """join_expand against a PRE-SORTED build (see join_lookup_sorted)."""
    kp = pack_with_ranges(list(probe_key), ranges)
    big = _U64_MAX
    if bits == 32:
        kp, big = downcast32(kp), _U32_MAX

    start = jnp.searchsorted(kb_sorted, kp, side="left")
    end = jnp.searchsorted(kb_sorted, kp, side="right")
    ok = probe_sel & (kp != big)
    # overflow hardening: searchsorted returns a NARROW index dtype, and a
    # cumsum KEEPS its input dtype — per-probe counts must widen to int64
    # BEFORE the prefix sum so the total-vs-capacity overflow check can
    # never itself wrap on a large fanout (capacities past 2^16 rows with
    # hot keys multiply fast; the check is the last line of defense and
    # must be exact at any count)
    cnt = jnp.where(ok, (end - start).astype(jnp.int64), jnp.int64(0))
    matched = cnt > 0

    offsets = jnp.cumsum(cnt)
    total = offsets[-1] if cnt.shape[0] else jnp.asarray(0, jnp.int64)
    j = jnp.arange(out_capacity, dtype=jnp.int64)
    # probe row for output slot j: first i with offsets[i] > j
    pi = jnp.searchsorted(offsets, j, side="right")
    pi_c = jnp.clip(pi, 0, cnt.shape[0] - 1)
    base = offsets[pi_c] - cnt[pi_c]          # first slot of probe row pi
    k = j - base
    out_sel = j < total
    build_pos = jnp.clip(start[pi_c].astype(jnp.int64) + k, 0,
                         kb_sorted.shape[0] - 1)
    build_row = order[build_pos].astype(jnp.int32)
    return pi_c.astype(jnp.int32), build_row, out_sel, matched, total


# --------------------------------------------------------------------------
# bloom digest — runtime join filters (plan/nodes.py PRuntimeFilter
# mode="digest"): a fixed-size bitmap over RANGE-FREE key hashes, so every
# segment's insertions agree on bit positions without a collective range
# reduction first. The digest (per-key u64 min/max + the bitmap words)
# rides ONE tiny all_gather; probe rows failing the min/max or bloom test
# drop BEFORE their redistribute. False positives only let extra rows
# through — the join itself stays exact.
# --------------------------------------------------------------------------


_MIX_M1 = jnp.uint64(0xBF58476D1CE4E5B9)
_MIX_M2 = jnp.uint64(0x94D049BB133111EB)
_MIX_SEED = jnp.uint64(0x9E3779B97F4A7C15)


def _mix64(x: jnp.ndarray) -> jnp.ndarray:
    """splitmix64 finalizer — u64 arithmetic only (TPU-legal)."""
    x = (x ^ (x >> jnp.uint64(30))) * _MIX_M1
    x = (x ^ (x >> jnp.uint64(27))) * _MIX_M2
    return x ^ (x >> jnp.uint64(31))


def bloom_hash(key_u64s: Sequence[jnp.ndarray]) -> jnp.ndarray:
    """One u64 hash per row over the sort_key_u64 forms of the key tuple.
    Deliberately independent of packing ranges: equal key tuples hash
    identically on every segment, unlike packed keys whose ranges are
    fragment-local."""
    h = jnp.broadcast_to(_MIX_SEED, key_u64s[0].shape)
    for u in key_u64s:
        h = _mix64(h ^ u)
    return h


def bloom_bits_pow2(bits: int) -> int:
    """Clamp a configured bitmap size to a power of two ≥ 64 (word math
    and the position mask rely on it)."""
    return 1 << max(6, int(bits - 1).bit_length())


def _bloom_positions(h: jnp.ndarray, bits: int, k: int) -> list:
    """k bit positions per row sliced from ONE 64-bit hash — disjoint
    slices while they fit, overlapping (still a valid bloom) beyond."""
    lb = max(bits.bit_length() - 1, 1)
    step = max((64 - lb) // max(k, 1), 1)
    mask = jnp.uint64(bits - 1)
    return [((h >> jnp.uint64(i * step)) & mask).astype(jnp.int32)
            for i in range(max(k, 1))]


def bloom_build(key_u64s: Sequence[jnp.ndarray], sel: jnp.ndarray,
                bits: int, k: int) -> jnp.ndarray:
    """(bits // 32,) uint32 bitmap over the SELECTED rows' key hashes.
    Built as a bool bitmap (scatter of ones — the bitmap is tiny) then
    packed to words for the wire; cross-segment combination is a bitwise
    OR of the words."""
    h = bloom_hash(key_u64s)
    bm = jnp.zeros((bits,), dtype=jnp.bool_)
    for pos in _bloom_positions(h, bits, k):
        idx = jnp.where(sel, pos, bits)
        bm = bm.at[idx].set(True, mode="drop")
    w = bm.reshape(bits // 32, 32).astype(jnp.uint32)
    return jnp.sum(w << jnp.arange(32, dtype=jnp.uint32), axis=1,
                   dtype=jnp.uint32)


def bloom_test(words: jnp.ndarray, key_u64s: Sequence[jnp.ndarray],
               bits: int, k: int) -> jnp.ndarray:
    """Per-row membership test against a packed bitmap: True = possibly
    present (false positives possible), False = definitely absent."""
    h = bloom_hash(key_u64s)
    ok = jnp.ones(h.shape, dtype=jnp.bool_)
    for pos in _bloom_positions(h, bits, k):
        w = words[pos >> 5]
        ok = ok & (((w >> (pos & 31).astype(jnp.uint32))
                    & jnp.uint32(1)) != 0)
    return ok


# --------------------------------------------------------------------------
# motion wire format: pack every column of a row set (plus the row-validity
# mask) into ONE (rows, W) uint32 buffer, so each motion costs exactly one
# collective instead of one per column. Restoration is bit-identical: 4-byte
# dtypes bitcast to a u32 word, 8-byte dtypes to two words (the TPU-legal
# formulation — a direct f64↔u64 bitcast does not compile there, u32 word
# pairs do; see sort_key_u64), and bool columns ride as BITS of the leading
# flag word(s) next to the validity bit, so a shuffle ships no dedicated
# bool buffers at all.
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class WireLayout:
    """Static description of one packed wire buffer. Word 0 bit 0 is the
    row-validity bit; bool columns occupy the following bits (spilling
    into additional flag words past 32 bools); wider columns get 1 or 2
    whole words each, in sorted-name order so any two sessions that agree
    on the column dict agree on the layout."""

    names: tuple          # all column names, layout order (bools first)
    dtypes: tuple         # jnp/np dtype per name
    flag_bits: dict       # bool column name -> (word, bit)
    offsets: dict         # non-bool column name -> first word index
    n_flag_words: int     # leading words carrying validity + bool bits
    width: int            # W: total uint32 words per row

    def row_bytes(self) -> int:
        return 4 * self.width

    def payload_bytes(self) -> int:
        """Bytes of actual column data per row (excludes flag-word
        padding) — the numerator of wire efficiency."""
        bits = 1  # validity
        total = 0
        for dt in self.dtypes:
            if np.dtype(dt) == np.bool_:
                bits += 1
            else:
                total += np.dtype(dt).itemsize
        return total + (bits + 7) // 8


# the packed wire's declared dtype contract (the int64/DECIMAL limb
# convention bitcasts whole u32 words): a motion may ship bool columns
# (flag bits) and columns of exactly these byte widths. The plan
# verifier (plan/verify.py motion-wire-dtype) checks every motion's
# schema against this BEFORE execution; wire_layout enforces it at
# lowering time.
WIRE_ITEMSIZES = (4, 8)


def wire_layout(col_dtypes: dict) -> WireLayout:
    """Layout for a column dict (name -> dtype). Deterministic: bools in
    sorted order take flag bits, then the remaining columns in sorted
    order take whole words."""
    bools = sorted(n for n, dt in col_dtypes.items()
                   if np.dtype(dt) == np.bool_)
    wides = sorted(n for n, dt in col_dtypes.items()
                   if np.dtype(dt) != np.bool_)
    n_flag_words = max(1, -(-(1 + len(bools)) // 32))
    flag_bits = {}
    for i, n in enumerate(bools):
        flag_bits[n] = ((1 + i) // 32, (1 + i) % 32)
    offsets = {}
    w = n_flag_words
    for n in wides:
        size = np.dtype(col_dtypes[n]).itemsize
        if size not in WIRE_ITEMSIZES:
            raise NotImplementedError(
                f"wire pack: column {n!r} has {size}-byte dtype "
                f"{col_dtypes[n]}; only 4/8-byte dtypes and bool ship")
        offsets[n] = w
        w += size // 4
    names = tuple(bools + wides)
    dtypes = tuple(col_dtypes[n] for n in names)
    return WireLayout(names, dtypes, flag_bits, offsets, n_flag_words, w)


def pack_wire(cols: Columns, sel: jnp.ndarray,
              layout: WireLayout) -> jnp.ndarray:
    """(rows, W) uint32 buffer carrying every column and the validity
    mask. An all-zero row unpacks as invalid — scattered send buffers
    need no separate initialization for unused slots."""
    rows = sel.shape[0]
    words: list = [None] * layout.width
    flags = [jnp.zeros((rows,), jnp.uint32)
             for _ in range(layout.n_flag_words)]
    flags[0] = sel.astype(jnp.uint32)
    for name, (w, bit) in layout.flag_bits.items():
        flags[w] = flags[w] | (cols[name].astype(jnp.uint32)
                               << jnp.uint32(bit))
    for i, f in enumerate(flags):
        words[i] = f
    for name, off in layout.offsets.items():
        c = cols[name]
        u = jax.lax.bitcast_convert_type(c, jnp.uint32)
        if u.ndim == c.ndim:        # 4-byte dtype: one word
            words[off] = u
        else:                       # 8-byte dtype: two words (lo, hi)
            words[off] = u[..., 0]
            words[off + 1] = u[..., 1]
    return jnp.stack(words, axis=-1)


def unpack_wire(buf: jnp.ndarray,
                layout: WireLayout) -> tuple[Columns, jnp.ndarray]:
    """Inverse of pack_wire: bit-identical columns + the validity mask."""
    sel = (buf[..., 0] & jnp.uint32(1)).astype(jnp.bool_)
    cols: Columns = {}
    for name, dt in zip(layout.names, layout.dtypes):
        if np.dtype(dt) == np.bool_:
            w, bit = layout.flag_bits[name]
            cols[name] = ((buf[..., w] >> jnp.uint32(bit))
                          & jnp.uint32(1)).astype(jnp.bool_)
            continue
        off = layout.offsets[name]
        if np.dtype(dt).itemsize == 4:
            cols[name] = jax.lax.bitcast_convert_type(buf[..., off], dt)
        else:
            pair = jnp.stack([buf[..., off], buf[..., off + 1]], axis=-1)
            cols[name] = jax.lax.bitcast_convert_type(pair, dt)
    return cols, sel


def wire_rebucket(rows: jnp.ndarray, key: jnp.ndarray,
                  valid: jnp.ndarray, n_buckets: int,
                  cap: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Permutation re-bucket of PACKED wire rows — the two-level
    motion's host-combine primitive (no unpack: rows move as opaque
    (W,) u32 word vectors).

    ``rows`` (n, W) are wire rows, ``key`` (n,) the integer bucket for
    each row, ``valid`` which rows carry data. Valid rows compact
    stably (by position) into their bucket's slots; all-zero fill
    (which unpacks as invalid by the wire convention) pads the rest.
    Returns ((n_buckets, cap, W) buffer, (n_buckets,) int32 demand) —
    rows past ``cap`` are DROPPED FROM THE BUFFER but counted, so the
    caller's overflow check (demand > cap) fires before any result
    could ship; the capacity-ladder retry then promotes the rung.
    Same slot-scatter discipline as the redistribute lowering."""
    n = rows.shape[0]
    k = jnp.where(valid, key, n_buckets)
    counts = jax.ops.segment_sum(valid.astype(jnp.int32), k,
                                 num_segments=n_buckets + 1)[:n_buckets]
    order = jnp.argsort(k)          # stable: ties keep position order
    sorted_k = k[order]
    start = jnp.searchsorted(sorted_k, jnp.arange(n_buckets))
    rank = jnp.arange(n) - start[jnp.clip(sorted_k, 0, n_buckets - 1)]
    ok = (sorted_k < n_buckets) & (rank < cap)
    slot = jnp.where(ok, sorted_k * cap + rank, n_buckets * cap)
    out = jnp.zeros((n_buckets * cap, rows.shape[1]), dtype=rows.dtype)
    out = out.at[slot].set(rows[order], mode="drop")
    return out.reshape(n_buckets, cap, rows.shape[1]), counts


def rung_up(n: int) -> int:
    """Round a bucket capacity up to its ladder rung (the next power of
    two, floor 8): rungs quantize motion buffer shapes so the set of
    compiled executables per motion is small and bounded — ≤ log2 of the
    worst-case/seed ratio — and skew promotion always lands on a cached
    shape instead of an arbitrary new one."""
    n = max(int(n), 8)
    return 1 << (n - 1).bit_length()


# --------------------------------------------------------------------------
# misc
# --------------------------------------------------------------------------


def limit_mask(sel: jnp.ndarray, k: int, offset: int = 0) -> jnp.ndarray:
    """Keep rows offset..offset+k of the SELECTED sequence (post-sort)."""
    rank = jnp.cumsum(sel.astype(jnp.int64)) - 1
    return sel & (rank >= offset) & (rank < offset + k)


def compact(
    cols: Columns, sel: jnp.ndarray, capacity: int
) -> tuple[Columns, jnp.ndarray, jnp.ndarray]:
    """Stable-compact selected rows to the front at a (possibly smaller)
    capacity — used before motions to shrink shuffle width (the TupleSplit /
    multi-stage-agg motivation, SURVEY.md §2.2).

    Also returns the TRUE selected-row count; the executor must check it
    against ``capacity`` post-run — rows beyond capacity are truncated, which
    is an error to surface, never silence."""
    n_selected = jnp.sum(sel.astype(jnp.int64))
    idx = sort_indices([jnp.zeros_like(sel, dtype=jnp.int32)], sel)
    idx = idx[:capacity]
    out = {n: c[idx] for n, c in cols.items()}
    return out, sel[idx], n_selected
