"""Join-index cache — sorted-build reuse across statements.

Every sorted-build join pays an O(n log n) argsort of its build side per
execution (exec/kernels.py build_sort) even though build sides are usually
dimension tables identical across statements, generic-plan re-executions,
and dispatcher batches. This module precomputes the build side's sort
scaffolding HOST-side — (stable sort order, sorted packed keys, packing
ranges), the exact numpy mirror of build_sort — caches it in a
session-level LRU keyed by (table, version, key columns, pack bits,
layout mode, segment count/slice), and feeds it to compiled programs as
an EXTRA INPUT next to the tables (like ``$params``): program shapes are
unchanged, so generic-plan zero-recompile reuse is preserved, and any
write bumps the table version, which changes the cache key — the existing
table-version/epoch machinery IS the invalidation contract.

Eligible joins (annotate_join_index, stamped post-distribution):

- the build subtree is a bare full-table scan (optionally via PShare), or
  that scan under a plain broadcast motion — the gathered buffer's row
  order is deterministic (shard-major), so the host can mirror it;
- every build key is a plain ColumnRef onto a scanned column;
- no build-side key-validity expression (NULL-key masking would change
  the masked sort order at run time).

Everything else falls back to the in-program argsort automatically: the
join lowering looks the input up with ``.get`` and computes the sort when
the key is absent (tiled/spill step programs assemble their own inputs
and strip the annotations at intake — exec/tiled.py, exec/tiled_dist.py).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from cloudberry_tpu.plan import expr as ex
from cloudberry_tpu.plan import nodes as N

_U64_MAX = np.uint64(0xFFFFFFFFFFFFFFFF)
_U32_MAX = np.uint32(0xFFFFFFFF)
_SIGN64 = np.uint64(1) << np.uint64(63)


@dataclass(frozen=True)
class JoinIndexSpec:
    """One eligible join's cached-index contract: the program input key
    (shared by every join wanting the same index) plus how the host
    reconstructs the build fragment's row layout."""

    key: str          # program input key ("$jix:…")
    table: str
    phys: tuple       # physical key column names, join-key order
    bits: int         # PJoin.pack_bits
    mode: str         # 'table' | 'shard' | 'gathered'
    capacity: int     # build fragment rows as traced


# ------------------------------------------------------------ numpy mirror
# of kernels.sort_key_u64 / key_ranges / pack_with_ranges / downcast32 —
# bit-exact, including uint64 wraparound and STABLE argsort tie order, so
# a cached index is indistinguishable from the in-program computation.


def _np_sort_key_u64(col: np.ndarray) -> np.ndarray:
    a = np.asarray(col)
    if a.dtype == np.bool_:
        return a.astype(np.uint64)
    if a.dtype == np.float32:
        bits = a.view(np.uint32)
        mask = np.where(bits >> np.uint32(31) != 0,
                        np.uint32(0xFFFFFFFF), np.uint32(1) << np.uint32(31))
        return (bits ^ mask).astype(np.uint64)
    if a.dtype == np.float64:
        bits = a.view(np.uint64)
        mask = np.where(bits >> np.uint64(63) != 0, _U64_MAX, _SIGN64)
        return bits ^ mask
    return a.astype(np.int64).view(np.uint64) ^ _SIGN64


def _np_index(cols: list[np.ndarray], n_rows: int, capacity: int,
              bits: int) -> dict[str, np.ndarray]:
    """(order, sorted keys, per-key lo/span) over the first ``n_rows`` of
    ``cols`` padded to ``capacity`` — the host-side build_sort."""
    return _np_index_masked(cols, np.arange(capacity) < n_rows,
                            capacity, bits)


# ------------------------------------------------------------- annotation


def annotate_join_index(plan: N.PlanNode, session) -> None:
    """Stamp every eligible PJoin with its JoinIndexSpec (``_jix``); the
    input-assembly chokepoints then feed the cached index and the join
    lowering skips the build-side argsort."""
    if session.config.join_filter.index_cache <= 0:
        return
    from cloudberry_tpu.exec import executor as X

    nseg = session.config.n_segments
    direct = getattr(plan, "_direct_segment", None) is not None
    for node in X.all_nodes(plan):
        if isinstance(node, N.PJoin) and not hasattr(node, "_jix"):
            spec = _build_spec(node, session, nseg, direct)
            if spec is not None:
                node._jix = spec


def _build_spec(node: N.PJoin, session, nseg: int, direct: bool):
    from cloudberry_tpu.exec.executor import keyed_scan

    if node.build_key_valid is not None:
        return None
    build = node.build
    mode = "table"
    while isinstance(build, N.PShare):
        build = build.child
    if isinstance(build, N.PMotion):
        if build.kind != "broadcast" or build.pre_compact:
            return None
        mode = "gathered"
        build = build.child
    while isinstance(build, N.PShare):
        build = build.child
    if not isinstance(build, N.PScan) or build.table_name == "$dual":
        return None
    if keyed_scan(build) or hasattr(build, "_point_col"):
        # pruned store reads / point slices change their row set per
        # statement — the table version cannot key their layout
        return None
    try:
        t = session.catalog.table(build.table_name)
    except KeyError:
        return None
    rev = {out: p for p, out in build.column_map.items()}
    phys = []
    for k in node.build_keys:
        if not isinstance(k, ex.ColumnRef):
            return None
        p = rev.get(k.name)
        if p is None:
            return None
        phys.append(p)
    if mode == "table" and nseg > 1 and not direct \
            and t.policy.kind != "replicated":
        # distributed colocated build: the fragment is this segment's
        # shard — one index row set per segment, sharded input
        mode = "shard"
    key = (f"$jix:{build.table_name}:{','.join(phys)}:"
           f"{node.pack_bits}:{mode}")
    return JoinIndexSpec(key, build.table_name, tuple(phys),
                         node.pack_bits, mode, build.capacity)


def strip_join_index(plan: N.PlanNode) -> None:
    """Remove every join-index annotation (tiled/spill intake): step
    programs assemble their own inputs and must never trace a program
    that expects an input nobody provides."""
    from cloudberry_tpu.exec import executor as X

    for node in X.all_nodes(plan):
        if isinstance(node, N.PJoin) and hasattr(node, "_jix"):
            del node._jix


def stash_join_index(plan: N.PlanNode) -> list:
    """(node, spec) pairs for every annotated join. Tiled planning strips
    speculatively before it knows it can execute the plan — a decline
    restores these (restore_join_index) so the one-shot fallback keeps
    the cached-index optimization."""
    from cloudberry_tpu.exec import executor as X

    return [(n, n._jix) for n in X.all_nodes(plan)
            if isinstance(n, N.PJoin) and hasattr(n, "_jix")]


def restore_join_index(stash) -> None:
    for node, spec in stash:
        node._jix = spec


def jix_specs_of(plan: N.PlanNode) -> list[JoinIndexSpec]:
    """Deduped (by input key) specs of every annotated join in the plan —
    the deterministic walk input assembly and trace both rely on."""
    from cloudberry_tpu.exec import executor as X

    seen: set[str] = set()
    out = []
    for node in X.all_nodes(plan):
        spec = getattr(node, "_jix", None) \
            if isinstance(node, N.PJoin) else None
        if spec is not None and spec.key not in seen:
            seen.add(spec.key)
            out.append(spec)
    return out


# ------------------------------------------------- shared-scope LRU
# (sched/sharedcache.py): sessions over the same durable store share one
# join-index scope — a dimension table's sorted-build scaffolding is
# computed once per store version engine-wide, not once per backend.


def _cache(session):
    from cloudberry_tpu.sched import sharedcache

    scope = sharedcache.scope_for(session)
    return scope.joinindex, scope.joinindex_lock


def _cached_index(session, spec: JoinIndexSpec, segment) -> dict:
    """The spec's index arrays from the scope LRU, built on miss.
    Keyed on the table's content-stable version token
    (sharedcache.table_key — the store version for store-backed tables
    outside transactions, object uid + local version otherwise): any
    write bumps it, so stale indexes are unreachable by construction
    (the invalidation contract)."""
    from cloudberry_tpu.sched import sharedcache

    t = session.catalog.table(spec.table)
    t.ensure_loaded()
    nseg = session.config.n_segments
    # the topology-epoch token rides every shared-tier key: an index
    # laid out under a pre-cutover epoch (shard-mode arrays follow the
    # epoch's placement) can never serve after the flip
    key = (sharedcache.table_key(session, spec.table), spec.phys,
           spec.bits, spec.mode, nseg, segment,
           sharedcache.topology_token(session))
    cache, lock = _cache(session)
    with lock:
        hit = cache.pop(key, None)
        if hit is not None:
            cache[key] = hit  # LRU touch
    log = getattr(session, "stmt_log", None)
    if hit is not None:
        if log is not None:
            log.bump("join_index_hits")
        return hit
    hit = _build_index(session, spec, segment, t, nseg)
    if log is not None:
        log.bump("join_index_builds")
    limit = max(session.config.join_filter.index_cache, 1)
    with lock:
        while len(cache) >= limit:
            cache.pop(next(iter(cache)))
        cache[key] = hit
    return hit


def _build_index(session, spec: JoinIndexSpec, segment, t, nseg: int):
    if spec.mode == "shard":
        st = session.sharded_table(spec.table)
        per = [_np_index([np.asarray(st.columns[p][s]) for p in spec.phys],
                         int(st.counts[s]), st.capacity, spec.bits)
               for s in range(nseg)]
        out = {k: np.stack([d[k] for d in per]) for k in per[0]}
        return out
    if spec.mode == "gathered":
        st = session.sharded_table(spec.table)
        cols = [np.asarray(st.columns[p]).reshape(-1) for p in spec.phys]
        cap = st.capacity * nseg
        # the broadcast buffer is shard-major with each shard's rows a
        # selected prefix — mirror via a per-row validity mask folded
        # into the sort sentinel (rows past a shard's count never sort
        # into the live region)
        sel_rows = np.concatenate([np.arange(st.capacity) < st.counts[s]
                                   for s in range(nseg)])
        return _np_index_masked(cols, sel_rows, cap, spec.bits)
    # mode == 'table': the whole table (single segment / replicated), or
    # ONE shard under direct dispatch
    if segment is not None and t.policy.kind not in ("replicated",):
        st = session.sharded_table(spec.table)
        cols = [np.asarray(st.columns[p][segment]) for p in spec.phys]
        return _np_index(cols, int(st.counts[segment]), st.capacity,
                         spec.bits)
    cols = [np.asarray(t.data[p]) for p in spec.phys]
    cap = max(spec.capacity, len(cols[0]) if cols else 1, 1)
    return _np_index(cols, t.num_rows, cap, spec.bits)


def _np_index_masked(cols, sel, capacity, bits):
    """_np_index over an explicit row-validity mask (gathered buffers:
    each shard contributes a selected prefix, not one global prefix)."""
    with np.errstate(over="ignore"):
        packed = np.zeros(capacity, dtype=np.uint64)
        oob = np.zeros(capacity, dtype=np.bool_)
        ranges = []
        for c in cols:
            u = np.zeros(capacity, dtype=np.uint64)
            u[:len(c)] = _np_sort_key_u64(c)[:capacity]
            lo = np.min(np.where(sel, u, _U64_MAX))
            hi = np.max(np.where(sel, u, np.uint64(0)))
            span = np.maximum(hi - lo, np.uint64(0)) + np.uint64(1)
            ranges.append((np.uint64(lo), np.uint64(span)))
            oob = oob | (u < lo) | (u - lo >= span)
            packed = packed * span + np.clip(u - lo, np.uint64(0),
                                             span - np.uint64(1))
        packed = np.where(oob, _U64_MAX, packed)
        if bits == 32:
            masked = np.where(sel, np.where(packed == _U64_MAX, _U32_MAX,
                                            packed.astype(np.uint32)),
                              _U32_MAX)
        else:
            masked = np.where(sel, packed, _U64_MAX)
    order = np.argsort(masked, kind="stable").astype(np.int32)
    out = {"order": order, "skeys": masked[order]}
    for i, (lo, span) in enumerate(ranges):
        out[f"lo{i}"] = lo
        out[f"span{i}"] = span
    return out


# -------------------------------------------------------- input assembly


def join_index_inputs(plan: N.PlanNode, session,
                      segment=None) -> dict:
    """{input key: index arrays} for every annotated join — the single /
    direct-dispatch assembly chokepoint (exec/executor.py
    _assemble_inputs, sched/paramplan.py bind_inputs)."""
    out = {}
    for spec in jix_specs_of(plan):
        out[spec.key] = _cached_index(session, spec, segment)
    return out


def dist_join_index_inputs(plan: N.PlanNode, session):
    """(inputs, in_specs) for the distributed program: 'shard'-mode
    indexes split on the segment axis, 'table'/'gathered' replicated."""
    from jax.sharding import PartitionSpec as P

    from cloudberry_tpu.parallel.mesh import SEG_AXIS

    inputs = {}
    specs = {}
    for spec in jix_specs_of(plan):
        arrs = _cached_index(session, spec, None)
        inputs[spec.key] = arrs
        if spec.mode == "shard":
            specs[spec.key] = {
                k: P(SEG_AXIS, None) if v.ndim == 2 else P(SEG_AXIS)
                for k, v in arrs.items()}
        else:
            specs[spec.key] = {k: P() for k in arrs}
    return inputs, specs
