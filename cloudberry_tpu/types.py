"""Logical type system and schemas.

Maps SQL types onto TPU-friendly physical dtypes. Strings are
dictionary-encoded at ingest (int32 codes + host-side dictionary) — the
reference reaches the same conclusion in its PAX columnar engine
(contrib/pax_storage: dictionary encodings + Arrow vectorized reader); on TPU
it is mandatory because variable-length data cannot live in device tensors.
Dates are int32 days since the Unix epoch. DECIMAL is carried as float64
logically, with exact int64 fixed-point accumulation for SUM (see
exec/kernels.py) — the reference uses PG numeric (arbitrary precision);
TPC-H money columns fit comfortably in the fixed-point scheme.
"""

from __future__ import annotations

import datetime
import enum
from dataclasses import dataclass

import numpy as np


class DType(enum.Enum):
    BOOL = "bool"
    INT32 = "int32"
    INT64 = "int64"
    FLOAT64 = "float64"   # SQL DOUBLE
    DECIMAL = "decimal"   # int64 fixed-point, scale tracked in SqlType
    DATE = "date"         # int32 days since 1970-01-01
    STRING = "string"     # int32 dictionary codes

    @property
    def np_dtype(self) -> np.dtype:
        return {
            DType.BOOL: np.dtype(np.bool_),
            DType.INT32: np.dtype(np.int32),
            DType.INT64: np.dtype(np.int64),
            DType.FLOAT64: np.dtype(np.float64),
            DType.DECIMAL: np.dtype(np.int64),
            DType.DATE: np.dtype(np.int32),
            DType.STRING: np.dtype(np.int32),
        }[self]

    @property
    def is_numeric(self) -> bool:
        return self in (DType.INT32, DType.INT64, DType.FLOAT64, DType.DECIMAL)


@dataclass(frozen=True)
class SqlType:
    """Logical type + decimal scale.

    DECIMAL is carried as int64 scaled by 10**scale — deliberate TPU-first
    design: f64 is emulated (and f64 bitcasts unsupported) on TPU, while
    int64 adds/compares are cheap 2×int32 ops. Money arithmetic is exact and
    SUM() accumulates without float error (the reference uses PG arbitrary-
    precision numerics; fixed-point covers the same analytic workloads).
    """

    base: DType
    scale: int = 0

    def __post_init__(self):
        if self.base != DType.DECIMAL and self.scale != 0:
            raise ValueError("scale only valid for DECIMAL")

    @property
    def np_dtype(self) -> np.dtype:
        return self.base.np_dtype

    @property
    def is_numeric(self) -> bool:
        return self.base.is_numeric

    def __repr__(self):
        if self.base == DType.DECIMAL:
            return f"decimal({self.scale})"
        return self.base.value


BOOL = SqlType(DType.BOOL)
INT32 = SqlType(DType.INT32)
INT64 = SqlType(DType.INT64)
FLOAT64 = SqlType(DType.FLOAT64)
DATE = SqlType(DType.DATE)
STRING = SqlType(DType.STRING)


def DECIMAL(scale: int = 2) -> SqlType:
    return SqlType(DType.DECIMAL, scale)


@dataclass(frozen=True)
class Field:
    name: str
    type: SqlType
    # SQL default: columns are nullable unless declared NOT NULL
    nullable: bool = True

    @property
    def dtype(self) -> DType:
        return self.type.base


@dataclass(frozen=True)
class Schema:
    fields: tuple[Field, ...]

    def __post_init__(self):
        names = [f.name for f in self.fields]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate column names in schema: {names}")

    @property
    def names(self) -> list[str]:
        return [f.name for f in self.fields]

    def field(self, name: str) -> Field:
        for f in self.fields:
            if f.name == name:
                return f
        raise KeyError(name)

    def __contains__(self, name: str) -> bool:
        return any(f.name == name for f in self.fields)

    @staticmethod
    def of(**cols: "SqlType | DType") -> "Schema":
        fields = []
        for n, t in cols.items():
            if isinstance(t, DType):
                t = SqlType(t)
            fields.append(Field(n, t))
        return Schema(tuple(fields))


EPOCH = datetime.date(1970, 1, 1)


def date_to_days(d: datetime.date | str) -> int:
    if isinstance(d, str):
        d = datetime.date.fromisoformat(d)
    return (d - EPOCH).days


def days_to_date(days: int) -> datetime.date:
    return EPOCH + datetime.timedelta(days=int(days))


# SQL type-name → SqlType (parser uses this for CREATE TABLE; DECIMAL(p,s)
# gets its scale from the parser).
SQL_TYPE_MAP = {
    "boolean": BOOL,
    "bool": BOOL,
    "int": INT64,
    "integer": INT32,
    "int4": INT32,
    "bigint": INT64,
    "int8": INT64,
    "smallint": INT32,
    "double": FLOAT64,
    "float8": FLOAT64,
    "real": FLOAT64,
    "decimal": DECIMAL(2),
    "numeric": DECIMAL(2),
    "date": DATE,
    "text": STRING,
    "varchar": STRING,
    "char": STRING,
    "bpchar": STRING,
}
