"""Streaming ingest plane — the append-optimized write path (ISSUE 18).

The reference's AO (append-optimized) tables absorb small writes into
segment files without rewriting the table; here the analog is an
``IngestBuffer`` per (table, tenant) that batches wire-level appends into
micro-partition-sized commits. The contract:

- **Durability only at commit.** ``append()`` buffers the rows and blocks
  until the flush that covers them commits (group commit: whoever's rows
  trip the size threshold — or the age flusher — flushes EVERYONE's
  pending rows in one batch). A successful return means the rows are in
  the store's committed manifest; an error means the batch did not
  commit (retry-safe for the caller, like any failed INSERT).
- **Bit-identical to INSERTs by construction.** A flush renders one
  multi-row ``INSERT INTO t [(cols)] VALUES (...), (...)`` per
  column-signature run and executes it through ``session.sql`` inside
  the server's write scope — so OCC, matview maintenance, autostats,
  exact DECIMAL text encoding, the StatementLog/flight recorder, and
  store-version bumps (which invalidate the buffer pool / shared cache /
  feedback sketches) all ride the one existing write path instead of a
  parallel one.
- **Backpressure is retryable.** Past ``config.ingest.max_buffered_rows``
  pending rows per buffer, ``append`` refuses with ``IngestQueueFull``
  (in the retryable taxonomy — clients back off and retry, the same
  shape as SchedQueueFull).
- **Lifecycle.** Appends honor per-request deadlines (StatementTimeout)
  and cooperative cancel; ``stop()`` drains — every buffered row is
  flushed before the service goes down (the wire layer refuses new
  appends while draining).

Lock discipline: ``IngestService._cond`` (declared in the graftlint
witness order) guards the buffer map and all buffer state; it is NEVER
held across a flush — the leader takes the batch under the condition,
releases it, executes the INSERT(s), then re-acquires to publish the
outcome and wake waiters.
"""

from __future__ import annotations

import contextlib
import re
import threading
import time

from cloudberry_tpu import lifecycle
from cloudberry_tpu.utils.faultinject import fault_point

_IDENT = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")


def _lit(v) -> str:
    """One wire value → the SQL literal text a user would have typed.
    The flush is bit-identical to hand-written INSERTs exactly because
    this rendering is the identity on literal text: ints print as ints,
    floats as their shortest round-trip repr (DECIMAL columns parse the
    text exactly, fixed-point), strings single-quoted with '' escaping
    (dates/times ride as strings and encode at bind time)."""
    if v is None:
        return "NULL"
    if v is True:
        return "TRUE"
    if v is False:
        return "FALSE"
    if isinstance(v, int):
        return str(v)
    if isinstance(v, float):
        return repr(v)
    if isinstance(v, str):
        return "'" + v.replace("'", "''") + "'"
    raise ValueError(
        f"unsupported append value type {type(v).__name__!r} "
        "(use null/bool/int/float/str)")


def render_insert(table: str, columns, rows) -> str:
    """The flush statement for one column-signature run of rows."""
    cols = f" ({', '.join(columns)})" if columns else ""
    vals = ", ".join(
        "(" + ", ".join(_lit(v) for v in row) + ")" for row in rows)
    return f"INSERT INTO {table}{cols} VALUES {vals}"


def _row_bytes(row) -> int:
    """Cheap host-bytes estimate for the buffer gauge: 8 per scalar plus
    string payload (the gauge is capacity-plane telemetry, not an
    allocator)."""
    n = 0
    for v in row:
        n += 8 + (len(v) if isinstance(v, str) else 0)
    return n


class _Batch:
    """One flush's worth of rows taken out of a buffer: the ordered
    column-signature runs plus the (lo, hi] enqueue span they cover."""

    __slots__ = ("runs", "lo", "hi", "first_ts")

    def __init__(self, runs, lo, hi, first_ts):
        self.runs = runs
        self.lo = lo
        self.hi = hi
        self.first_ts = first_ts


class _Buffer:
    """Per-(table, tenant) pending rows. All state is guarded by the
    owning IngestService's condition."""

    __slots__ = ("runs", "pending", "bytes", "first_ts", "enqueued",
                 "done", "flushing", "fails")

    def __init__(self):
        self.runs = []          # [(columns-tuple-or-None, [rows])]
        self.pending = 0        # rows currently buffered
        self.bytes = 0          # estimated host bytes buffered
        self.first_ts = None    # monotonic ts of the oldest pending row
        self.enqueued = 0       # rows ever accepted (monotonic)
        self.done = 0           # rows resolved (committed or failed)
        self.flushing = False   # a leader holds this buffer's batch
        self.fails = []         # [(lo, hi, exc)] — failed flush spans

    def add(self, columns, rows, now: float) -> int:
        """Append one wire batch; returns the caller's ack position."""
        if self.runs and self.runs[-1][0] == columns:
            self.runs[-1][1].extend(rows)
        else:
            self.runs.append((columns, list(rows)))
        self.pending += len(rows)
        self.bytes += sum(_row_bytes(r) for r in rows)
        if self.first_ts is None:
            self.first_ts = now
        self.enqueued += len(rows)
        return self.enqueued

    def take(self) -> _Batch:
        """Hand the whole pending set to a flush leader."""
        batch = _Batch(self.runs, self.done + self._in_flight(),
                       self.enqueued, self.first_ts)
        self.runs = []
        self.pending = 0
        self.bytes = 0
        self.first_ts = None
        return batch

    def _in_flight(self) -> int:
        # rows between done and the pending set (a batch being flushed)
        return self.enqueued - self.done - self.pending

    def error_for(self, pos: int):
        for lo, hi, exc in self.fails:
            if lo < pos <= hi:
                return exc
        return None


class IngestService:
    """The streaming append plane: buffers per (table, tenant), size/age
    flush thresholds, group commit through the session's one write path.
    One instance serves a whole Server (wired with the server's
    ``exec_scope`` so flushes take the same write lock SQL does); tests
    drive it directly on a bare Session."""

    def __init__(self, session, exec_scope=None):
        cfg = session.config.ingest
        self.session = session
        self.flush_rows = max(1, int(cfg.flush_rows))
        self.flush_ms = float(cfg.flush_ms)
        self.max_buffered_rows = max(1, int(cfg.max_buffered_rows))
        self._exec_scope = exec_scope
        self._cond = threading.Condition()
        self._buffers: dict[tuple, _Buffer] = {}
        self._stop = False
        self._thread = None
        # wired by the server: called (outside locks) with the table
        # name after each committed flush — the compaction wake-up
        self.on_commit = None

    # ------------------------------------------------------------ lifecycle

    def _ensure_flusher(self) -> None:
        """Spawn the age flusher lazily: a server that never sees an
        append never pays a thread."""
        if self._thread is not None:
            return
        with self._cond:
            if self._thread is None and not self._stop:
                t = threading.Thread(target=self._age_flusher,
                                     name="ingest-flusher", daemon=True)
                self._thread = t
                t.start()

    def stop(self) -> None:
        """Drain flush-on-stop: refuse new appends, flush every buffered
        row, and only then return — a stopping server never drops
        acknowledged-pending work on the floor."""
        with self._cond:
            self._stop = True
            t, self._thread = self._thread, None
            self._cond.notify_all()
        if t is not None:
            t.join(timeout=10)
        self.drain()

    def drain(self) -> None:
        """Flush until no buffer has pending rows and no flush is in
        flight (other leaders' flushes are waited out)."""
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            work = []
            with self._cond:
                for key, buf in self._buffers.items():
                    if buf.pending and not buf.flushing:
                        work.append((key, buf, buf.take()))
                        buf.flushing = True
                busy = bool(work) or any(
                    b.flushing for b in self._buffers.values())
            if not busy:
                return
            for key, buf, batch in work:
                self._run_flush(key, buf, batch)
            if not work:
                time.sleep(0.005)

    # --------------------------------------------------------------- append

    def append(self, table: str, rows, columns=None,
               tenant: str | None = None,
               deadline_s: float | None = None) -> int:
        """Buffer ``rows`` for ``table`` and block until the covering
        flush commits. Returns the number of rows made durable."""
        self._validate(table, rows, columns)
        self._ensure_flusher()
        log = getattr(self.session, "stmt_log", None)
        cols = tuple(columns) if columns else None
        key = (table, tenant)
        now = time.monotonic()
        deadline = now + deadline_s if deadline_s else None
        lead_batch = None
        with self._cond:
            if self._stop:
                raise lifecycle.ServerDraining("ingest is draining")
            buf = self._buffers.get(key)
            if buf is None:
                buf = self._buffers[key] = _Buffer()
            if buf.pending + len(rows) > self.max_buffered_rows:
                if log is not None:
                    log.bump("ingest_queue_full", tenant=tenant)
                raise lifecycle.IngestQueueFull(
                    f"ingest buffer for {table!r} is full "
                    f"({buf.pending} rows pending); retry")
            pos = buf.add(cols, rows, now)
            self._cond.notify_all()
            while True:
                err = buf.error_for(pos)
                if err is not None:
                    raise err
                if buf.done >= pos:
                    break
                now = time.monotonic()
                if deadline is not None and now >= deadline:
                    raise lifecycle.StatementTimeout(
                        f"append to {table!r} timed out awaiting commit "
                        "(rows remain buffered; durability unknown)")
                lifecycle.check_cancel()
                if buf.pending and not buf.flushing \
                        and self._due(buf, now):
                    lead_batch = buf.take()
                    buf.flushing = True
                    break
                self._cond.wait(timeout=self._wait_s(buf, now, deadline))
        if lead_batch is not None:
            self._run_flush(key, buf, lead_batch)
            with self._cond:
                err = buf.error_for(pos)
            if err is not None:
                raise err
        if log is not None:
            log.bump("ingest_appends", tenant=tenant)
        return len(rows)

    def _validate(self, table, rows, columns) -> None:
        if not _IDENT.match(table or ""):
            raise ValueError(f"bad table name {table!r}")
        if columns is not None:
            for c in columns:
                if not _IDENT.match(c or ""):
                    raise ValueError(f"bad column name {c!r}")
        if not rows:
            raise ValueError("append needs at least one row")
        width = len(columns) if columns else len(rows[0])
        for row in rows:
            if not isinstance(row, (list, tuple)) or len(row) != width:
                raise ValueError(
                    "append rows must be equal-width lists")

    def _due(self, buf: _Buffer, now: float) -> bool:
        if buf.pending >= self.flush_rows:
            return True
        return buf.first_ts is not None \
            and (now - buf.first_ts) * 1000.0 >= self.flush_ms

    def _wait_s(self, buf: _Buffer, now: float, deadline) -> float:
        wake = now + max(self.flush_ms / 1000.0, 0.001)
        if buf.first_ts is not None:
            wake = min(wake, buf.first_ts + self.flush_ms / 1000.0)
        if deadline is not None:
            wake = min(wake, deadline)
        return max(0.001, min(wake - now, 0.05))

    # ---------------------------------------------------------------- flush

    def _age_flusher(self) -> None:
        """Background thread: commits buffers whose oldest row has aged
        past flush_ms even when no appender is waiting to lead (e.g.
        every appender already timed out, or leads a different buffer)."""
        while True:
            lifecycle.check_cancel()
            work = []
            with self._cond:
                self._cond.wait(
                    timeout=max(0.005, self.flush_ms / 2000.0))
                if self._stop:
                    return
                now = time.monotonic()
                for key, buf in self._buffers.items():
                    if buf.pending and not buf.flushing \
                            and self._due(buf, now):
                        work.append((key, buf, buf.take()))
                        buf.flushing = True
            for key, buf, batch in work:
                self._run_flush(key, buf, batch)

    def _run_flush(self, key, buf: _Buffer, batch: _Batch) -> None:
        """Execute one batch OUTSIDE the condition, then publish the
        outcome. A failed flush resolves its span with the error — the
        rows are NOT durable and every covered appender sees the
        exception (never a silent drop, never a false ack)."""
        table, tenant = key
        log = getattr(self.session, "stmt_log", None)
        err = None
        try:
            # the device-loss-mid-flush chaos seam: an armed fault here
            # fails the WHOLE batch before any statement commits
            fault_point("ingest_flush")
            scope = self._exec_scope(write=True) \
                if self._exec_scope is not None \
                else contextlib.nullcontext()
            with scope:
                for cols, rows in batch.runs:
                    self.session.sql(render_insert(table, cols, rows))
        except BaseException as e:  # noqa: BLE001 — delivered to waiters
            err = e
        with self._cond:
            buf.flushing = False
            buf.done = max(buf.done, batch.hi)
            if err is not None:
                buf.fails.append((batch.lo, batch.hi, err))
                del buf.fails[:-16]
            self._cond.notify_all()
        if log is not None:
            if err is None:
                log.bump("ingest_flushes")
                log.bump("ingest_rows", batch.hi - batch.lo,
                         tenant=tenant)
                log.registry.observe(
                    "ingest_flush_seconds",
                    time.monotonic() - (batch.first_ts
                                        or time.monotonic()))
            else:
                log.bump("ingest_flush_errors")
        if err is None and self.on_commit is not None:
            try:
                self.on_commit(table)
            except Exception:  # noqa: BLE001 — observer must not break
                if log is not None:
                    log.bump("ingest_commit_hook_errors")

    # ------------------------------------------------------------ telemetry

    def buffered_bytes(self) -> int:
        """The ``mem_ingest_buffer_bytes`` gauge feed
        (obs/capacity.refresh_gauges)."""
        with self._cond:
            return sum(b.bytes for b in self._buffers.values())

    def snapshot(self) -> dict:
        """``meta "ingest"``: buffer occupancy + the counter/latency
        story in one read."""
        with self._cond:
            bufs = [{"table": k[0], "tenant": k[1],
                     "pending_rows": b.pending,
                     "pending_bytes": b.bytes,
                     "flushing": b.flushing}
                    for k, b in sorted(self._buffers.items(),
                                       key=lambda kv: (kv[0][0],
                                                       kv[0][1] or ""))]
            draining = self._stop
        out = {"enabled": True, "draining": draining,
               "flush_rows": self.flush_rows, "flush_ms": self.flush_ms,
               "max_buffered_rows": self.max_buffered_rows,
               "buffered_rows": sum(b["pending_rows"] for b in bufs),
               "buffered_bytes": sum(b["pending_bytes"] for b in bufs),
               "buffers": bufs}
        log = getattr(self.session, "stmt_log", None)
        if log is not None:
            for c in ("ingest_appends", "ingest_rows", "ingest_flushes",
                      "ingest_flush_errors", "ingest_queue_full"):
                out[c.replace("ingest_", "")] = log.counter(c)
            h = log.registry.hist("ingest_flush_seconds") or {}
            out["flush_ms_p95"] = round(h.get("p95", 0.0) * 1000.0, 3)
        return out
