"""Directory tables — files as catalog objects (the dirtable analog).

The reference's directory tables store uploaded files in table-managed
storage and expose one metadata row per file (relative_path, size,
last_modified, md5), loaded via gpdirtableload and read through UDFs.
Analog: files live under ``<store>/_dirtab/<table>/``; the catalog entry
is a metadata relation refreshed from the filesystem at every
referencing statement (planner.py hook), so SQL sees uploads
immediately; content IO goes through the Session API
(``dir_upload`` / ``dir_read`` / ``dir_remove``). Under TDE
(storage.encryption_key) file contents encrypt at rest like any other
store data.
"""

from __future__ import annotations

import hashlib
import os
import time

import numpy as np

from cloudberry_tpu import types as T


class DirTableError(RuntimeError):
    pass


SCHEMA = T.Schema.of(relative_path=T.STRING, size=T.INT64,
                     last_modified=T.STRING, md5=T.STRING)


def _root(session, table: str) -> str:
    if session.store is None:
        raise DirTableError(
            "directory tables need durable storage (storage.root)")
    return os.path.join(session.store.root, "_dirtab", table.lower())


def _safe(table: str, rel: str) -> str:
    rel = rel.strip("/")
    if not rel or ".." in rel.split("/"):
        raise DirTableError(f"bad relative path {rel!r}")
    return rel


def create(session, name: str) -> None:
    from cloudberry_tpu.catalog.catalog import DistributionPolicy

    os.makedirs(_root(session, name), exist_ok=True)
    # metadata relation: ephemeral catalog entry (durable=False) — the
    # DIRECTORY is the durable state; rows re-derive from it per statement
    t = session.catalog.create_table(name, SCHEMA,
                                     DistributionPolicy.random(),
                                     durable=False)
    t.directory = {"table": name.lower()}


def upload(session, table: str, rel: str, data: bytes) -> str:
    from cloudberry_tpu.storage import iofault

    root = _root(session, table)
    if not os.path.isdir(root):
        raise DirTableError(f"unknown directory table {table!r}")
    rel = _safe(table, rel)
    path = os.path.join(root, rel)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    cipher = session.store.cipher
    # durable like any other store write: an upload the catalog row will
    # advertise must survive a crash (and IO faults surface typed)
    iofault.durable_write(
        path, cipher.encrypt(data) if cipher is not None else data)
    return rel


def read(session, table: str, rel: str) -> bytes:
    from cloudberry_tpu.lifecycle import StorageIOError
    from cloudberry_tpu.storage import iofault

    path = os.path.join(_root(session, table), _safe(table, rel))
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except FileNotFoundError:
        raise DirTableError(f"no file {rel!r} in directory table {table!r}")
    except OSError as e:
        # an EIO is NOT "no such file" — surface it as the retryable
        # storage fault it is, and count it
        iofault.note_io_error(path, e)
        raise StorageIOError(f"{path}: {e}") from e
    cipher = session.store.cipher
    return cipher.decrypt(raw) if cipher is not None else raw


def remove(session, table: str, rel: str) -> None:
    from cloudberry_tpu.lifecycle import StorageIOError
    from cloudberry_tpu.storage import iofault

    path = os.path.join(_root(session, table), _safe(table, rel))
    try:
        os.remove(path)
    except FileNotFoundError:
        raise DirTableError(f"no file {rel!r} in directory table {table!r}")
    except OSError as e:
        iofault.note_io_error(path, e)
        raise StorageIOError(f"{path}: {e}") from e


def refresh(session, t) -> None:
    """Re-derive the metadata rows from the directory (statement-start
    hook). md5 is of the DECRYPTED content — the identity of what the
    user uploaded, stable across key rotation."""
    root = _root(session, t.directory["table"])
    cipher = session.store.cipher
    rows = []
    for dirpath, _, files in os.walk(root):
        for fname in sorted(files):
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            with open(path, "rb") as f:
                raw = f.read()
            if cipher is not None:
                raw = cipher.decrypt(raw)
            st = os.stat(path)
            rows.append((rel, len(raw),
                         time.strftime("%Y-%m-%d %H:%M:%S",
                                       time.gmtime(st.st_mtime)),
                         hashlib.md5(raw).hexdigest()))
    rows.sort()
    data = {
        "relative_path": np.asarray([r[0] for r in rows], dtype=object),
        "size": np.asarray([r[1] for r in rows], dtype=np.int64),
        "last_modified": np.asarray([r[2] for r in rows], dtype=object),
        "md5": np.asarray([r[3] for r in rows], dtype=object),
    }
    from cloudberry_tpu.columnar.batch import encode_column

    enc = {}
    for f in SCHEMA.fields:
        arr = data[f.name]
        enc[f.name] = encode_column(arr, f, t.dicts) \
            if f.dtype == T.DType.STRING else arr
    t._loading = True  # metadata rows never persist — the directory is
    try:              # the durable state
        t.set_data(enc, t.dicts)
    finally:
        t._loading = False
