"""TableStore — persistent tables over immutable micro-partitions with
snapshot manifests.

The transactional design follows SURVEY.md §7.1's stance: instead of
re-building per-node WAL + 2PC (cdbtm.c), the coordinator owns ONE logical
commit log per store: every write produces new immutable partition files plus
a new manifest version; readers pin a manifest version and see a consistent
snapshot (the distributed-snapshot analog, cdbdistributedsnapshot.c — here
trivially consistent because data files never mutate). Deletes are
delete-vectors recorded in the manifest (the AO visimap analog,
appendonly_visimap.c). Commit = atomic rename of the CURRENT pointer; crash
before rename leaves the previous snapshot intact (crash recovery = nothing
to do).

Layout:
    root/<table>/part-<uuid>.cbmp           immutable column data
    root/<table>/_manifests/v<k>.json       snapshot manifests
    root/<table>/_manifests/CURRENT         text: latest committed version
"""

from __future__ import annotations

import fcntl
import json
import os
import tempfile
import threading
import uuid
from dataclasses import dataclass
from typing import Optional

import numpy as np

from cloudberry_tpu.columnar.dictionary import StringDictionary
from cloudberry_tpu.storage import iofault
from cloudberry_tpu.storage import micropartition as mp
from cloudberry_tpu.types import DType, Schema


@dataclass
class PartitionEntry:
    file: str
    num_rows: int
    # stats: {col: [min, max]}
    stats: dict
    # sorted row ids deleted from this partition (visimap analog)
    deleted: list[int]


class QuotaError(RuntimeError):
    """Store disk usage reached storage.quota_bytes (diskquota analog)."""


class TableStore:
    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        # session-transaction write deferral: inside BEGIN..COMMIT, data
        # changes collect in _txn_dirty (and drops in _txn_drops) and hit
        # disk only at COMMIT; ROLLBACK discards them — the store never
        # sees uncommitted state (single-coordinator commit discipline)
        self.autocommit = True
        self._txn_dirty: dict[str, object] = {}
        self._txn_drops: list[str] = []
        self.rows_per_partition = 1 << 20
        # TDE (utils/tde.py): set via storage.encryption_key; encrypts
        # micro-partition files and manifests at rest
        self.cipher = None
        # content-checksum verification at decode (pg_checksums analog):
        # column blobs carry a crc in the footer; a mismatch raises
        # StorageCorruptionError instead of decoding garbage. Config:
        # storage.verify_checksums (default on — crc32 is cheap next to
        # decompression).
        self.verify_checksums = True
        # disk quota (diskquota extension analog): enforced at write time
        # against real on-disk usage; 0 = unlimited. Like the reference's,
        # enforcement is a hard stop once usage REACHES the quota — the
        # write that crosses it succeeds, the next one is refused.
        self.quota_bytes = 0
        # snapshot pinning: while a session transaction is open, every read
        # through read_manifest resolves to the version current at BEGIN —
        # repeatable reads even while OTHER sessions commit (the
        # distributed-snapshot discipline, cdbdistributedsnapshot.c)
        self.pinned: dict[str, int] = {}
        # intra-process writer exclusion (see lock()): the O_EXCL file
        # only arbitrates between PROCESSES; threads sharing this store
        # object (the ingest flusher, the compaction worker, statement
        # threads) serialize here first
        self._tlock = threading.Lock()
        self._lock_owner: Optional[int] = None

    # ------------------------------------------------- session transactions

    def begin_txn(self) -> None:
        self.autocommit = False
        self._txn_dirty = {}
        self._txn_stats: dict[str, object] = {}
        self._txn_drops = []
        # append tracking for the OCC merge: a transaction whose writes to
        # a table were ALL appends can merge onto a concurrently-committed
        # snapshot instead of aborting (concurrent INSERTs both succeed —
        # the concurrent-DML capability of the reference's GDD,
        # src/backend/utils/gdd/README.md)
        self._txn_appends: dict[str, int] = {}
        self._txn_rewrites: set[str] = set()
        self.pinned = {name: self.current_version(name)
                       for name in self.table_names()}

    def note_txn_write(self, name: str, appended: Optional[int]) -> None:
        """Record whether a deferred in-transaction write was an append
        (last ``appended`` rows new, rest untouched) or a rewrite."""
        if appended is None:
            self._txn_rewrites.add(name)
            self._txn_appends.pop(name, None)
        elif name not in self._txn_rewrites:
            self._txn_appends[name] = \
                self._txn_appends.get(name, 0) + appended

    def txn_append_only(self, name: str) -> bool:
        return (name in getattr(self, "_txn_appends", {})
                and name not in getattr(self, "_txn_rewrites", set())
                and name not in self._txn_drops)

    def commit_txn(self, base: Optional[dict] = None) -> None:
        self.pinned = {}  # commit writes against CURRENT, not the snapshot
        base = base or {}
        for name in self._txn_drops:
            self.drop_table(name)
        for name, t in self._txn_dirty.items():
            moved = self.current_version(name) != base.get(name, 0)
            if moved and self.txn_append_only(name):
                # another session committed first but this transaction
                # only APPENDED: merge the new tail onto their snapshot
                # (serial order: theirs, then this one)
                self._merge_append(t, self._txn_appends[name])
                # this session's RAM copy is missing the other session's
                # rows — force a cold re-register at the next sync
                t._store_version = None
            else:
                t._store_version = self.save_table(t,
                                                   self.rows_per_partition)
        # stats-only changes (ANALYZE with no DML): one manifest write,
        # not a full data re-snapshot
        for name, t in getattr(self, "_txn_stats", {}).items():
            if name not in self._txn_dirty and t.stats.ndv:
                t._store_version = self.save_stats(
                    name, t.stats.ndv, t.stats.hist,
                    t.stats.analyzed_rows)
        self.abort_txn()

    def abort_txn(self) -> None:
        self.autocommit = True
        self._txn_dirty = {}
        self._txn_stats = {}
        self._txn_drops = []
        self._txn_appends = {}
        self._txn_rewrites = set()
        self.pinned = {}

    def effective_version(self, name: str) -> int:
        v = self.pinned.get(name)
        return v if v is not None else self.current_version(name)

    def conflicting_tables(self, base: dict[str, int]) -> list[str]:
        """Tables this transaction wrote whose store version moved past the
        BEGIN snapshot AND whose writes cannot merge — the OCC check.
        Append-only writes merge onto the concurrent snapshot (commit_txn);
        rewrites (UPDATE/DELETE) and drops conflict: first committer wins,
        the later COMMIT must fail rather than overwrite. Stats-only
        changes (ANALYZE) never conflict — advisory, last write wins."""
        written = set(self._txn_dirty) | set(self._txn_drops)
        return sorted(n for n in written
                      if self.current_version(n) != base.get(n, 0)
                      and not self.txn_append_only(n))

    def _merge_append(self, t, k: int) -> int:
        """Append transaction ``t``'s last ``k`` rows onto the CURRENT
        snapshot (which another session committed after this transaction
        began). String codes re-encode against the stored dictionary (the
        two sessions may have extended the base dictionary differently),
        and stored uniqueness flags are re-verified against the merged
        data — a column stays unique only if the tail neither overlaps the
        stored values nor repeats internally."""
        name = t.name
        tail = {c: np.asarray(v)[-k:] for c, v in t.data.items()}
        validity = {c: np.asarray(v)[-k:] for c, v in t.validity.items()
                    if len(v)}
        man = self.read_manifest(name)
        stored_dicts = {c: StringDictionary(v)
                        for c, v in man.get("dicts", {}).items()}
        dicts = {}
        for c, d in t.dicts.items():
            sd = stored_dicts.get(c)
            if sd is None or sd.values == d.values:
                dicts[c] = d
                continue
            vals = d.decode(tail[c])
            tail[c] = sd.encode(np.asarray(vals, dtype=object))
            dicts[c] = sd
        unique = dict(man.get("unique", {}))
        for c, was in list(unique.items()):
            if not was or c not in tail:
                continue
            tc = tail[c]
            if len(np.unique(tc)) != len(tc):
                unique[c] = False
                continue
            stored, _ = self.read_partitions(name, man["partitions"], [c])
            unique[c] = not bool(np.isin(tc, stored[c]).any())
        v = self.append(name, tail, t.schema, dicts, replace=False,
                        validity=validity, unique=unique,
                        rows_per_partition=self.rows_per_partition)
        return v

    # ----------------------------------------------------------- manifests

    def _mdir(self, table: str) -> str:
        return os.path.join(self.root, table, "_manifests")

    def current_version(self, table: str) -> int:
        try:
            with open(os.path.join(self._mdir(table), "CURRENT")) as f:
                return int(f.read().strip())
        except FileNotFoundError:
            return 0

    def read_manifest(self, table: str,
                      version: Optional[int] = None) -> dict:
        if version is None:
            version = self.pinned.get(table)
        v = self.current_version(table) if version is None else version
        if v == 0:
            return {"version": 0, "schema": None, "partitions": [],
                    "dicts": {}}
        mpath = os.path.join(self._mdir(table), f"v{v}.json")
        with open(mpath, "rb") as f:
            raw = f.read()
        if raw[:8] == b"CBMPENC1":
            if self.cipher is None:
                from cloudberry_tpu.utils.tde import TdeError

                raise TdeError(f"{mpath}: encrypted manifest but no "
                               "storage.encryption_key configured")
            raw = self.cipher.decrypt(raw[8:])
        return json.loads(raw)

    def _commit(self, table: str, manifest: dict) -> int:
        """Atomically publish a new snapshot (single-coordinator commit).
        The store lock closes the version-read → publish window against
        other processes."""
        with self.lock():
            return self._commit_locked(table, manifest)

    def _commit_locked(self, table: str, manifest: dict) -> int:
        from cloudberry_tpu.utils.faultinject import fault_point

        mdir = self._mdir(table)
        os.makedirs(mdir, exist_ok=True)
        v = self.current_version(table) + 1
        manifest["version"] = v
        path = os.path.join(mdir, f"v{v}.json")
        raw = json.dumps(manifest).encode()
        if self.cipher is not None:
            raw = b"CBMPENC1" + self.cipher.encrypt(raw)
        # the manifest body write — a crash here leaves a torn/orphan
        # v{N}.json that CURRENT never points at (fsck collects it)
        fault_point("io_manifest_write")
        iofault.durable_write(path, raw)
        # atomic CURRENT swap — the commit point; the fault point simulates
        # a crash in the window after the manifest is written but before the
        # commit becomes visible (chaos tests verify the old snapshot wins)
        if fault_point("storage_commit_before_current"):
            return v
        fd, tmp = tempfile.mkstemp(dir=mdir)
        with os.fdopen(fd, "w") as f:
            f.write(str(v))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(mdir, "CURRENT"))
        iofault.fsync_dir(mdir)  # the rename must survive power loss too
        # the committed-but-unacknowledged window: a crash here loses the
        # ack, not the data — restart-verify must FIND these rows durable
        fault_point("storage_commit_after_current")
        self._bump_epoch()
        return v

    # store-wide change token: one cheap read tells a session whether ANY
    # table changed since it last looked (catalog-sync fast path). A unique
    # token, not a counter — concurrent bumps can never collapse into one
    # value and hide a commit (no read-modify-write race).

    def epoch(self) -> str:
        try:
            with open(os.path.join(self.root, "_EPOCH")) as f:
                return f.read().strip()
        except FileNotFoundError:
            return ""

    def _bump_epoch(self) -> None:
        fd, tmp = tempfile.mkstemp(dir=self.root)
        with os.fdopen(fd, "w") as f:
            f.write(uuid.uuid4().hex)
        os.replace(tmp, os.path.join(self.root, "_EPOCH"))

    # ---------------------------------------------- inter-process write lock

    def lock(self, timeout_s: float = 30.0):
        """Store-wide mutual exclusion: _tlock serializes the THREADS
        sharing this store object (ingest flusher, compaction worker,
        statement threads), an flock(2) on the persistent _LOCK file
        serializes PROCESSES. Held around version-check-then-commit so
        two committers can never both pass the OCC check and overwrite
        each other. Re-entrant within one thread — a boolean "am I
        inside?" flag is NOT enough here: it is readable by sibling
        threads, and a sibling that treated the holder's flag as its own
        re-entrancy would walk straight into the critical section and
        tear the v{N}.json both would then write.

        flock, not a pid-stamped O_EXCL file: the kernel drops the lock
        the instant the holder dies (crash-only — a SIGKILLed writer
        needs no stale-lock breaking), and breaking by unlink had an
        unfixable TOCTOU — between "pid in _LOCK is dead" and the
        unlink, a racer can break the same stale file and acquire a
        fresh one, which the unlink then destroys, letting two processes
        into the commit critical section. The _LOCK file itself is
        permanent (unlink-on-release re-opens the same race: a lock
        taken on a just-unlinked inode excludes nobody); its content is
        the holder's pid, for diagnostics only."""
        import contextlib
        import time as _time

        @contextlib.contextmanager
        def _locked():
            me = threading.get_ident()
            if self._lock_owner == me:
                yield
                return
            from cloudberry_tpu.utils.faultinject import fault_point

            fault_point("store_lock_acquire")
            if not self._tlock.acquire(timeout=timeout_s):
                raise RuntimeError(
                    f"store lock timeout after {timeout_s}s — another "
                    "thread of this process is holding the store lock")
            try:
                path = os.path.join(self.root, "_LOCK")
                fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o644)
                try:
                    deadline = _time.monotonic() + timeout_s
                    while True:
                        try:
                            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                            break
                        except OSError:
                            if _time.monotonic() > deadline:
                                raise RuntimeError(
                                    f"store lock timeout after {timeout_s}s "
                                    f"— another process holds {path}")
                            _time.sleep(0.01)
                    try:
                        os.ftruncate(fd, 0)
                        os.write(fd, str(os.getpid()).encode())
                    except OSError:
                        pass  # diagnostics only — the flock IS the lock
                    self._lock_owner = me
                    try:
                        yield
                    finally:
                        self._lock_owner = None
                        try:
                            os.ftruncate(fd, 0)
                        except OSError:
                            pass
                finally:
                    os.close(fd)  # releases the flock
            finally:
                self._tlock.release()

        return _locked()

    # -------------------------------------------------------------- writes

    def append(self, table: str, data: dict[str, np.ndarray], schema: Schema,
               dicts: dict[str, StringDictionary] | None = None,
               rows_per_partition: int = 1 << 20,
               replace: bool = False, policy=None,
               validity: dict[str, np.ndarray] | None = None,
               unique: dict[str, bool] | None = None,
               partition_spec: tuple | None = None) -> int:
        """Append rows as new micro-partitions (``replace=True``: the new
        snapshot contains ONLY these rows — still one atomic commit, so a
        crash mid-write never publishes an empty intermediate).
        ``validity`` masks persist as extra "$nn:<col>" bool columns.
        Returns the new snapshot version."""
        tdir = os.path.join(self.root, table)
        os.makedirs(tdir, exist_ok=True)
        self._check_quota(table)
        man = self.read_manifest(table)
        if replace:
            man["partitions"] = []
        n = len(next(iter(data.values()))) if data else 0
        phys_schema = schema
        phys_data = data
        if validity:
            from cloudberry_tpu.types import BOOL, Field as TField

            phys_data = dict(data)
            extra = []
            for c, v in validity.items():
                phys_data[f"$nn:{c}"] = np.asarray(v, dtype=np.bool_)
                extra.append(TField(f"$nn:{c}", BOOL))
            phys_schema = Schema(tuple(schema.fields) + tuple(extra))
        spec = partition_spec if partition_spec is not None \
            else (tuple(man["partition_spec"])
                  if man.get("partition_spec") else None)
        new_parts = []
        for pkey, idx in _partition_rows(spec, phys_data, n):
            group = phys_data if idx is None \
                else {k: v[idx] for k, v in phys_data.items()}
            gn = n if idx is None else len(idx)
            for lo in range(0, max(gn, 1), rows_per_partition):
                hi = min(lo + rows_per_partition, gn)
                if hi <= lo:
                    break
                chunk = {k: v[lo:hi] for k, v in group.items()}
                fname = f"part-{uuid.uuid4().hex}.cbmp"
                footer = mp.write_micropartition(
                    os.path.join(tdir, fname), chunk, phys_schema, dicts,
                    cipher=self.cipher)
                stats = {c["name"]: [c["min"], c["max"]]
                         for c in footer["columns"] if "min" in c}
                entry = {"file": fname, "num_rows": hi - lo,
                         "stats": stats, "deleted": []}
                if pkey is not None:
                    entry["pkey"] = pkey
                new_parts.append(entry)
        # dictionaries are table-level, append-only state: a new dict must
        # EXTEND the stored one (codes in already-written partitions keep
        # decoding correctly); anything else is a caller error, not silent
        # corruption.
        man["schema"] = [mp._field_json(f) for f in schema.fields]
        man["not_null"] = [f.name for f in schema.fields if not f.nullable]
        if replace:
            man["nullable"] = sorted(validity or [])
        elif validity:
            man["nullable"] = sorted(set(man.get("nullable", []))
                                     | set(validity))
        if unique is not None:
            man["unique"] = unique
        if policy is not None:
            man["policy"] = {"kind": policy.kind, "keys": list(policy.keys)}
        if spec is not None:
            man["partition_spec"] = list(spec)
        old_dicts = man.get("dicts", {}) if not replace else {}
        new_dicts = {k: list(d.values) for k, d in (dicts or {}).items()}
        for k, old in old_dicts.items():
            new = new_dicts.get(k)
            if new is None:
                new_dicts[k] = old
            elif new[:len(old)] != old:
                raise ValueError(
                    f"dictionary for column {k!r} is not an append-only "
                    f"extension of the stored dictionary")
        man["dicts"] = new_dicts
        man["partitions"] = man["partitions"] + new_parts
        return self._commit(table, man)

    _QUOTA_TTL_S = 5.0

    def disk_usage(self, fresh: bool = False) -> int:
        """Bytes on disk under the store root (partition files, manifests,
        sequences — everything the store owns). Cached for a few seconds:
        quota enforcement is approximate by design (the reference's
        diskquota worker likewise refreshes usage on an interval rather
        than walking per write)."""
        import time as _time

        now = _time.monotonic()
        cached = getattr(self, "_usage_cache", None)
        if not fresh and cached is not None \
                and now - cached[0] < self._QUOTA_TTL_S:
            return cached[1]
        total = 0
        for dirpath, _, files in os.walk(self.root):
            for f in files:
                try:
                    total += os.path.getsize(os.path.join(dirpath, f))
                except FileNotFoundError:
                    pass  # raced a concurrent unlink — benign
                except OSError as e:
                    iofault.note_io_error(os.path.join(dirpath, f), e)
        self._usage_cache = (now, total)
        return total

    def _invalidate_usage(self) -> None:
        self._usage_cache = None

    def _check_quota(self, table: str) -> None:
        if self.quota_bytes <= 0:
            return
        used = self.disk_usage()
        if used >= self.quota_bytes:
            # re-walk before refusing: the cache may predate a reclaim
            used = self.disk_usage(fresh=True)
            if used < self.quota_bytes:
                return
            raise QuotaError(
                f"disk quota exceeded: store uses {used} of "
                f"{self.quota_bytes} quota bytes; writes to {table!r} "
                "refused (DELETE / DROP TABLE to reclaim)")

    def delete_rows(self, table: str, pred) -> int:
        """Mark rows deleted (visimap-style) where pred(columns)->bool mask;
        pred receives decoded per-partition columns. Returns new version.

        OCC like every other manifest writer: the per-partition masks are
        computed outside the lock (file IO), and the commit only lands if
        the manifest version is still the one that was read — a concurrent
        append/compaction commit forces a re-read and re-apply, so neither
        side's partitions are silently dropped (last-writer-wins on the
        whole manifest was a lost-update bug under the write plane)."""
        for _ in range(50):
            man = self.read_manifest(table)
            tdir = os.path.join(self.root, table)
            for part in man["partitions"]:
                cols = mp.read_columns(os.path.join(tdir, part["file"]),
                                       cipher=self.cipher,
                                       verify=self.verify_checksums)
                mask = np.asarray(pred(cols))
                if mask.any():
                    dead = set(part["deleted"]) \
                        | set(np.nonzero(mask)[0].tolist())
                    part["deleted"] = sorted(dead)
            with self.lock():
                if self.current_version(table) == man["version"]:
                    return self._commit(table, man)
        raise RuntimeError(
            f"delete_rows({table!r}) kept losing the manifest OCC race")

    # --------------------------------------------------------------- reads

    def select_partitions(self, table: str, ranges: dict | None = None,
                          eqs: dict | None = None,
                          version: Optional[int] = None
                          ) -> tuple[list[dict], dict]:
        """Pick the partitions a predicate can touch, without reading any
        column data. ``ranges``: {col: (lo, hi)}; ``eqs``: {col: value}.
        Manifest min/max prunes first (no file IO); equality predicates then
        check footer bloom filters (footer-only IO). Returns (surviving
        partition entries, report) — the report counts candidates and
        skips per mechanism (for EXPLAIN and the file-skip tests)."""
        man = self.read_manifest(table, version)
        tdir = os.path.join(self.root, table)
        report = {"candidates": len(man["partitions"]),
                  "skipped_minmax": 0, "skipped_bloom": 0}
        ranges = dict(ranges or {})
        for c, v in (eqs or {}).items():
            lo, hi = ranges.get(c, (None, None))
            lo = v if lo is None else max(lo, v)
            hi = v if hi is None else min(hi, v)
            ranges[c] = (lo, hi)
        out = []
        for part in man["partitions"]:
            if ranges and not all(_part_may_match(part, c, lo, hi)
                                  for c, (lo, hi) in ranges.items()):
                report["skipped_minmax"] += 1
                continue
            if eqs and not self.bloom_may_match(
                    table, part, {c: [v] for c, v in eqs.items()}):
                report["skipped_bloom"] += 1
                continue
            out.append(part)
        return out, report

    def bloom_may_match(self, table: str, part: dict,
                        col_values: dict) -> bool:
        """One footer read answering: could this partition hold ANY of the
        given values in EVERY listed column? (False = provably not — the
        shared membership primitive for eq pruning and the partition
        selector.)"""
        footer = mp.read_footer(
            os.path.join(self.root, table, part["file"]),
            cipher=self.cipher)
        encs = {c["name"]: c for c in footer["columns"]}
        for col, vals in col_values.items():
            enc = encs.get(col)
            if enc is None:
                continue
            if not any(mp.bloom_may_contain(enc, v) for v in vals):
                return False
        return True

    def read_partitions(self, table: str, parts: list[dict],
                        columns: list[str] | None = None,
                        version: Optional[int] = None,
                        pool=None, on_decode=None) -> tuple[dict, dict]:
        """Read (selected columns of) the given partitions; "$nn:" validity
        columns split out. Returns (columns dict, validity dict).
        ``pool``/``on_decode`` ride through to the column decode
        (micropartition.read_columns) — the scan pipeline's
        column-parallel decode and its ``decode_seconds`` feed."""
        from cloudberry_tpu.utils.faultinject import fault_point

        fault_point("store_read_partition")
        man = self.read_manifest(table, version)
        schema = Schema(tuple(mp._field_from_json(j) for j in man["schema"]))
        nullable = set(man.get("nullable", []))
        tdir = os.path.join(self.root, table)
        names = list(columns) if columns is not None else list(schema.names)
        want = names + [f"$nn:{c}" for c in names if c in nullable]
        chunks: list[dict[str, np.ndarray]] = []
        for part in parts:
            cols = mp.read_columns(os.path.join(tdir, part["file"]),
                                   want, cipher=self.cipher,
                                   pool=pool, on_decode=on_decode,
                                   verify=self.verify_checksums)
            if part["deleted"]:
                keep = np.ones(part["num_rows"], dtype=bool)
                keep[np.asarray(part["deleted"], dtype=np.int64)] = False
                cols = {k: v[keep] for k, v in cols.items()}
                cols["$n"] = int(keep.sum())
            else:
                cols["$n"] = part["num_rows"]
            chunks.append(cols)
        out, validity = {}, {}
        for name in want:
            arrs = []
            for c in chunks:
                a = c.get(name)
                if a is None:
                    # older partition without the validity column: all valid
                    a = np.ones(c["$n"], dtype=np.bool_)
                arrs.append(a)
            base = name[4:] if name.startswith("$nn:") else None
            f_dt = (np.bool_ if base is not None
                    else schema.field(name).type.np_dtype)
            col = (np.concatenate(arrs) if arrs
                   else np.zeros(0, dtype=f_dt))
            if base is not None:
                validity[base] = col
            else:
                out[name] = col
        return out, validity

    def scan(self, table: str, columns: list[str] | None = None,
             version: Optional[int] = None,
             prune: dict | None = None) -> tuple[dict, Schema, dict]:
        """Snapshot read. ``prune``: {col: (lo, hi)} ranges — partitions
        provably outside are skipped via footer stats.

        Returns (columns dict, schema, dicts); validity columns under
        their "$nn:<col>" names when present."""
        man = self.read_manifest(table, version)
        if man["schema"] is None:
            raise KeyError(f"table {table!r} has no data in store")
        schema = Schema(tuple(mp._field_from_json(j) for j in man["schema"]))
        parts, _ = self.select_partitions(table, prune, version=version)
        cols, validity = self.read_partitions(table, parts, columns,
                                              version=version)
        for c, v in validity.items():
            cols[f"$nn:{c}"] = v
        dicts = {k: StringDictionary(v) for k, v in man["dicts"].items()}
        return cols, schema, dicts

    # ------------------------------------------------------------ sequences
    # Durable, store-wide sequences (the gp_fastsequence / QD-owned nextval
    # analog): one JSON file guarded by the store lock; allocation is
    # write-through (nextval never rolls back — PostgreSQL semantics) and
    # every session on the root draws from the same number line.

    def _atomic_json(self, path: str, obj) -> None:
        """Durable atomic JSON replace (shared by sequences/matview defs,
        the topology record, and the compaction journal — same
        discipline as the manifest CURRENT swap)."""
        from cloudberry_tpu.utils.faultinject import fault_point

        fault_point("io_atomic_json")
        iofault.atomic_json(path, obj, dirpath=self.root)

    def _seq_path(self) -> str:
        return os.path.join(self.root, "_SEQUENCES.json")

    def _read_sequences(self) -> dict:
        try:
            with open(self._seq_path()) as f:
                return json.load(f)
        except FileNotFoundError:
            return {}

    def _write_sequences(self, seqs: dict) -> None:
        self._atomic_json(self._seq_path(), seqs)

    def create_sequence(self, name: str, start: int = 1, increment: int = 1,
                        if_not_exists: bool = False) -> None:
        if increment == 0:
            raise ValueError("INCREMENT must not be zero")
        with self.lock():
            seqs = self._read_sequences()
            if name in seqs:
                if if_not_exists:
                    return
                raise ValueError(f"sequence {name!r} already exists")
            seqs[name] = {"next": int(start), "inc": int(increment)}
            self._write_sequences(seqs)

    def drop_sequence(self, name: str, if_exists: bool = False) -> None:
        with self.lock():
            seqs = self._read_sequences()
            if name not in seqs:
                if if_exists:
                    return
                raise KeyError(f"unknown sequence {name!r}")
            del seqs[name]
            self._write_sequences(seqs)

    def sequence_alloc(self, name: str) -> int:
        """Reserve and return the next value."""
        with self.lock():
            seqs = self._read_sequences()
            s = seqs.get(name)
            if s is None:
                raise KeyError(f"unknown sequence {name!r}")
            base = s["next"]
            s["next"] = base + s["inc"]
            self._write_sequences(seqs)
            return base

    def sequence_setval(self, name: str, value: int) -> None:
        with self.lock():
            seqs = self._read_sequences()
            s = seqs.get(name)
            if s is None:
                raise KeyError(f"unknown sequence {name!r}")
            s["next"] = int(value) + s["inc"]
            self._write_sequences(seqs)

    def sequence_names(self) -> list[str]:
        return sorted(self._read_sequences())

    # --------------------------------------------------- matview definitions

    def save_matviews(self, defs: dict) -> None:
        """Persist materialized-view definitions (full DDL text) — the
        gp_matview_aux catalog analog."""
        with self.lock():
            self._atomic_json(os.path.join(self.root, "_MATVIEWS.json"),
                              defs)

    def load_matviews(self) -> dict:
        try:
            with open(os.path.join(self.root, "_MATVIEWS.json")) as f:
                return json.load(f)
        except FileNotFoundError:
            return {}

    # ------------------------------------------------------ session bridge

    def save_table(self, t, rows_per_partition: int = 1 << 20) -> int:
        """Persist a catalog Table's current data as a fresh snapshot
        (one atomic commit). Records per-column uniqueness so cold
        registration can plan PK joins without loading data."""
        unique = {c: bool(t.is_unique(c)) for c in t.schema.names
                  if t.data.get(c) is not None
                  and t.data[c].dtype.kind in "iu"}
        v = self.append(t.name, t.data, t.schema, t.dicts, replace=True,
                        policy=t.policy, validity=t.validity,
                        unique=unique,
                        partition_spec=t.partition_spec,
                        rows_per_partition=rows_per_partition)
        if t.stats.ndv:
            # ANALYZE output survives the snapshot (deferred-commit path)
            v = self.save_stats(t.name, t.stats.ndv, t.stats.hist,
                                t.stats.analyzed_rows)
        return v

    def drop_table(self, name: str) -> None:
        import shutil

        tdir = os.path.join(self.root, name)
        if os.path.isdir(tdir):
            shutil.rmtree(tdir)
            self._invalidate_usage()  # reclaim visible to the next quota check
            self._bump_epoch()

    def table_names(self) -> list[str]:
        out = []
        for name in sorted(os.listdir(self.root)):
            if os.path.isfile(os.path.join(self._mdir(name), "CURRENT")):
                out.append(name)
        return out

    def save_stats(self, name: str, ndv: dict[str, int],
                   hist: dict | None = None,
                   analyzed_rows: int | None = None) -> int:
        """Persist ANALYZE output as a new manifest version (stats change
        is a catalog change — same atomic commit discipline). ``hist``:
        equi-depth histogram bounds per column (pg_statistic
        histogram_bounds role); ``analyzed_rows``: row count at ANALYZE
        time (the autostats change baseline)."""
        man = self.read_manifest(name)
        man["ndv"] = {k: int(v) for k, v in ndv.items()}
        if hist is not None:
            man["hist"] = {k: [float(x) for x in v]
                           for k, v in hist.items()}
        if analyzed_rows is not None:
            man["analyzed_rows"] = int(analyzed_rows)
        return self._commit(name, man)

    def register_cold(self, catalog, name: str):
        """Register a stored table WITHOUT loading data: schema, policy,
        dictionaries, nullability, row count, per-column min/max and
        uniqueness all come from the manifest, so the planner can bind and
        prune scans against the cold table (the reference analog: catalog
        entries + pg_statistic exist without touching segment files)."""
        from cloudberry_tpu.catalog.catalog import DistributionPolicy
        from cloudberry_tpu.types import Field as TField

        man = self.read_manifest(name)
        if man["schema"] is None:
            return None
        nullable = set(man.get("nullable", []))
        not_null = set(man.get("not_null", []))
        fields = tuple(
            TField(j["name"],
                   mp._field_from_json(j).type,
                   nullable=j["name"] not in not_null)
            for j in man["schema"])
        pol = man.get("policy")
        policy = (DistributionPolicy(pol["kind"], tuple(pol["keys"]))
                  if pol else DistributionPolicy.random())
        from cloudberry_tpu.catalog.catalog import Table

        t = Table(name, Schema(fields), policy)
        if man.get("partition_spec"):
            t.partition_spec = tuple(man["partition_spec"])
        t.data = {f.name: np.zeros(0, dtype=f.type.np_dtype)
                  for f in fields}
        catalog.adopt(t)  # no create_table: must not write a new snapshot
        t.backing = self
        t.cold = True
        t._store_version = man["version"]
        t.dicts = {k: StringDictionary(v) for k, v in man["dicts"].items()}
        # placeholder keys: the binder only needs to know WHICH columns are
        # nullable to emit scan mask fields; arrays load with the data
        t.validity = {c: np.zeros(0, dtype=np.bool_) for c in nullable}
        rows = 0
        mm: dict[str, tuple] = {}
        for p in man["partitions"]:
            rows += p["num_rows"] - len(p["deleted"])
            for c, (lo, hi) in p.get("stats", {}).items():
                if c.startswith("$nn:"):
                    continue
                old = mm.get(c)
                mm[c] = ((lo, hi) if old is None
                         else (min(old[0], lo), max(old[1], hi)))
        t.stats.row_count = rows
        t.stats.min_max = {c: (float(lo), float(hi))
                           for c, (lo, hi) in mm.items()}
        # uniqueness survives deletion (a subset of unique stays unique)
        t.stats.unique = {c: bool(u)
                          for c, u in man.get("unique", {}).items()}
        t.stats.ndv = {c: int(v) for c, v in man.get("ndv", {}).items()}
        t.stats.hist = {c: list(v) for c, v in man.get("hist", {}).items()}
        t.stats.analyzed_rows = int(man.get("analyzed_rows", -1))
        return t

    def load_table(self, catalog, name: str,
                   version: Optional[int] = None):
        """Materialize a stored table into a catalog (replaces data)."""
        from cloudberry_tpu.catalog.catalog import DistributionPolicy

        data, schema, dicts = self.scan(name, version=version)
        validity = {k[4:]: v for k, v in data.items()
                    if k.startswith("$nn:")}
        data = {k: v for k, v in data.items() if not k.startswith("$nn:")}
        pol = self.read_manifest(name, version).get("policy")
        policy = (DistributionPolicy(pol["kind"], tuple(pol["keys"]))
                  if pol else DistributionPolicy.random())
        if name in catalog.tables:
            t = catalog.table(name)
            t.policy = policy
        else:
            t = catalog.create_table(name, schema, policy)
        t.dicts = dicts
        t.set_data(data, dicts, validity=validity)
        return t


def _partition_rows(spec, phys_data: dict, n: int):
    """Yield (pkey, row_indices) groups per the PARTITION BY spec — each
    group becomes partition-pure files whose manifest min/max stats are
    exact partition bounds (the reference keeps a partition catalog +
    PartitionSelector; here the stats ARE the partition metadata). Rows
    outside the declared RANGE land in a DEFAULT-partition analog."""
    if spec is None or n == 0:
        yield None, None
        return
    kind, col = spec[0], spec[1]
    vals = phys_data.get(col)
    if vals is None:  # partition column pruned out of this write — no route
        yield None, None
        return
    v = np.asarray(vals)
    if kind == "range":
        start, end, every = int(spec[2]), int(spec[3]), int(spec[4])
        # floor_divide BEFORE any int cast: truncation toward zero would
        # misroute negative fractional values into the wrong bucket
        if v.dtype.kind == "f":
            ids = np.floor_divide(v - start, every).astype(np.int64)
        else:
            ids = np.floor_divide(v.astype(np.int64) - start, every)
        nbuckets = -(-(end - start) // every)
        ids = np.where((v < start) | (v >= end), np.int64(-1), ids)
        for b in range(-1, nbuckets):
            idx = np.nonzero(ids == b)[0]
            if len(idx):
                yield ("default" if b < 0
                       else f"r{start + b * every}"), idx
    else:  # list
        for val in np.unique(v):
            idx = np.nonzero(v == val)[0]
            yield f"l{val}", idx


def _part_may_match(part: dict, col: str, lo, hi) -> bool:
    st = part.get("stats", {}).get(col)
    if st is None:
        return True
    if lo is not None and st[1] < lo:
        return False
    if hi is not None and st[0] > hi:
        return False
    return True
