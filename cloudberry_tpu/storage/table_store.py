"""TableStore — persistent tables over immutable micro-partitions with
snapshot manifests.

The transactional design follows SURVEY.md §7.1's stance: instead of
re-building per-node WAL + 2PC (cdbtm.c), the coordinator owns ONE logical
commit log per store: every write produces new immutable partition files plus
a new manifest version; readers pin a manifest version and see a consistent
snapshot (the distributed-snapshot analog, cdbdistributedsnapshot.c — here
trivially consistent because data files never mutate). Deletes are
delete-vectors recorded in the manifest (the AO visimap analog,
appendonly_visimap.c). Commit = atomic rename of the CURRENT pointer; crash
before rename leaves the previous snapshot intact (crash recovery = nothing
to do).

Layout:
    root/<table>/part-<uuid>.cbmp           immutable column data
    root/<table>/_manifests/v<k>.json       snapshot manifests
    root/<table>/_manifests/CURRENT         text: latest committed version
"""

from __future__ import annotations

import json
import os
import tempfile
import uuid
from dataclasses import dataclass
from typing import Optional

import numpy as np

from cloudberry_tpu.columnar.dictionary import StringDictionary
from cloudberry_tpu.storage import micropartition as mp
from cloudberry_tpu.types import DType, Schema


@dataclass
class PartitionEntry:
    file: str
    num_rows: int
    # stats: {col: [min, max]}
    stats: dict
    # sorted row ids deleted from this partition (visimap analog)
    deleted: list[int]


class TableStore:
    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    # ----------------------------------------------------------- manifests

    def _mdir(self, table: str) -> str:
        return os.path.join(self.root, table, "_manifests")

    def current_version(self, table: str) -> int:
        try:
            with open(os.path.join(self._mdir(table), "CURRENT")) as f:
                return int(f.read().strip())
        except FileNotFoundError:
            return 0

    def read_manifest(self, table: str,
                      version: Optional[int] = None) -> dict:
        v = self.current_version(table) if version is None else version
        if v == 0:
            return {"version": 0, "schema": None, "partitions": [],
                    "dicts": {}}
        with open(os.path.join(self._mdir(table), f"v{v}.json")) as f:
            return json.load(f)

    def _commit(self, table: str, manifest: dict) -> int:
        """Atomically publish a new snapshot (single-coordinator commit)."""
        mdir = self._mdir(table)
        os.makedirs(mdir, exist_ok=True)
        v = self.current_version(table) + 1
        manifest["version"] = v
        path = os.path.join(mdir, f"v{v}.json")
        with open(path, "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        # atomic CURRENT swap — the commit point; the fault point simulates
        # a crash in the window after the manifest is written but before the
        # commit becomes visible (chaos tests verify the old snapshot wins)
        from cloudberry_tpu.utils.faultinject import fault_point

        if fault_point("storage_commit_before_current"):
            return v
        fd, tmp = tempfile.mkstemp(dir=mdir)
        with os.fdopen(fd, "w") as f:
            f.write(str(v))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(mdir, "CURRENT"))
        return v

    # -------------------------------------------------------------- writes

    def append(self, table: str, data: dict[str, np.ndarray], schema: Schema,
               dicts: dict[str, StringDictionary] | None = None,
               rows_per_partition: int = 1 << 20,
               replace: bool = False, policy=None) -> int:
        """Append rows as new micro-partitions (``replace=True``: the new
        snapshot contains ONLY these rows — still one atomic commit, so a
        crash mid-write never publishes an empty intermediate).
        Returns the new snapshot version."""
        tdir = os.path.join(self.root, table)
        os.makedirs(tdir, exist_ok=True)
        man = self.read_manifest(table)
        if replace:
            man["partitions"] = []
        n = len(next(iter(data.values()))) if data else 0
        new_parts = []
        for lo in range(0, max(n, 1), rows_per_partition):
            hi = min(lo + rows_per_partition, n)
            if hi <= lo:
                break
            chunk = {k: v[lo:hi] for k, v in data.items()}
            fname = f"part-{uuid.uuid4().hex}.cbmp"
            footer = mp.write_micropartition(
                os.path.join(tdir, fname), chunk, schema, dicts)
            stats = {c["name"]: [c["min"], c["max"]]
                     for c in footer["columns"] if "min" in c}
            new_parts.append({"file": fname, "num_rows": hi - lo,
                              "stats": stats, "deleted": []})
        # dictionaries are table-level, append-only state: a new dict must
        # EXTEND the stored one (codes in already-written partitions keep
        # decoding correctly); anything else is a caller error, not silent
        # corruption.
        man["schema"] = [mp._field_json(f) for f in schema.fields]
        if policy is not None:
            man["policy"] = {"kind": policy.kind, "keys": list(policy.keys)}
        old_dicts = man.get("dicts", {}) if not replace else {}
        new_dicts = {k: list(d.values) for k, d in (dicts or {}).items()}
        for k, old in old_dicts.items():
            new = new_dicts.get(k)
            if new is None:
                new_dicts[k] = old
            elif new[:len(old)] != old:
                raise ValueError(
                    f"dictionary for column {k!r} is not an append-only "
                    f"extension of the stored dictionary")
        man["dicts"] = new_dicts
        man["partitions"] = man["partitions"] + new_parts
        return self._commit(table, man)

    def delete_rows(self, table: str, pred) -> int:
        """Mark rows deleted (visimap-style) where pred(columns)->bool mask;
        pred receives decoded per-partition columns. Returns new version."""
        man = self.read_manifest(table)
        schema = Schema(tuple(mp._field_from_json(j) for j in man["schema"]))
        tdir = os.path.join(self.root, table)
        for part in man["partitions"]:
            cols = mp.read_columns(os.path.join(tdir, part["file"]))
            mask = np.asarray(pred(cols))
            if mask.any():
                dead = set(part["deleted"]) | set(np.nonzero(mask)[0].tolist())
                part["deleted"] = sorted(dead)
        del schema
        return self._commit(table, man)

    # --------------------------------------------------------------- reads

    def scan(self, table: str, columns: list[str] | None = None,
             version: Optional[int] = None,
             prune: dict | None = None) -> tuple[dict, Schema, dict]:
        """Snapshot read. ``prune``: {col: (lo, hi)} ranges — partitions
        provably outside are skipped via footer stats.

        Returns (columns dict, schema, dicts)."""
        man = self.read_manifest(table, version)
        if man["schema"] is None:
            raise KeyError(f"table {table!r} has no data in store")
        schema = Schema(tuple(mp._field_from_json(j) for j in man["schema"]))
        tdir = os.path.join(self.root, table)
        chunks: list[dict[str, np.ndarray]] = []
        for part in man["partitions"]:
            if prune and not all(
                    _part_may_match(part, c, lo, hi)
                    for c, (lo, hi) in prune.items()):
                continue
            cols = mp.read_columns(os.path.join(tdir, part["file"]), columns)
            if part["deleted"]:
                keep = np.ones(part["num_rows"], dtype=bool)
                keep[np.asarray(part["deleted"], dtype=np.int64)] = False
                cols = {k: v[keep] for k, v in cols.items()}
            chunks.append(cols)
        names = columns or schema.names
        out = {}
        for name in names:
            arrs = [c[name] for c in chunks]
            f = schema.field(name)
            out[name] = (np.concatenate(arrs) if arrs
                         else np.zeros(0, dtype=f.type.np_dtype))
        dicts = {k: StringDictionary(v) for k, v in man["dicts"].items()}
        return out, schema, dicts

    # ------------------------------------------------------ session bridge

    def save_table(self, t) -> int:
        """Persist a catalog Table's current data as a fresh snapshot
        (one atomic commit)."""
        return self.append(t.name, t.data, t.schema, t.dicts, replace=True,
                           policy=t.policy)

    def load_table(self, catalog, name: str,
                   version: Optional[int] = None):
        """Materialize a stored table into a catalog (replaces data)."""
        from cloudberry_tpu.catalog.catalog import DistributionPolicy

        data, schema, dicts = self.scan(name, version=version)
        pol = self.read_manifest(name, version).get("policy")
        policy = (DistributionPolicy(pol["kind"], tuple(pol["keys"]))
                  if pol else DistributionPolicy.random())
        if name in catalog.tables:
            t = catalog.table(name)
            t.policy = policy
        else:
            t = catalog.create_table(name, schema, policy)
        t.dicts = dicts
        t.set_data(data, dicts)
        return t


def _part_may_match(part: dict, col: str, lo, hi) -> bool:
    st = part.get("stats", {}).get(col)
    if st is None:
        return True
    if lo is not None and st[1] < lo:
        return False
    if hi is not None and st[0] > hi:
        return False
    return True
