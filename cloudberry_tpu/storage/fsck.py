"""Store fsck + orphan GC — the gpcheckcat / pg_checksums offline pass.

``fsck()`` walks a store root and verifies the crash-consistency
contract the write path promises (ISSUE 19):

- manifest closure: every table's CURRENT resolves to a manifest that
  parses, every partition file it references exists, footer row counts
  agree with the manifest, and delete vectors stay in range;
- store-level JSON (sequences, matviews, topology, feedback, the
  compaction journal) parses — the atomic-replace discipline makes torn
  JSON structurally impossible, so a torn file here is a real defect;
- ``deep=True`` re-reads every referenced column blob and checks its
  footer content checksum (micropartition.verify_file — the
  pg_checksums sweep);
- orphan census: partition files no manifest version references, and
  stale ``tmp*`` droppings from interrupted atomic replaces. Orphans
  are NOT corruption — they are exactly what a kill between a partition
  write and its manifest commit leaves behind — so they report
  separately and never fail the verdict. Journal-pending replacement
  files and anything younger than ``grace_s`` are protected (an
  in-flight commit looks orphaned until CURRENT lands).

The census is fail-safe: a table whose manifest chain could not be
FULLY read (CURRENT torn, an old committed version unreadable, the
_manifests dir unlistable) or that recorded any problem is excluded
from the orphan census entirely (``report["census_skipped"]``) — an
incomplete referenced-set would classify live data files as orphans,
and ``--gc`` would then destroy exactly the table fsck was run to
diagnose. Likewise a torn compaction journal suppresses the census for
every table, because journal-pending protection is unknowable.

``gc=True`` unlinks collectable orphans. The verdict is ``clean`` iff
no corruption problems were found.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

from cloudberry_tpu.storage import micropartition as mp

# store-level JSON files the atomic-replace discipline covers
_STORE_JSON = ("_SEQUENCES.json", "_MATVIEWS.json", "_TOPOLOGY.json",
               "_FEEDBACK.json", "_COMPACTION.json")
# root files that are never orphans (cluster metadata, lock, epoch)
_KEEP = {"cluster.json", "_EPOCH", "_LOCK"} | set(_STORE_JSON)


def _journal_protected(root: str) -> Optional[set[str]]:
    """table-relative paths the compaction journal's pending record still
    owns — their commit may be about to happen on restart. ``None`` when
    the journal EXISTS but cannot be read: protection is then unknowable
    and the orphan census must not run at all."""
    try:
        with open(os.path.join(root, "_COMPACTION.json")) as f:
            rec = json.load(f)
    except FileNotFoundError:
        return set()
    except (OSError, ValueError):
        return None
    pend = rec.get("pending") or {}
    table = pend.get("table")
    if not table:
        return set()
    return {os.path.join(table, f) for f in pend.get("files", ())}


def _check_table(store, root: str, name: str, deep: bool,
                 report: dict) -> Optional[set[str]]:
    """Verify one table; returns the set of referenced partition files
    (across ALL manifest versions — older snapshots pin their files
    until their manifests are pruned), or ``None`` when the manifest
    chain could not be fully read — the referenced-set is then
    incomplete and MUST NOT drive the orphan census (every live file
    would look orphaned and --gc would unlink the table's data)."""
    problems = report["problems"]
    tdir = os.path.join(root, name)
    mdir = os.path.join(tdir, "_manifests")
    referenced: set[str] = set()
    try:
        man = store.read_manifest(name)
    except Exception as e:  # noqa: BLE001 — any parse failure is the finding
        problems.append(f"{name}: CURRENT manifest unreadable: {e}")
        return None
    entry = {"version": man.get("version", 0),
             "partitions": len(man.get("partitions", ())),
             "rows": 0, "checked": 0}
    for part in man.get("partitions", ()):
        fname = part["file"]
        path = os.path.join(tdir, fname)
        referenced.add(fname)
        if not os.path.exists(path):
            problems.append(f"{name}/{fname}: referenced by CURRENT "
                            f"manifest v{man['version']} but missing")
            continue
        try:
            footer = mp.read_footer(path, cipher=store.cipher)
        except Exception as e:  # noqa: BLE001
            problems.append(f"{name}/{fname}: footer unreadable: {e}")
            continue
        if footer.get("num_rows") != part["num_rows"]:
            problems.append(
                f"{name}/{fname}: manifest says {part['num_rows']} rows, "
                f"footer says {footer.get('num_rows')}")
        bad_dv = [r for r in part.get("deleted", ())
                  if not 0 <= r < part["num_rows"]]
        if bad_dv:
            problems.append(f"{name}/{fname}: delete vector rows "
                            f"{bad_dv[:4]} out of range "
                            f"[0, {part['num_rows']})")
        entry["rows"] += part["num_rows"] - len(part.get("deleted", ()))
        if deep:
            for p in mp.verify_file(path, cipher=store.cipher):
                problems.append(f"{name}/{fname}: {p}")
            entry["checked"] += 1
    # older manifest versions pin their files too (versioned reads)
    chain_complete = True
    try:
        for mf in os.listdir(mdir):
            if mf.startswith("v") and mf.endswith(".json"):
                try:
                    v = int(mf[1:-5])
                    old = store.read_manifest(name, v)
                except Exception:  # noqa: BLE001
                    # AHEAD of CURRENT: expected crash residue (possibly
                    # torn, never committed). At or BEHIND: a committed
                    # snapshot whose pins we cannot enumerate — the
                    # referenced-set is incomplete, census unsafe.
                    if mf[1:-5].isdigit() and int(mf[1:-5]) <= entry["version"]:
                        problems.append(
                            f"{name}/_manifests/{mf}: committed manifest "
                            "unreadable")
                        chain_complete = False
                    continue
                referenced.update(p["file"]
                                  for p in old.get("partitions", ()))
    except OSError:
        chain_complete = False  # cannot enumerate versions at all
    report["tables"][name] = entry
    return referenced if chain_complete else None


def fsck(root: str, cipher=None, deep: bool = False,
         grace_s: float = 300.0, gc: bool = False,
         now: Optional[float] = None) -> dict:
    """Verify a store root; optionally collect orphans. Returns the
    report dict (see module docstring); ``report["clean"]`` is the
    verdict."""
    from cloudberry_tpu.storage.table_store import TableStore

    store = TableStore(root)
    store.cipher = cipher
    store.verify_checksums = True
    now = time.time() if now is None else now
    report: dict = {"root": root, "tables": {}, "problems": [],
                    "orphans": [], "collected": [], "census_skipped": []}
    protected = _journal_protected(root)

    for name in sorted(os.listdir(root)):
        tdir = os.path.join(root, name)
        if not os.path.isdir(os.path.join(tdir, "_manifests")):
            continue
        n_problems = len(report["problems"])
        referenced = _check_table(store, root, name, deep, report)
        # fail-safe census: only a table whose manifest chain was FULLY
        # read and that reported zero problems may have its unreferenced
        # files classified as orphans — anything else and "orphan" may
        # mean "live file we failed to account for", which --gc would
        # then destroy. A torn compaction journal (protected is None)
        # suppresses the census store-wide for the same reason.
        if (referenced is None or protected is None
                or len(report["problems"]) > n_problems):
            report["census_skipped"].append(name)
            continue
        # orphan census: partition files no manifest version references
        for fname in sorted(os.listdir(tdir)):
            rel = os.path.join(name, fname)
            full = os.path.join(tdir, fname)
            is_part = fname.startswith("part-") and fname.endswith(".cbmp")
            is_tmp = fname.startswith("tmp")
            if not (is_part or is_tmp) or fname in referenced:
                continue
            if rel in protected:
                continue
            try:
                age = now - os.path.getmtime(full)
            except OSError:
                continue  # vanished mid-walk — already collected
            report["orphans"].append(
                {"path": rel, "age_s": round(age, 1),
                 "collectable": age >= grace_s})
        # interrupted atomic replaces under _manifests, plus manifest
        # versions AHEAD of CURRENT — the residue of a crash between the
        # v{N}.json write and the CURRENT swap (possibly torn; never
        # reachable, so an orphan rather than corruption)
        cur = report["tables"].get(name, {}).get("version", 0)
        mdir = os.path.join(tdir, "_manifests")
        for fname in sorted(os.listdir(mdir)):
            ahead = False
            if fname.startswith("v") and fname.endswith(".json"):
                try:
                    ahead = int(fname[1:-5]) > cur
                except ValueError:
                    pass
            if not (fname.startswith("tmp") or ahead):
                continue
            full = os.path.join(mdir, fname)
            try:
                age = now - os.path.getmtime(full)
            except OSError:
                continue
            report["orphans"].append(
                {"path": os.path.join(name, "_manifests", fname),
                 "age_s": round(age, 1), "collectable": age >= grace_s})

    # store-level JSON must parse (atomic replace ⇒ torn = defect);
    # stale tmp files at the root are interrupted replaces
    for fname in sorted(os.listdir(root)):
        full = os.path.join(root, fname)
        if fname in _STORE_JSON:
            try:
                with open(full) as f:
                    json.load(f)
            except ValueError as e:
                report["problems"].append(f"{fname}: torn JSON: {e}")
            except OSError as e:
                report["problems"].append(f"{fname}: unreadable: {e}")
        elif fname.startswith("tmp") and os.path.isfile(full):
            try:
                age = now - os.path.getmtime(full)
            except OSError:
                continue
            report["orphans"].append(
                {"path": fname, "age_s": round(age, 1),
                 "collectable": age >= grace_s})

    if gc:
        for o in report["orphans"]:
            if not o["collectable"]:
                continue
            try:
                os.unlink(os.path.join(root, o["path"]))
                report["collected"].append(o["path"])
            except OSError:
                pass  # raced another collector / vanished — fine
        report["orphans"] = [o for o in report["orphans"]
                             if o["path"] not in set(report["collected"])]

    report["clean"] = not report["problems"]
    return report
