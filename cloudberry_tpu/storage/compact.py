"""Background compaction service — the VACUUM analog (ISSUE 18).

The streaming ingest plane (storage/ingest.py) and the online
rebalancer (parallel/topology.py) both grow a store's manifests
sideways: small appends become small partitions, DELETEs become delete
vectors, and a rebalance leaves destination-tagged (``"seg"`` /
``"seg_nseg"``) delta partitions that nothing folds back in. This
service is the fold: a lifecycle-scoped, breaker-guarded worker with
the rebalancer's exact shape — throttled chunks, ONE OCC-checked atomic
manifest commit per chunk, journal-resumable, conflict re-reads and
retries while concurrent appends keep serving — that

- merges delta partitions (grouped by (pkey, seg, seg_nseg): routing
  tags and partition-pruning keys are load-bearing, so merges never
  cross them),
- applies delete vectors (a rewritten partition carries none; a fully
  deleted partition simply disappears),
- re-sorts merged rows toward the table's scan order (the range/list
  partition column when one is declared), and
- re-packs toward ``storage.rows_per_partition``,

maintaining the bounded-delta invariant: a table's delta-partition
count (``delta_parts``: dirty partitions + mergeable small tails) is
driven back toward 0 whenever it exceeds ``config.compact.
max_delta_parts`` (hysteresis — once triggered, a table compacts to
clean, so the invariant holds with slack rather than oscillating at
the threshold).

Correctness story: compaction only REARRANGES committed live rows — a
compacted store answers every query bit-identically to its un-compacted
self (pinned across TPC-H in tests/test_compaction.py). Concurrency is
pure OCC: the chunk reads a manifest snapshot, writes replacement files
to fresh names, then commits under the store lock only if the version
it read is still current; a concurrent INSERT/DELETE/append wins the
race and the chunk re-reads and retries (bounded). Replaced partition
files are NOT unlinked — older manifest versions stay readable, the
same snapshot semantics the rebalancer keeps. Only never-committed
orphans (an OCC loss, or a crash between file write and commit) are
deleted — the latter by the ``_COMPACTION.json`` journal on restart.

Lock discipline: ``CompactionService._cond`` (in the graftlint witness
order) guards worker lifecycle state only; it is NEVER held across
manifest reads, file writes, or the store lock.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid

import numpy as np

from cloudberry_tpu import lifecycle
from cloudberry_tpu.storage import iofault
from cloudberry_tpu.utils.faultinject import fault_point

_JOURNAL = "_COMPACTION.json"


class CompactionError(lifecycle.StatementError):
    """The chunk loop kept losing the OCC race (adversarial writer) —
    transient by nature, retry once the write burst passes."""

    retryable = True


# --------------------------------------------------------- delta census


def _live(part: dict) -> int:
    return part["num_rows"] - len(part["deleted"])


def _group_key(part: dict):
    return (part.get("pkey"), part.get("seg"), part.get("seg_nseg"))


def delta_parts(man: dict, rows_per_partition: int,
                target_fill: float) -> int:
    """The bounded invariant's census for one table: partitions with
    delete vectors (each needs a rewrite) plus, per (pkey, seg,
    seg_nseg) group, every mergeable small tail beyond the one natural
    tail a healthy append pattern always has."""
    fill_rows = max(1, int(rows_per_partition * target_fill))
    dirty = 0
    smalls: dict = {}
    for p in man.get("partitions", ()):
        if p["deleted"]:
            dirty += 1
        elif _live(p) < fill_rows:
            k = _group_key(p)
            smalls[k] = smalls.get(k, 0) + 1
    return dirty + sum(max(0, n - 1) for n in smalls.values())


def _select_chunk(man: dict, fill_rows: int, cap: int):
    """Pick one group's worth of work: dirty partitions first, then
    small clean tails, capped at ``cap`` sources. A lone small clean
    tail is NOT work (merging it with itself forever is the classic
    compaction livelock); a lone dirty partition is (the rewrite drops
    its delete vector). Groups are visited in manifest order —
    deterministic, and old debt ages out first."""
    groups: dict = {}
    order = []
    for p in man.get("partitions", ()):
        k = _group_key(p)
        if k not in groups:
            groups[k] = []
            order.append(k)
        groups[k].append(p)
    for k in order:
        dirty = [p for p in groups[k] if p["deleted"]]
        small = [p for p in groups[k]
                 if not p["deleted"] and _live(p) < fill_rows]
        if not dirty and len(small) < 2:
            continue
        return k, (dirty + small)[:max(1, cap)]
    return None, []


# ------------------------------------------------------------- the merge


def _read_live(store, name: str, part: dict) -> dict:
    """One source partition's live rows, every physical column."""
    from cloudberry_tpu.storage import micropartition as mp

    path = os.path.join(store.root, name, part["file"])
    cols = mp.read_columns(path, cipher=store.cipher,
                           verify=getattr(store, "verify_checksums", True))
    if part["deleted"]:
        keep = np.ones(part["num_rows"], dtype=bool)
        keep[np.asarray(part["deleted"], dtype=np.int64)] = False
        cols = {k: v[keep] for k, v in cols.items()}
    return cols


def _merge_columns(chunks: list[dict]) -> dict:
    """Concatenate per-file column dicts over the UNION of their
    physical columns. Files written before a column turned nullable
    lack its "$nn:" companion — those rows are all-valid by definition
    (ones), exactly the default the read path synthesizes. A missing
    DATA column would be schema drift this engine doesn't produce;
    refuse loudly rather than invent values."""
    names = []
    for c in chunks:
        for k in c:
            if k not in names:
                names.append(k)
    out = {}
    for k in names:
        pieces = []
        for c in chunks:
            v = c.get(k)
            if v is None:
                if not k.startswith("$nn:"):
                    raise CompactionError(
                        f"column {k!r} missing from a source partition "
                        "(schema drift) — refusing to merge")
                n = len(next(iter(c.values()))) if c else 0
                v = np.ones(n, dtype=np.bool_)
            pieces.append(np.asarray(v))
        out[k] = np.concatenate(pieces) if len(pieces) > 1 else pieces[0]
    return out


def _sort_for_scan(man: dict, cols: dict) -> dict:
    """Stable re-sort toward the table's declared scan order: the
    range/list partition column when present (partition pruning's
    min/max stats tighten the most there). No declared order is fine —
    merged rows keep source order (stable concatenation)."""
    spec = man.get("partition_spec")
    if not spec or len(spec) < 2:
        return cols
    key = spec[1]
    v = cols.get(key)
    if v is None or len(v) < 2:
        return cols
    order = np.argsort(np.asarray(v), kind="stable")
    return {k: np.ascontiguousarray(a[order]) for k, a in cols.items()}


class CompactionService:
    """The background fold. One instance per Server (or bare Session in
    tests); the ingest plane's ``on_commit`` pokes :meth:`wake` so debt
    from a write burst folds promptly, and the interval scan catches
    debt from DELETEs / rebalances that never touched ingest."""

    def __init__(self, session, exec_scope=None):
        cfg = session.config.compact
        self.session = session
        self.interval_s = max(0.05, float(cfg.interval_s))
        self.throttle_s = float(cfg.throttle_s)
        self.chunk_partitions = max(1, int(cfg.chunk_partitions))
        self.max_delta_parts = max(0, int(cfg.max_delta_parts))
        self.target_fill = float(cfg.target_fill)
        self._exec_scope = exec_scope  # parity with IngestService; the
        # chunk commit is pure OCC + store lock, so it does NOT take the
        # server write scope — holding it would stall foreground writes,
        # defeating the background contract
        self._cond = threading.Condition()
        self._stop = False
        self._thread = None
        self._wake = False
        self._last_delta_max = 0
        self.restore()

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        with self._cond:
            if self._thread is None and not self._stop:
                t = threading.Thread(target=self._worker,
                                     name="compactor", daemon=True)
                self._thread = t
                t.start()

    def stop(self) -> None:
        with self._cond:
            self._stop = True
            t, self._thread = self._thread, None
            self._cond.notify_all()
        if t is not None:
            t.join(timeout=10)

    def wake(self, table: str | None = None) -> None:
        """Called (outside any lock) after a committed ingest flush."""
        with self._cond:
            self._wake = True
            self._cond.notify_all()

    def _worker(self) -> None:
        log = getattr(self.session, "stmt_log", None)
        while True:
            with self._cond:
                if not (self._wake or self._stop):
                    self._cond.wait(timeout=self.interval_s)
                if self._stop:
                    return
                self._wake = False
            lifecycle.check_cancel()
            # breaker-guarded like planned cutover: a read-only-degraded
            # engine must not spend devices/IO on reorganization
            breaker = getattr(self.session, "_breaker", None)
            if breaker is not None \
                    and getattr(breaker, "state", "closed") == "open":
                continue
            try:
                self.run_once()
            except lifecycle.StatementCancelled:
                continue  # an operator cancelled one pass, not the service
            except Exception:  # noqa: BLE001 — the worker must survive
                if log is not None:
                    log.bump("compact_errors")

    # ----------------------------------------------------------- the scan

    def run_once(self, table: str | None = None,
                 force: bool = False) -> dict:
        """One full pass: every table over the invariant threshold
        (``force`` compacts regardless) is driven to a clean manifest.
        Returns the pass's counters; safe to call directly in tests."""
        store = getattr(self.session, "store", None)
        out = {"tables": 0, "chunks": 0, "rows": 0, "parts_merged": 0,
               "delta_parts_max": 0}
        if store is None:
            return out
        rpp = getattr(store, "rows_per_partition", 1 << 20)
        names = [table] if table is not None else sorted(
            store.table_names())
        worst = 0
        for name in names:
            man = store.read_manifest(name)
            if man["schema"] is None:
                continue
            dp = delta_parts(man, rpp, self.target_fill)
            if not force and dp <= self.max_delta_parts:
                worst = max(worst, dp)
                continue
            res = self._compact_table(name)
            out["tables"] += 1
            out["chunks"] += res["chunks"]
            out["rows"] += res["rows"]
            out["parts_merged"] += res["parts_merged"]
            worst = max(worst, delta_parts(
                store.read_manifest(name), rpp, self.target_fill))
        out["delta_parts_max"] = worst
        with self._cond:
            self._last_delta_max = worst
        return out

    def _compact_table(self, name: str) -> dict:
        """One table to clean, as ONE statement: the pass appears in the
        StatementLog / flight recorder / metrics exactly like foreground
        SQL, and ``stmt_log.cancel(sid)`` aborts it cooperatively at the
        next chunk seam (the pg_cancel_backend story holds for
        background work too)."""
        from cloudberry_tpu.obs import flightrec

        log = getattr(self.session, "stmt_log", None)
        sql = f"COMPACT {name}"
        sid = log.begin(sql) if log is not None else 0
        handle = lifecycle.StatementHandle(sid)
        if log is not None:
            log.attach(sid, handle)
        t0 = time.monotonic()
        try:
            with lifecycle.statement_scope(handle):
                totals = self._run_chunks(name, handle)
        except BaseException as e:
            if log is not None:
                log.finish(sid, "error",
                           error=f"{type(e).__name__}: {e}")
                flightrec.maybe_capture(
                    self.session, sql, "error", time.monotonic() - t0,
                    handle, error=e)
            raise
        if log is not None:
            log.finish(sid, "ok", rows=totals["rows"])
            flightrec.maybe_capture(
                self.session, sql, "ok", time.monotonic() - t0, handle,
                counters={f"compact_{k}": v for k, v in totals.items()})
        return totals

    def _run_chunks(self, name: str, handle) -> dict:
        store = self.session.store
        log = getattr(self.session, "stmt_log", None)
        rpp = getattr(store, "rows_per_partition", 1 << 20)
        fill_rows = max(1, int(rpp * self.target_fill))
        totals = {"chunks": 0, "rows": 0, "parts_merged": 0}
        attempts = 0
        while True:
            handle.check()
            man = store.read_manifest(name)
            if man["schema"] is None:
                return totals
            key, parts = _select_chunk(man, fill_rows,
                                       self.chunk_partitions)
            if not parts:
                return totals
            # the chunk seam: 'hang' wedges here cooperatively (the
            # cancel-mid-chunk chaos case polls handle.check via the
            # statement scope); 'error'/'skip' perturb the loop
            fault_point("compact_chunk")
            ok, rows = self._merge_chunk(store, name, man, key, parts)
            if not ok:
                if log is not None:
                    log.bump("compact_conflicts")
                attempts += 1
                if attempts > 20:
                    raise CompactionError(
                        f"compaction of {name!r} kept losing the OCC "
                        "race; aborting (will retry next pass)")
                continue
            attempts = 0
            totals["chunks"] += 1
            totals["rows"] += rows
            totals["parts_merged"] += len(parts)
            if log is not None:
                log.bump("compact_chunks")
                log.bump("compact_rows", rows)
                log.bump("compact_parts_merged", len(parts))
            self._journal_progress(store, chunks=1, rows=rows,
                                   parts_merged=len(parts))
            if self.throttle_s > 0:
                time.sleep(self.throttle_s)

    # ----------------------------------------------------------- one chunk

    def _merge_chunk(self, store, name: str, man: dict, key,
                     parts: list[dict]) -> tuple[bool, int]:
        """Merge one group's sources into re-sorted, re-packed
        replacements; ONE atomic OCC-checked manifest commit. Returns
        (committed, live_rows); committed=False is the conflict signal
        (caller re-reads and retries). The journal's pending record
        brackets the file writes so a crash anywhere in between leaves
        only orphans a restart can identify and delete."""
        from cloudberry_tpu.columnar.dictionary import StringDictionary
        from cloudberry_tpu.storage import micropartition as mp
        from cloudberry_tpu.types import BOOL, Field as TField, Schema

        pkey, seg, seg_nseg = key
        tdir = os.path.join(store.root, name)
        cols = _sort_for_scan(man, _merge_columns(
            [_read_live(store, name, p) for p in parts]))
        n_live = len(next(iter(cols.values()))) if cols else 0
        rpp = getattr(store, "rows_per_partition", 1 << 20)
        # physical schema: manifest data fields + "$nn:" bools (the
        # rebalancer's exact recipe, topology._move_partition_delta)
        fields = {f.name: f for f in
                  (mp._field_from_json(j) for j in man["schema"])}
        phys_fields = []
        for cname in cols:
            if cname in fields:
                phys_fields.append(fields[cname])
            elif cname.startswith("$nn:"):
                phys_fields.append(TField(cname, BOOL))
        phys_schema = Schema(tuple(phys_fields))
        dicts = {k: StringDictionary(v)
                 for k, v in man.get("dicts", {}).items()}
        plan = [(f"part-{uuid.uuid4().hex}.cbmp", lo,
                 min(lo + rpp, n_live))
                for lo in range(0, n_live, max(rpp, 1))]
        self._journal_pending(store, name, [f for f, _, _ in plan])
        new_entries = []
        try:
            for fname, lo, hi in plan:
                chunk = {k: np.ascontiguousarray(v[lo:hi])
                         for k, v in cols.items()}
                footer = mp.write_micropartition(
                    os.path.join(tdir, fname), chunk, phys_schema,
                    dicts, cipher=store.cipher)
                stats = {c["name"]: [c["min"], c["max"]]
                         for c in footer["columns"] if "min" in c}
                entry = {"file": fname, "num_rows": hi - lo,
                         "stats": stats, "deleted": []}
                if pkey is not None:
                    entry["pkey"] = pkey
                if seg is not None:
                    entry["seg"] = seg
                if seg_nseg is not None:
                    entry["seg_nseg"] = seg_nseg
                new_entries.append(entry)
            gone = {p["file"] for p in parts}
            with store.lock():
                # the crash-restart seam: an 'error' here dies AFTER the
                # replacement files exist but BEFORE the commit — the
                # journal's pending record is what makes that survivable
                fault_point("compact_commit")
                if store.current_version(name) != man["version"]:
                    for e in new_entries:
                        try:
                            os.unlink(os.path.join(tdir, e["file"]))
                        except FileNotFoundError:
                            pass  # already gone — nothing was lost
                        except OSError as exc:
                            # an undeletable orphan is an IO fault worth
                            # counting; fsck's GC sweep retries it later
                            iofault.note_io_error(e["file"], exc)
                    self._journal_pending(store, None, None)
                    return False, 0
                man["partitions"] = [p for p in man["partitions"]
                                     if p["file"] not in gone]
                man["partitions"] = man["partitions"] + new_entries
                store._commit(name, man)
        except BaseException:
            # pending stays set: the restart journal owns the cleanup
            raise
        self._journal_pending(store, None, None)
        return True, n_live

    # ------------------------------------------------------------- journal

    def _journal_path(self, store) -> str:
        return os.path.join(store.root, _JOURNAL)

    def _read_journal(self, store) -> dict:
        try:
            with open(self._journal_path(store)) as f:
                rec = json.load(f)
        except (FileNotFoundError, ValueError):
            rec = {}
        rec.setdefault("counters", {"chunks": 0, "rows": 0,
                                    "parts_merged": 0})
        rec.setdefault("pending", None)
        return rec

    def _journal_pending(self, store, table, files) -> None:
        rec = self._read_journal(store)
        rec["pending"] = ({"table": table, "files": list(files)}
                          if table is not None else None)
        # the journal's own durability seam: a crash here must leave
        # either the old or the new pending record, never torn JSON
        fault_point("io_journal_write")
        store._atomic_json(self._journal_path(store), rec)

    def _journal_progress(self, store, **deltas) -> None:
        rec = self._read_journal(store)
        for k, v in deltas.items():
            rec["counters"][k] = rec["counters"].get(k, 0) + v
        store._atomic_json(self._journal_path(store), rec)

    def restore(self) -> None:
        """Crash recovery, run at construction: a pending record names
        replacement files whose commit may or may not have happened —
        files absent from the table's CURRENT manifest are orphans from
        a pre-commit crash and are deleted; files present committed
        (the crash was after) and stay. Either way the store is clean
        and the next pass re-derives its work from the manifest —
        resumability without replaying anything."""
        store = getattr(self.session, "store", None)
        if store is None:
            return
        rec = self._read_journal(store)
        pend = rec.get("pending")
        if not pend:
            return
        name = pend["table"]
        try:
            man = store.read_manifest(name)
            committed = {p["file"] for p in man.get("partitions", ())}
        except Exception:  # noqa: BLE001 — table may be gone entirely
            committed = set()
        for f in pend.get("files", ()):
            if f not in committed:
                try:
                    os.unlink(os.path.join(store.root, name, f))
                except FileNotFoundError:
                    pass  # already gone — nothing to clean
                except OSError as exc:
                    iofault.note_io_error(f, exc)
        self._journal_pending(store, None, None)
        log = getattr(self.session, "stmt_log", None)
        if log is not None:
            log.bump("compact_journal_restores")

    # ------------------------------------------------------------ telemetry

    def delta_parts_gauge(self) -> int:
        """Last pass's worst per-table delta count (the capacity-plane
        gauge feed; a fresh manifest census per gauge refresh would be
        IO on the telemetry path)."""
        with self._cond:
            return self._last_delta_max

    def snapshot(self) -> dict:
        """``meta "compaction"``: config, live per-table census, counter
        story, and the journal's durable progress in one read."""
        store = getattr(self.session, "store", None)
        with self._cond:
            running = self._thread is not None and not self._stop
        out = {"enabled": True, "running": running,
               "interval_s": self.interval_s,
               "throttle_s": self.throttle_s,
               "chunk_partitions": self.chunk_partitions,
               "max_delta_parts": self.max_delta_parts,
               "target_fill": self.target_fill,
               "tables": []}
        if store is not None:
            rpp = getattr(store, "rows_per_partition", 1 << 20)
            for name in sorted(store.table_names()):
                man = store.read_manifest(name)
                if man["schema"] is None:
                    continue
                out["tables"].append(
                    {"table": name,
                     "partitions": len(man["partitions"]),
                     "delta_parts": delta_parts(man, rpp,
                                                self.target_fill)})
            out["journal"] = self._read_journal(store)["counters"]
        log = getattr(self.session, "stmt_log", None)
        if log is not None:
            for c in ("compact_chunks", "compact_rows",
                      "compact_parts_merged", "compact_conflicts",
                      "compact_errors", "compact_journal_restores"):
                out[c.replace("compact_", "")] = log.counter(c)
        return out
