"""Faulty-IO shim — the storage layer's one set of write primitives.

Every durable write in the store (micro-partition bodies, v{N}.json
manifests, the CURRENT swap, _SEQUENCES/_MATVIEWS/_TOPOLOGY/_FEEDBACK
json, the compaction journal) goes through this module, which buys three
things at once (ISSUE 19):

- ONE place that gets durability right: whole-file writes fsync before
  they count (a micro-partition that only reached the page cache when
  the manifest committed was a silent torn-store bug), atomic JSON
  replaces fsync the temp file AND the directory entry (os.replace is
  only crash-durable once the directory is);
- a fault surface the chaos/torture tests drive through the existing
  faultinject inventory: the caller declares ``fault_point("io_*")`` at
  the seam, and when an armed IO action fires there this module
  implements it against the very next write — torn write (prefix only),
  short write, dropped fsync (bytes vanish at ``simulated_crash()``),
  ENOSPC, EIO;
- the ``storage_io_errors`` counter + typed taxonomy: OS-layer write
  failures surface as retryable ``StorageIOError`` (the previous
  snapshot is intact — the commit protocol guarantees it), never as a
  silent ``except OSError: pass``.

The reference analog is the xlog.c discipline: WAL/data writes funnel
through one durability layer that knows when fsync is required, and the
fault-injection build corrupts exactly that layer.
"""

from __future__ import annotations

import errno
import json
import os
import tempfile
import threading
import zlib
from typing import Optional

from cloudberry_tpu.lifecycle import StorageIOError
from cloudberry_tpu.utils import faultinject

# rank-5 innermost leaf in the graftlint witness order (lint/config.py):
# guards the counter dict and the unsynced-write registry only; nothing
# is called while it is held, and rank-4 holders (the feedback store's
# _io_lock) reach it through durable_write
_lock = threading.Lock()
_counts = {"storage_io_errors": 0}
# fsync-dropped writes: path -> True if the file existed before the
# write. simulated_crash() "loses power": the buffered bytes vanish.
_unsynced: dict[str, bool] = {}


# ------------------------------------------------------------- counters


def note_io_error(path: str, exc: Optional[BaseException] = None) -> None:
    """Count one storage-layer IO failure (the ``storage_io_errors``
    counter). Callers with a StatementLog in reach mirror it there so
    the metrics exposition carries it too."""
    with _lock:
        _counts["storage_io_errors"] += 1


def io_error_count() -> int:
    with _lock:
        return _counts["storage_io_errors"]


def reset_counters() -> None:
    with _lock:
        _counts["storage_io_errors"] = 0


# ------------------------------------------------------------ checksums

# Content checksum for micro-partition column blobs: crc32 via zlib's C
# loop — the xxhash-class point (fast, non-cryptographic, catches bit
# flips and truncation) without a new dependency. Stored in the footer
# as "crc32:<hex>" so the algorithm can evolve without ambiguity.


KNOWN_HASH_ALGOS = frozenset({"crc32"})


def content_hash(data: bytes) -> str:
    return f"crc32:{zlib.crc32(data) & 0xFFFFFFFF:08x}"


def hash_verdict(stored: str, data: bytes) -> str:
    """``"ok"`` | ``"mismatch"`` | ``"unknown"``. An unrecognized
    algorithm prefix is indistinguishable from a corrupted label (the
    ``crc32:`` tag itself can take the bit flip), so it gets its own
    verdict: the hot read path stays lenient for forward compat, but
    the offline pass (verify_file) reports it instead of treating the
    blob as verified."""
    algo, _, _hex = stored.partition(":")
    if algo not in KNOWN_HASH_ALGOS:
        return "unknown"
    return "ok" if content_hash(data) == stored else "mismatch"


def hash_matches(stored: str, data: bytes) -> bool:
    return hash_verdict(stored, data) != "mismatch"


# --------------------------------------------------------------- writes


def _partial(path: str, data: bytes, n: int) -> None:
    """Leave a prefix on disk, unsynced — what a torn write leaves."""
    try:
        with open(path, "wb") as f:
            f.write(data[:n])
    except OSError:
        pass  # the injected failure is about to be raised anyway


def durable_write(path: str, data: bytes, fsync: bool = True) -> None:
    """Write ``data`` to ``path`` whole-file, fsynced by default.
    Implements the thread's pending armed IO fault (the caller's
    preceding ``fault_point("io_*")`` seam); OS failures raise
    ``StorageIOError`` and count."""
    pending = faultinject.take_io_action()
    act = pending[1] if pending else None
    if act == "eio":
        note_io_error(path)
        raise StorageIOError(
            f"{path}: I/O error (injected EIO at {pending[0]!r})")
    if act == "enospc":
        _partial(path, data, len(data) // 2)
        note_io_error(path)
        e = StorageIOError(
            f"{path}: no space left on device (injected ENOSPC at "
            f"{pending[0]!r})")
        e.errno = errno.ENOSPC
        raise e
    if act == "torn":
        _partial(path, data, len(data) // 2)
        note_io_error(path)
        raise StorageIOError(
            f"{path}: torn write — {len(data) // 2} of {len(data)} "
            f"bytes reached disk (injected at {pending[0]!r})")
    if act == "short":
        _partial(path, data, max(len(data) - 8, 0))
        note_io_error(path)
        raise StorageIOError(
            f"{path}: short write — os.write returned fewer bytes "
            f"than requested (injected at {pending[0]!r})")
    existed = os.path.exists(path)
    try:
        with open(path, "wb") as f:
            f.write(data)
            if fsync and act != "fsync_drop":
                f.flush()
                os.fsync(f.fileno())
    except OSError as e:
        note_io_error(path, e)
        raise StorageIOError(f"{path}: {e}") from e
    if act == "fsync_drop":
        with _lock:
            _unsynced.setdefault(path, existed)


def fsync_dir(path: str) -> None:
    """Best-effort directory fsync: os.replace is only crash-durable
    once the directory entry is on disk. Some filesystems refuse
    O_RDONLY-fsync on directories (EINVAL/EACCES) — those journal the
    rename anyway."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_json(path: str, obj, dirpath: Optional[str] = None) -> None:
    """Durable atomic JSON replace: temp file in ``dirpath`` (default:
    the target's directory), fsynced write, os.replace, directory
    fsync. A failure at ANY step leaves the previous file intact — torn
    JSON is structurally impossible on this path."""
    data = json.dumps(obj).encode()
    d = dirpath or os.path.dirname(path) or "."
    try:
        fd, tmp = tempfile.mkstemp(dir=d)
        os.close(fd)
    except OSError as e:
        note_io_error(path, e)
        raise StorageIOError(f"{path}: {e}") from e
    existed = os.path.exists(path)
    try:
        durable_write(tmp, data)
        os.replace(tmp, path)
    except StorageIOError:
        _unlink_quiet(tmp)
        raise
    except OSError as e:
        _unlink_quiet(tmp)
        note_io_error(path, e)
        raise StorageIOError(f"{path}: {e}") from e
    # an fsync-dropped temp write was just renamed onto the target: the
    # bytes at risk now live at the DESTINATION path (replace moves the
    # unsynced inode), so the crash simulation must lose it there — with
    # the pre-replace existence deciding truncate-vs-unlink
    _migrate_unsynced(tmp, path, existed)
    fsync_dir(d)


def _unlink_quiet(path: str) -> None:
    try:
        os.unlink(path)
    except OSError:
        pass


def _migrate_unsynced(old: str, new: str, new_existed: bool) -> None:
    """os.replace moved an unsynced write from ``old`` to ``new``."""
    with _lock:
        if old in _unsynced:
            del _unsynced[old]
            _unsynced.setdefault(new, new_existed)


# -------------------------------------------------- simulated power loss


def simulated_crash() -> list[str]:
    """Lose every fsync-dropped write, as a power cut would: files that
    did not exist before vanish; rewrites lose their buffered bytes
    (truncate — the on-disk state of an unsynced overwrite is
    undefined, and empty is the adversarial case). Returns the affected
    paths — tests assert the store recovers without them."""
    with _lock:
        items = sorted(_unsynced.items())
        _unsynced.clear()
    lost = []
    for path, existed in items:
        try:
            if existed:
                with open(path, "r+b") as f:
                    f.truncate(0)
            else:
                os.unlink(path)
            lost.append(path)
        except OSError:
            continue
    return lost


def unsynced_paths() -> list[str]:
    with _lock:
        return sorted(_unsynced)
