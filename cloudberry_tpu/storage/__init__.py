from cloudberry_tpu.storage.micropartition import (
    read_columns,
    read_footer,
    write_micropartition,
)
from cloudberry_tpu.storage.table_store import TableStore

__all__ = ["write_micropartition", "read_footer", "read_columns", "TableStore"]
