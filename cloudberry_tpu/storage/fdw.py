"""Foreign data wrappers — external engines as scannable tables.

The reference's FDW layer lets a foreign server answer scans through a
per-server access driver (PostgreSQL FDW API; the reference ships
gp2gp/jdbc-style wrappers in contrib). Same shape here, sized for this
engine's statement model: a FOREIGN TABLE re-fetches from its server at
every referencing statement (like external tables, planner.py
_refresh_referenced_externals), so queries always see the source's
current rows; everything downstream — distribution, pruning, joins —
treats the fetched batch as an ordinary table.

``register_fdw(name, reader)`` is also the CustomScan-style extension
hook: a reader is any callable (options, schema) -> iterable of row
tuples, so plugging an arbitrary compute source in takes three lines.

Built-in servers:
- ``sqlite``: reads a table or arbitrary query from a SQLite database
  (stdlib sqlite3) — OPTIONS (database '/path/db', table 't') or
  (database '...', query 'select ...').
"""

from __future__ import annotations

from typing import Callable, Iterable

import numpy as np

from cloudberry_tpu import types as T


class FdwError(RuntimeError):
    pass


_SERVERS: dict[str, Callable] = {}


def register_fdw(name: str, reader: Callable[[dict, object],
                                             Iterable[tuple]]) -> None:
    """Register a foreign server: reader(options, schema) -> row tuples."""
    _SERVERS[name.lower()] = reader


def known_servers() -> list[str]:
    return sorted(_SERVERS)


def fetch_foreign(session, t) -> None:
    """(Re)load a foreign table from its server — called at statement
    start for referenced foreign tables."""
    spec = t.foreign
    reader = _SERVERS.get(spec["server"])
    if reader is None:
        raise FdwError(f"unknown foreign server {spec['server']!r} "
                       f"(known: {', '.join(known_servers())})")
    try:
        rows = list(reader(spec["options"], t.schema))
    except FdwError:
        raise
    except Exception as e:  # noqa: BLE001 — driver errors surface as FDW
        raise FdwError(f"foreign table {t.name!r}: {type(e).__name__}: {e}")
    data, validity = rows_to_columns(rows, t.schema, t.dicts)
    t._loading = True  # ephemeral: foreign rows never persist to the store
    try:
        t.set_data(data, t.dicts, validity=validity)
    finally:
        t._loading = False


def rows_to_columns(rows: list[tuple], schema, dicts):
    """Typed python row tuples -> columnar arrays + validity masks
    (NULLs canonicalize later in set_data)."""
    from cloudberry_tpu.columnar.batch import encode_column

    n = len(rows)
    data: dict[str, np.ndarray] = {}
    validity: dict[str, np.ndarray] = {}
    for i, f in enumerate(schema.fields):
        vals = [r[i] if i < len(r) else None for r in rows]
        isnull = np.asarray([v is None for v in vals], dtype=np.bool_)
        if isnull.any() and not f.nullable:
            raise FdwError(f"NULL in NOT NULL foreign column {f.name!r}")
        try:
            if f.dtype == T.DType.DECIMAL:
                scale = 10 ** f.type.scale
                arr = np.asarray(
                    [0 if v is None else int(round(float(v) * scale))
                     for v in vals], dtype=np.int64)
            elif f.dtype in (T.DType.INT32, T.DType.INT64):
                arr = np.asarray([0 if v is None else int(v)
                                  for v in vals]).astype(f.type.np_dtype)
            elif f.dtype == T.DType.FLOAT64:
                arr = np.asarray([0.0 if v is None else float(v)
                                  for v in vals], dtype=np.float64)
            elif f.dtype == T.DType.DATE:
                arr = np.asarray(
                    [0 if v is None else T.date_to_days(str(v))
                     for v in vals]).astype(f.type.np_dtype)
            else:
                arr = encode_column(
                    np.asarray(["" if v is None else str(v)
                                for v in vals], dtype=object), f, dicts)
        except (ValueError, TypeError, OverflowError) as e:
            raise FdwError(f"bad foreign value for column {f.name!r}: {e}")
        data[f.name] = arr
        if isnull.any():
            validity[f.name] = ~isnull
    if not data and n:
        raise FdwError("foreign schema has no columns")
    return data, validity


# ------------------------------------------------------- built-in servers


def _sqlite_reader(options: dict, schema) -> Iterable[tuple]:
    import sqlite3

    db = options.get("database")
    if not db:
        raise FdwError("sqlite server needs OPTIONS (database '...')")
    query = options.get("query")
    if query is None:
        table = options.get("table")
        if not table:
            raise FdwError("sqlite server needs a table or query option")
        if not table.replace("_", "").isalnum():
            raise FdwError(f"bad sqlite table name {table!r}")
        cols = ", ".join(f.name for f in schema.fields)
        query = f"SELECT {cols} FROM {table}"  # noqa: S608 — name checked
    con = sqlite3.connect(f"file:{db}?mode=ro", uri=True)
    try:
        yield from con.execute(query)
    finally:
        con.close()


register_fdw("sqlite", _sqlite_reader)
