"""Micro-partition files — the PAX / AO-columnar analog.

The reference's columnar storage (contrib/pax_storage: ORC-like micro
partitions with protobuf footer metadata, min/max + bloom stats, zstd/RLE
encodings; and AO varblocks, src/backend/access/appendonly/README.md) maps
here to immutable single-file micro-partitions:

    [magic][column blocks...][footer JSON][footer_len: u32][magic]

Footer carries schema, per-column encoding + byte ranges + min/max stats, and
the string dictionaries. Readers prune whole files on stats before touching
column bytes, then read only requested columns (column projection) — the same
two moves PAX's sparse filters make (micro_partition_stats.cc). Encodings:
raw | zlib | rle (run-length + zlib'd runs), chosen per column by measured
size. zstd used when available (it is in this image), zlib as the fallback —
mirroring the reference's zstd/zlib ladder.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time
import zlib
from typing import Iterable, Optional

import numpy as np

try:
    import zstandard as _zstd

    _ZC = _zstd.ZstdCompressor(level=3)
except Exception:  # pragma: no cover
    _zstd = None

# Decompression contexts are PER THREAD: the zstd context is stateful
# and not safe for concurrent decompress calls, and the scan pipeline
# (exec/scanpipe.py) decodes columns in parallel across a reader pool.
# The native codecs (zstd, zlib, the dvarint C path) all release the
# GIL on big blocks, so per-thread contexts are what actually lets the
# pool overlap — a single shared context would serialize right back.
_TLS = threading.local()


def _zstd_dctx():
    d = getattr(_TLS, "zd", None)
    if d is None:
        d = _TLS.zd = _zstd.ZstdDecompressor()
    return d

from cloudberry_tpu.columnar.dictionary import StringDictionary
from cloudberry_tpu.lifecycle import StorageCorruptionError
from cloudberry_tpu.storage import iofault
from cloudberry_tpu.types import DType, Field, Schema, SqlType
from cloudberry_tpu.utils.faultinject import fault_point

MAGIC = b"CBTPMP1\n"
MAGIC_ENC = b"CBMPENC1"  # TDE-encrypted container (utils/tde.py)

# Verified-clean memo for the scan-path checksum check: partition files
# are IMMUTABLE once committed (append/compact only ever write NEW
# files), and a repeat read on a warm page cache re-checks the same
# cached bytes — it can detect nothing the first check did not. So each
# (file, column) verifies ONCE per process per on-disk identity
# (size + mtime_ns key the entry; a rewritten or bit-flipped-then-
# retouched file re-verifies), which is the reference discipline:
# pg_checksums-protected pages verify when they ENTER the buffer pool,
# not on every buffer access. fsck's deep pass (verify_file) never
# consults the memo — offline verification is always full. Benign
# races only (two threads may both verify a key); cleared wholesale at
# the cap because correctness never depends on a hit.
_VERIFIED_CAP = 65536
_verified: dict[tuple, bool] = {}


def _compress(raw: bytes, codec: str) -> bytes:
    if codec == "zstd":
        return _ZC.compress(raw)
    if codec == "zlib":
        return zlib.compress(raw, 6)
    return raw


def _decompress(buf: bytes, codec: str) -> bytes:
    if codec == "zstd":
        return _zstd_dctx().decompress(buf)
    if codec == "zlib":
        return zlib.decompress(buf)
    return buf


def decode_column(enc: dict, blob: bytes, dtype: np.dtype,
                  num_rows: int) -> np.ndarray:
    """Decode ONE column's stored blob to its array — the unit of work
    the scan pipeline's reader pool parallelizes (thread-safe: per-
    thread decompression contexts, native dvarint path where the
    toolchain built it)."""
    raw = _decompress(blob, enc["codec"])
    if enc["encoding"] == "rle":
        return _rle_decode(raw, enc["n_runs"], dtype, num_rows)
    if enc["encoding"] == "dvarint":
        from cloudberry_tpu import native

        return native.dvarint_decode(raw, num_rows).astype(dtype)
    return np.frombuffer(raw, dtype=dtype, count=num_rows).copy()


def _rle_encode(arr: np.ndarray) -> Optional[tuple[bytes, int]]:
    """Run-length encode; None if it wouldn't help (too many runs)."""
    if len(arr) == 0:
        return b"", 0
    change = np.nonzero(np.diff(arr))[0]
    n_runs = len(change) + 1
    if n_runs * 12 >= arr.nbytes:
        return None
    starts = np.concatenate([[0], change + 1])
    lengths = np.diff(np.concatenate([starts, [len(arr)]]))
    values = arr[starts]
    raw = lengths.astype(np.int32).tobytes() + values.tobytes()
    return raw, n_runs


def _rle_decode(raw: bytes, n_runs: int, dtype: np.dtype, n: int) -> np.ndarray:
    lengths = np.frombuffer(raw, dtype=np.int32, count=n_runs)
    values = np.frombuffer(raw, dtype=dtype, offset=n_runs * 4, count=n_runs)
    return np.repeat(values, lengths)[:n]


def write_micropartition(path: str, data: dict[str, np.ndarray],
                         schema: Schema,
                         dicts: dict[str, StringDictionary] | None = None,
                         codec: str | None = None,
                         cipher=None) -> dict:
    """Write one immutable micro-partition; returns its footer dict.
    ``cipher`` (TDE, the pg_tde analog): an object with
    encrypt(bytes)/decrypt(bytes) — the whole file encrypts, because
    footers carry min/max stats and string dictionaries (data, not just
    metadata)."""
    dicts = dicts or {}
    codec = codec or ("zstd" if _zstd is not None else "zlib")
    n = len(next(iter(data.values()))) if data else 0
    columns = []
    blobs = []
    offset = len(MAGIC)
    for f in schema.fields:
        arr = np.ascontiguousarray(data[f.name])
        enc: dict = {"name": f.name, "codec": codec}
        rle = _rle_encode(arr)
        dv = None
        if rle is None and arr.dtype == np.int64 and len(arr):
            # delta varint (native codec) wins on keys/sorted-ish int64
            from cloudberry_tpu import native

            dv = native.dvarint_encode(arr)
            if len(dv) * 2 > arr.nbytes:
                dv = None  # not worth it
        if rle is not None:
            raw, n_runs = rle
            enc["encoding"] = "rle"
            enc["n_runs"] = n_runs
        elif dv is not None:
            raw = dv
            enc["encoding"] = "dvarint"
        else:
            raw = arr.tobytes()
            enc["encoding"] = "raw"
        blob = _compress(raw, codec)
        if len(blob) >= len(raw) and enc["encoding"] == "raw":
            blob = raw
            enc["codec"] = "none"
        enc["offset"] = offset
        enc["length"] = len(blob)
        # content checksum of the stored blob (the pg_checksums analog,
        # ISSUE 19): verified at decode behind storage.verify_checksums
        # and by `mgmt fsck` — a flipped bit is a typed
        # StorageCorruptionError, never a wrong answer
        enc["cksum"] = iofault.content_hash(blob)
        if f.dtype != DType.STRING and n and arr.dtype.kind in "iuf":
            enc["min"] = _json_num(arr.min())
            enc["max"] = _json_num(arr.max())
            if arr.dtype.kind in "iu":
                # bloom filter for equality pruning (PAX
                # micro_partition_stats.cc bloom move): point predicates
                # skip files min/max can't exclude
                enc["bloom"] = _bloom_build(arr)
        if f.dtype == DType.STRING and f.name in dicts:
            enc["dictionary"] = dicts[f.name].values
        offset += len(blob)
        columns.append(enc)
        blobs.append(blob)

    footer = {
        "format": 1,
        "num_rows": n,
        "schema": [_field_json(f) for f in schema.fields],
        "columns": columns,
    }
    fbytes = json.dumps(footer).encode()
    body = bytearray(MAGIC)
    for b in blobs:
        body += b
    body += fbytes
    body += struct.pack("<I", len(fbytes))
    body += MAGIC
    if cipher is not None:
        # TDE: the WHOLE file encrypts — footers carry min/max stats and
        # string dictionaries, which are data, not just metadata
        body = MAGIC_ENC + cipher.encrypt(bytes(body))
    # the faulty-IO seam + the one durable write path: partition bytes
    # must be ON DISK before the manifest that references them commits
    # (fsync here; the commit fsyncs the manifest and CURRENT)
    fault_point("io_partition_write")
    iofault.durable_write(path, bytes(body))
    return footer


def _file_bytes(path: str, cipher) -> bytes:
    """Whole file, decrypted when TDE is on. Random access trades away:
    an encrypted file reads fully even for one column — the at-rest
    security boundary costs sequential IO, like the reference's TDE."""
    with open(path, "rb") as fh:
        head = fh.read(len(MAGIC_ENC))
        if head == MAGIC_ENC:
            if cipher is None:
                raise ValueError(
                    f"{path}: encrypted micro-partition but no "
                    "storage.encryption_key configured")
            return cipher.decrypt(fh.read())
        return head + fh.read()


def read_footer(path: str, cipher=None) -> dict:
    with open(path, "rb") as fh:
        head = fh.read(len(MAGIC_ENC))
        if head != MAGIC_ENC:
            # plaintext: seek to the trailer — footer-only pruning reads
            # must stay ~KB regardless of partition size
            if head[:len(MAGIC)] != MAGIC:
                raise ValueError(f"{path}: not a micro-partition file")
            fh.seek(-(len(MAGIC) + 4), 2)
            (flen,) = struct.unpack("<I", fh.read(4))
            tail = fh.read(len(MAGIC))
            if tail != MAGIC:
                raise ValueError(f"{path}: corrupt trailer")
            fh.seek(-(len(MAGIC) + 4 + flen), 2)
            return json.loads(fh.read(flen))
    # TDE: random access trades away — decrypt the whole file
    buf = _file_bytes(path, cipher)
    if buf[:len(MAGIC)] != MAGIC or buf[-len(MAGIC):] != MAGIC:
        raise ValueError(f"{path}: corrupt encrypted container")
    (flen,) = struct.unpack(
        "<I", buf[-(len(MAGIC) + 4):-len(MAGIC)])
    return json.loads(buf[-(len(MAGIC) + 4 + flen):-(len(MAGIC) + 4)])


def read_columns(path: str, names: Iterable[str] | None = None,
                 footer: dict | None = None,
                 cipher=None, pool=None,
                 on_decode=None, verify=False) -> dict[str, np.ndarray]:
    """Read (selected columns of) one micro-partition. ``pool``: a
    concurrent.futures-style executor for column-parallel decode (blob
    IO stays sequential — one file, one descriptor; the CPU work fans
    out). ``on_decode(seconds)`` reports each column's pure decode
    wall — the ``decode_seconds`` histogram feed. ``verify``: check
    each blob against its footer content checksum before decoding
    (storage.verify_checksums) — a mismatch raises
    ``StorageCorruptionError`` instead of decoding garbage."""
    with open(path, "rb") as fh:
        head = fh.read(len(MAGIC_ENC))
    if head == MAGIC_ENC:
        # TDE: sequential whole-file decrypt, then in-memory slicing
        buf = _file_bytes(path, cipher)

        def read_blob(enc):
            return buf[enc["offset"]:enc["offset"] + enc["length"]]

        if footer is None:
            (flen,) = struct.unpack(
                "<I", buf[-(len(MAGIC) + 4):-len(MAGIC)])
            footer = json.loads(
                buf[-(len(MAGIC) + 4 + flen):-(len(MAGIC) + 4)])
    else:
        # plaintext: seek-based column projection (no whole-file read)
        footer = footer or read_footer(path)
        fh = open(path, "rb")

        def read_blob(enc, fh=fh):
            fh.seek(enc["offset"])
            return fh.read(enc["length"])

    try:
        want = set(names) if names is not None else None
        schema = {c["name"]: c for c in footer["columns"]}
        types = {f["name"]: _field_from_json(f) for f in footer["schema"]}
        n = footer["num_rows"]
        sel = [(name, enc) for name, enc in schema.items()
               if want is None or name in want]
        # sequential blob reads (one descriptor), then fan the decode out
        blobs = {name: read_blob(enc) for name, enc in sel}

        if verify:
            st = os.stat(path)
            ident = (path, st.st_size, st.st_mtime_ns)

        def _one(name, enc):
            t0 = time.perf_counter()
            if verify and "cksum" in enc \
                    and (name,) + ident not in _verified:
                if not iofault.hash_matches(enc["cksum"], blobs[name]):
                    raise StorageCorruptionError(
                        f"{path}: column {name!r} failed its content "
                        f"checksum ({enc['cksum']}) — stored bytes are "
                        "corrupt; run `mgmt fsck`")
                if len(_verified) >= _VERIFIED_CAP:
                    _verified.clear()
                _verified[(name,) + ident] = True
            arr = decode_column(enc, blobs[name],
                                types[name].type.np_dtype, n)
            if on_decode is not None:
                on_decode(time.perf_counter() - t0)
            return arr

        if pool is not None and len(sel) > 1:
            futs = [(name, pool.submit(_one, name, enc))
                    for name, enc in sel]
            return {name: f.result() for name, f in futs}
        return {name: _one(name, enc) for name, enc in sel}
    finally:
        if head != MAGIC_ENC:
            fh.close()


def verify_file(path: str, cipher=None) -> list[str]:
    """Offline integrity check of one micro-partition (the fsck deep
    pass): container framing parses and every column blob matches its
    footer checksum. Returns problem descriptions (empty = clean);
    never raises for corruption — fsck wants the list, not the first
    failure."""
    problems = []
    try:
        footer = read_footer(path, cipher=cipher)
    except Exception as e:  # noqa: BLE001 — any parse failure IS the finding
        return [f"{path}: unreadable container/footer: {e}"]
    try:
        with open(path, "rb") as fh:
            head = fh.read(len(MAGIC_ENC))
        if head == MAGIC_ENC:
            buf = _file_bytes(path, cipher)

            def read_blob(enc):
                return buf[enc["offset"]:enc["offset"] + enc["length"]]
        else:
            fh = open(path, "rb")

            def read_blob(enc, fh=fh):
                fh.seek(enc["offset"])
                return fh.read(enc["length"])
        try:
            for enc in footer["columns"]:
                if "cksum" not in enc:
                    continue  # pre-checksum file: nothing to verify
                blob = read_blob(enc)
                if len(blob) != enc["length"]:
                    problems.append(
                        f"{path}: column {enc['name']!r} truncated "
                        f"({len(blob)} of {enc['length']} bytes)")
                    continue
                verdict = iofault.hash_verdict(enc["cksum"], blob)
                if verdict == "mismatch":
                    problems.append(
                        f"{path}: column {enc['name']!r} failed its "
                        f"content checksum ({enc['cksum']})")
                elif verdict == "unknown":
                    # a corrupted algorithm label must not read as clean
                    # offline; the hot path alone stays lenient for
                    # forward-compat footers
                    problems.append(
                        f"{path}: column {enc['name']!r} carries an "
                        f"unknown checksum algorithm ({enc['cksum']!r}) "
                        "— cannot verify")
        finally:
            if head != MAGIC_ENC:
                fh.close()
    except Exception as e:  # noqa: BLE001
        problems.append(f"{path}: unreadable column data: {e}")
    return problems


_BLOOM_BITS = 2048
_BLOOM_K = 3


def _bloom_hashes(vals: np.ndarray) -> list[np.ndarray]:
    """k bit positions per value via two mixed 64-bit hashes (Kirsch-
    Mitzenmacher double hashing)."""
    x = vals.astype(np.uint64, copy=True)
    x ^= x >> np.uint64(33)
    x *= np.uint64(0xFF51AFD7ED558CCD)
    x ^= x >> np.uint64(33)
    h2 = x * np.uint64(0xC4CEB9FE1A85EC53) ^ (x >> np.uint64(29))
    m = np.uint64(_BLOOM_BITS)
    return [((x + np.uint64(i) * h2) % m).astype(np.int64)
            for i in range(_BLOOM_K)]


def _bloom_build(arr: np.ndarray) -> str:
    import base64

    bits = np.zeros(_BLOOM_BITS, dtype=bool)
    for pos in _bloom_hashes(arr):
        bits[pos] = True
    return base64.b64encode(np.packbits(bits).tobytes()).decode()


def bloom_may_contain(enc: dict, value) -> bool:
    """False means the partition provably lacks ``value`` in this column."""
    b64 = enc.get("bloom")
    if b64 is None:
        return True
    import base64

    bits = np.unpackbits(
        np.frombuffer(base64.b64decode(b64), dtype=np.uint8))
    for pos in _bloom_hashes(np.asarray([value], dtype=np.int64)):
        if not bits[int(pos[0])]:
            return False
    return True


def prune_by_stats(footer: dict, column: str, lo=None, hi=None) -> bool:
    """True if the partition MAY contain rows with column in [lo, hi] —
    False means provably disjoint and the file can be skipped (the
    min/max sparse-filter move of PAX micro_partition_stats.cc)."""
    enc = next((c for c in footer["columns"] if c["name"] == column), None)
    if enc is None or "min" not in enc:
        return True
    if lo is not None and enc["max"] < lo:
        return False
    if hi is not None and enc["min"] > hi:
        return False
    return True


def _field_json(f: Field) -> dict:
    return {"name": f.name, "base": f.type.base.value, "scale": f.type.scale}


def _field_from_json(j: dict) -> Field:
    return Field(j["name"], SqlType(DType(j["base"]), j.get("scale", 0)))


def _json_num(v):
    v = v.item() if hasattr(v, "item") else v
    return v
