"""Catalog — tables, schemas, distribution policies.

The MPP catalog analog: the reference records how every table is spread over
segments in ``gp_distribution_policy`` (hash keys / randomly / replicated)
and the cluster layout in ``gp_segment_configuration`` (SURVEY.md §2.1
"Catalog extensions"). Here a ``DistributionPolicy`` hangs off each table and
drives the planner's locus assignment; placement uses the same
jump-consistent-hash discipline as cdbhash.c:55 so elastic resize moves
minimal data.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Literal, Optional

import numpy as np

import itertools

from cloudberry_tpu.columnar.dictionary import StringDictionary
from cloudberry_tpu.types import Schema
from cloudberry_tpu.utils import hashing


@dataclass(frozen=True)
class DistributionPolicy:
    kind: Literal["hashed", "random", "replicated"]
    keys: tuple[str, ...] = ()

    @staticmethod
    def hashed(*keys: str) -> "DistributionPolicy":
        return DistributionPolicy("hashed", tuple(keys))

    @staticmethod
    def replicated() -> "DistributionPolicy":
        return DistributionPolicy("replicated")

    @staticmethod
    def random() -> "DistributionPolicy":
        return DistributionPolicy("random")


@dataclass
class TableStats:
    row_count: int = 0
    # per-column (min, max) over numeric/date columns — scan pruning + costing
    min_max: dict[str, tuple[float, float]] = field(default_factory=dict)
    # lazily-computed per-column uniqueness (PK detection for join planning)
    unique: dict[str, bool] = field(default_factory=dict)
    # number of distinct values per column (the pg_statistic n_distinct
    # analog) — computed lazily or by ANALYZE; drives join/group costing
    ndv: dict[str, int] = field(default_factory=dict)
    # equi-depth histogram bounds per numeric column (the pg_statistic
    # histogram_bounds analog): N+1 ascending values splitting the valid
    # rows into N equal-count buckets — range selectivity interpolates
    # within the containing bucket instead of assuming a uniform [min,max]
    hist: dict[str, list] = field(default_factory=dict)
    # row count at the last ANALYZE (-1 = never) — the autostats trigger
    # compares against it (gp_autostats_mode, autostats.c:283)
    analyzed_rows: int = -1


@dataclass
class Table:
    name: str
    schema: Schema
    policy: DistributionPolicy
    data: dict[str, np.ndarray] = field(default_factory=dict)   # host columns
    dicts: dict[str, StringDictionary] = field(default_factory=dict)
    stats: TableStats = field(default_factory=TableStats)
    # per-column validity (True = value present); absent column = no NULLs.
    # Invariant: data values are canonicalized to 0 at invalid lanes, so
    # hashing/placement/grouping see a stable representative.
    validity: dict[str, np.ndarray] = field(default_factory=dict)
    # durable storage binding (storage/table_store.py). cold=True means the
    # data lives ONLY in micro-partition files: scans read pruned partitions
    # per query; ensure_loaded() materializes for paths that need RAM arrays
    backing: object = None
    cold: bool = False
    # PARTITION BY spec (gp_partition_template analog): stored writes route
    # rows into partition-pure micro-partition files so manifest min/max
    # stats become exact partition bounds — elimination needs no separate
    # partition catalog. ('range', col, start, end, every) | ('list', col)
    partition_spec: tuple | None = None
    # readable external table source (access/external analog): {url,
    # delimiter, header, reject_limit, reject_percent, log_errors}; data
    # re-reads from the source at every statement (never stored)
    external: dict | None = None

    @property
    def num_rows(self) -> int:
        return self.stats.row_count

    def ensure_loaded(self) -> None:
        """Materialize a cold stored table into RAM (DML paths and
        distributed placement need whole arrays)."""
        if not self.cold or self.backing is None:
            return
        cols, _, dicts = self.backing.scan(self.name)
        validity = {k[4:]: v for k, v in cols.items()
                    if k.startswith("$nn:")}
        data = {k: v for k, v in cols.items() if not k.startswith("$nn:")}
        self._loading = True
        try:
            self.set_data(data, dicts, validity=validity)
        finally:
            self._loading = False
        self.cold = False

    def set_data(self, data: dict[str, np.ndarray],
                 dicts: dict[str, StringDictionary] | None = None,
                 validity: dict[str, np.ndarray] | None = None,
                 appended: int | None = None) -> None:
        # a refused persist (disk quota, full disk) must not leave RAM
        # ahead of the store — capture enough to restore on failure
        import copy as _copy

        _prev = (self.data, self.dicts, self.validity,
                 _copy.deepcopy(self.stats), getattr(self, "_version", 0),
                 self.cold)
        try:
            self._set_data_inner(data, dicts, validity, appended)
        except Exception:
            (self.data, self.dicts, self.validity, self.stats,
             self._version, self.cold) = _prev
            raise

    def _set_data_inner(self, data: dict[str, np.ndarray],
                        dicts: dict[str, StringDictionary] | None = None,
                        validity: dict[str, np.ndarray] | None = None,
                        appended: int | None = None) -> None:
        self.data = data
        self.dicts = dicts or {}
        n = len(next(iter(data.values()))) if data else 0
        self.stats.row_count = n
        self.stats.unique = {}
        self.stats.ndv = {}
        self.validity = {}
        no_change = appended == 0  # zero-row append: skip persistence
        if no_change:
            appended = None
        for c, v in (validity or {}).items():
            v = np.asarray(v, dtype=np.bool_)
            if c in data and not v.all():
                self.validity[c] = v
                # canonical zero at NULL lanes (placement/grouping stability)
                data[c] = np.where(v, data[c],
                                   np.zeros((), dtype=data[c].dtype))
        # globally-unique version: a DROP+CREATE+INSERT sequence must never
        # reproduce an old version (statement caches key on it)
        self._version = next(_VERSION_COUNTER)
        for f in self.schema.fields:
            arr = data.get(f.name)
            if arr is not None and arr.dtype.kind in "if" and n:
                vm = self.validity.get(f.name)
                vals = arr[vm] if vm is not None else arr
                if len(vals):
                    self.stats.min_max[f.name] = (float(vals.min()),
                                                  float(vals.max()))
        # durable tables: every data change is a new atomic snapshot; an
        # append-only change persists just the new tail partitions. Inside
        # a transaction, writes defer to COMMIT (store.begin_txn).
        if self.backing is not None and not getattr(self, "_loading", False) \
                and not no_change:
            if not getattr(self.backing, "autocommit", True):
                self.backing._txn_dirty[self.name] = self
                # append-vs-rewrite note feeds the commit-time OCC merge
                # decision (concurrent INSERTs both succeed)
                self.backing.note_txn_write(self.name, appended)
                self.cold = False
                return
            if appended is not None and appended < n:
                k = appended
                # refresh persisted uniqueness incrementally: a previously
                # unique column stays unique iff the appended tail has no
                # internal dups and no overlap with the head (O(N) isin,
                # not a full O(N log N) re-sort per statement)
                prev = self.backing.read_manifest(self.name) \
                    .get("unique", {})
                unique = dict(prev)
                for c, flag in prev.items():
                    arr = data.get(c)
                    if arr is None or not flag:
                        continue
                    tail, head = arr[n - k:], arr[:n - k]
                    unique[c] = bool(
                        len(np.unique(tail)) == len(tail)
                        and not np.isin(tail, head).any())
                self._store_version = self.backing.append(
                    self.name, {c: v[-k:] for c, v in data.items()},
                    self.schema, self.dicts,
                    validity={c: v[-k:] for c, v in self.validity.items()},
                    unique=unique,
                    policy=self.policy,
                    rows_per_partition=self.backing.rows_per_partition)
            else:
                self._store_version = self.backing.save_table(
                    self, getattr(self.backing, "rows_per_partition",
                                  1 << 20))
            self.cold = False

    def ndv(self, col: str) -> Optional[int]:
        """Distinct-value count for costing (exact; computed lazily and
        cached — the auto-ANALYZE stance, autostats.c:283). Cold tables
        only report manifest-persisted values (ANALYZE writes them)."""
        cached = self.stats.ndv.get(col)
        if cached is not None:
            return cached
        if self.cold:
            return None
        arr = self.data.get(col)
        if arr is None or arr.dtype.kind not in "iufb" \
            or self.stats.row_count == 0:
            return None
        n = int(len(np.unique(arr)))
        self.stats.ndv[col] = n
        return n

    HIST_BUCKETS = 64

    def analyze(self) -> dict[str, int]:
        """Collect NDV and equi-depth histograms for every numeric column
        (the distributed-ANALYZE analog, analyze.c:31 — strings count
        distinct dictionary codes; histogram role: pg_statistic
        histogram_bounds) and persist into the manifest if durable."""
        self.ensure_loaded()
        for f in self.schema.fields:
            arr = self.data.get(f.name)
            if arr is None or arr.dtype.kind not in "iufb" \
                    or not self.stats.row_count:
                continue
            self.stats.ndv[f.name] = int(len(np.unique(arr)))
            if arr.dtype.kind in "iuf":
                # valid rows only: canonical-zero NULL fills would put a
                # false spike at 0
                vm = self.validity.get(f.name)
                vals = arr[vm] if vm is not None and len(vm) == len(arr) \
                    else arr
                if len(vals):
                    qs = np.linspace(0.0, 1.0, self.HIST_BUCKETS + 1)
                    self.stats.hist[f.name] = [
                        float(v) for v in np.quantile(vals, qs)]
        self.stats.analyzed_rows = int(self.stats.row_count)
        # fresh stats change plan choices (selectivity, memo motion
        # costing): bump the STATS version so cached compiled statements
        # re-plan — deliberately not _version, which OCC snapshots watch
        # (an ANALYZE must never abort a concurrent writer)
        self._stats_version = next(_VERSION_COUNTER)
        if self.backing is not None:
            if getattr(self.backing, "autocommit", True):
                self._store_version = \
                    self.backing.save_stats(self.name, self.stats.ndv,
                                            self.stats.hist,
                                            self.stats.analyzed_rows)
            else:
                # inside a transaction: a stats-only marker — COMMIT writes
                # one manifest (save_stats), never a full data re-snapshot,
                # and ROLLBACK discards it
                self.backing._txn_stats[self.name] = self
        return dict(self.stats.ndv)

    def is_unique(self, col: str) -> bool:
        """Whether a column's values are distinct (PK detection; the planner
        uses this the way nodeHash.c trusts unique-ified hash sides). Lazy +
        cached; recomputed when data changes (set_data clears the cache)."""
        if self.cold:
            # data not in RAM: only manifest-recorded uniqueness counts
            return bool(self.stats.unique.get(col, False))
        cached = self.stats.unique.get(col)
        if cached is None:
            arr = self.data.get(col)
            if arr is None or arr.dtype.kind not in "iuf" \
                    or col in self.validity:
                cached = False  # nullable columns never count as PKs
            else:
                cached = bool(len(np.unique(arr)) == len(arr))
            self.stats.unique[col] = cached
        return cached

    def is_unique_cols(self, cols: tuple[str, ...]) -> bool:
        """Exact multi-column uniqueness (composite PK detection, e.g.
        partsupp's (ps_partkey, ps_suppkey)) — lexsort + adjacent compare."""
        if self.cold:
            # conservative without RAM data (single-column manifests only)
            return any(bool(self.stats.unique.get(c, False)) for c in cols)
        key = "|".join(sorted(cols))
        cached = self.stats.unique.get(key)
        if cached is None:
            arrs = [self.data.get(c) for c in cols]
            if any(a is None or a.dtype.kind not in "iuf" for a in arrs) \
                    or any(c in self.validity for c in cols):
                cached = False
            elif self.stats.row_count == 0:
                cached = True
            else:
                order = np.lexsort(tuple(arrs))
                eq = np.ones(len(order) - 1, dtype=bool)
                for a in arrs:
                    s_ = a[order]
                    eq &= s_[1:] == s_[:-1]
                cached = not bool(eq.any())
            self.stats.unique[key] = cached
        return cached

    def to_pandas(self):
        """Decode the (already physically-encoded) table data to pandas;
        NULL lanes render as None."""
        import pandas as pd

        from cloudberry_tpu.columnar.batch import decode_column

        out = {}
        for f in self.schema.fields:
            col = decode_column(np.asarray(self.data[f.name]), f, self.dicts)
            vm = self.validity.get(f.name)
            if vm is not None:
                col = np.asarray(col, dtype=object)
                col[~vm] = None
            out[f.name] = col
        return pd.DataFrame(out)

    def shard_assignment(self, n_segments: int) -> Optional[np.ndarray]:
        """Segment id per row (None for replicated tables).

        Hash-distributed: jump_consistent_hash over the distribution keys —
        minimal movement on resize (gpexpand analog). Random ('Strewn' locus):
        round-robin.
        """
        if self.policy.kind == "replicated":
            return None
        n = self.stats.row_count
        if self.policy.kind == "random":
            return (np.arange(n) % n_segments).astype(np.int32)
        # staged successor-epoch assignment (parallel/topology.py): the
        # background rebalancer pre-hashes the table at the pending
        # epoch's segment count so cutover's first shard layout skips
        # the full re-hash; version+nseg key it, so a stale stage can
        # never serve
        staged = getattr(self, "_topo_assign", None)
        if staged is not None and staged[1] == n_segments \
                and staged[0] == getattr(self, "_version", 0) \
                and len(staged[2]) == n:
            return staged[2]
        cols = [self.data[k] for k in self.policy.keys]
        h = hashing.hash_columns_np([np.asarray(c) for c in cols])
        return hashing.jump_consistent_hash_np(h, n_segments)


_VERSION_COUNTER = itertools.count(1)


class Catalog:
    def __init__(self):
        self.tables: dict[str, Table] = {}
        # durable store (storage/table_store.py) when the session is
        # storage-backed; new tables bind to it at CREATE
        self.store = None
        # name -> unbound query AST (views re-bind per statement, so they
        # track base-table changes like the reference's rewriter)
        self.views: dict[str, object] = {}
        # bumped on any DDL that can change name resolution (view create/
        # drop, table create/drop) — statement caches key on it
        self.ddl_version: int = 0
        # sequences (gp_fastsequence / '?'-message analog): storeless
        # sessions keep state here; store-backed sessions delegate every
        # allocation to the store's locked _SEQUENCES.json so all sessions
        # draw from one coordinator-owned number line. nextval never rolls
        # back (PostgreSQL semantics) — deliberately outside txn snapshots.
        self.sequences: dict[str, dict] = {}
        # materialized views: name -> plan/matview.MatViewDef (the data
        # lives in an ordinary table of the same name)
        self.matviews: dict[str, object] = {}
        # resource queues (resqueue.c analog); "default" always exists and
        # is unlimited — sessions pick one via config.resource.queue
        from cloudberry_tpu.exec.resource import ResourceQueue

        self.resource_queues: dict[str, ResourceQueue] = {
            "default": ResourceQueue("default")}
        self._seq_currval: dict[str, int] = {}  # session-local currval
        # storeless allocation is read-modify-write on shared session
        # state — server handler threads share one Session, so it needs
        # its own lock (the store path is covered by the store file lock)
        self._seq_lock = __import__("threading").Lock()

    def bump_ddl(self) -> None:
        self.ddl_version += 1

    # ------------------------------------------------------------ sequences

    def create_sequence(self, name: str, start: int = 1, increment: int = 1,
                        if_not_exists: bool = False) -> None:
        name = name.lower()
        if increment == 0:
            raise ValueError("INCREMENT must not be zero")
        if self.store is not None:
            self.store.create_sequence(name, start, increment, if_not_exists)
            return
        with self._seq_lock:
            if name in self.sequences:
                if if_not_exists:
                    return
                raise ValueError(f"sequence {name!r} already exists")
            self.sequences[name] = {"next": int(start),
                                    "inc": int(increment)}

    def drop_sequence(self, name: str, if_exists: bool = False) -> None:
        name = name.lower()
        if self.store is not None:
            self.store.drop_sequence(name, if_exists)
            self._seq_currval.pop(name, None)
            return
        with self._seq_lock:
            if name not in self.sequences:
                if if_exists:
                    return
                raise KeyError(f"unknown sequence {name!r}")
            del self.sequences[name]
        self._seq_currval.pop(name, None)

    def seq_nextval(self, name: str) -> int:
        """Allocate the next value — the segments-fetch-from-the-QD
        protocol (postgres.c '?' message, cdb_sequence_nextval_qe): the
        coordinator owns the number line; here that is the locked store
        file (durable) or this catalog under its own lock."""
        name = name.lower()
        if self.store is not None:
            base = self.store.sequence_alloc(name)
        else:
            with self._seq_lock:
                s = self.sequences.get(name)
                if s is None:
                    raise KeyError(f"unknown sequence {name!r}")
                base = s["next"]
                s["next"] = base + s["inc"]
        self._seq_currval[name] = base
        return base

    def seq_currval(self, name: str) -> int:
        name = name.lower()
        v = self._seq_currval.get(name)
        if v is None:
            raise ValueError(
                f"currval of sequence {name!r} is not yet defined in "
                "this session")
        return v

    def seq_setval(self, name: str, value: int) -> int:
        name = name.lower()
        if self.store is not None:
            self.store.sequence_setval(name, value)
        else:
            with self._seq_lock:
                s = self.sequences.get(name)
                if s is None:
                    raise KeyError(f"unknown sequence {name!r}")
                s["next"] = int(value) + s["inc"]
        self._seq_currval[name] = int(value)
        return int(value)

    def adopt(self, t: "Table") -> "Table":
        """Register an externally-constructed table (store registration)
        without the CREATE-time persistence side effects."""
        t._version = next(_VERSION_COUNTER)
        self.tables[t.name] = t
        self.bump_ddl()
        return t

    def create_table(self, name: str, schema: Schema,
                     policy: DistributionPolicy | None = None,
                     if_not_exists: bool = False,
                     partition_spec: tuple | None = None,
                     durable: bool = True, bump: bool = True) -> Table:
        name = name.lower()
        if name in self.tables:
            if if_not_exists:
                return self.tables[name]
            raise ValueError(f"table {name!r} already exists")
        t = Table(name, schema, policy or DistributionPolicy.random())
        if partition_spec is not None:
            if partition_spec[1] not in schema.names:
                raise ValueError(
                    f"partition column {partition_spec[1]!r} is not a "
                    "column of the table")
            t.partition_spec = partition_spec
        # empty columns from the start so scans of unpopulated tables work
        t.data = {f.name: np.zeros(0, dtype=f.type.np_dtype)
                  for f in schema.fields}
        t._version = next(_VERSION_COUNTER)
        if self.store is not None and durable:
            t.backing = self.store
            if self.store.autocommit:
                # durable schema from CREATE on
                t._store_version = self.store.save_table(t)
            else:
                self.store._txn_dirty[name] = t
        self.tables[name] = t
        if bump:
            # bump=False: transient tables (table functions) are invisible
            # to SQL names, so creating one must not evict every cached
            # compiled statement via the ddl version
            self.bump_ddl()
        return t

    def drop_table(self, name: str, if_exists: bool = False) -> None:
        name = name.lower()
        if name not in self.tables and if_exists:
            return
        t = self.tables[name]
        if t.backing is not None:
            if t.backing.autocommit:
                t.backing.drop_table(name)
            else:
                t.backing._txn_drops.append(name)
                t.backing._txn_dirty.pop(name, None)
                getattr(t.backing, "_txn_stats", {}).pop(name, None)
        del self.tables[name]
        self.bump_ddl()

    def table(self, name: str) -> Table:
        t = self.tables.get(name.lower())
        if t is None:
            raise KeyError(f"unknown table {name!r}")
        return t
