from cloudberry_tpu.catalog.catalog import Catalog, Table, DistributionPolicy

__all__ = ["Catalog", "Table", "DistributionPolicy"]
