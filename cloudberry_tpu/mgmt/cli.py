"""Management CLI — the gpMgmt plane analog (`python -m cloudberry_tpu`).

Reference tools → subcommands (SURVEY §2.7):
- gpinitsystem → ``init``      (create a cluster: store root + topology)
- gpstate      → ``state``     (topology, devices, health, tables)
- FTS probe    → ``probe``     (one health probe round)
- gpexpand /
  gpshrink     → ``expand``    (resize topology; reports the moved-row
                                fraction, which jump_consistent_hash keeps
                                ≈ delta/N — the gpexpand minimal-movement
                                promise, cdbhash.c:55)
- gpcheckcat   → ``check``     (storage/catalog consistency scan)
- psql -c      → ``sql``       (run a statement against the cluster store)

The "cluster" is a store directory plus ``cluster.json`` (the
gp_segment_configuration analog). Segments are mesh slots, so start/stop are
process-lifecycle no-ops; recovery is re-execution (see parallel/health.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _cluster_path(store: str) -> str:
    return os.path.join(store, "cluster.json")


def load_cluster(store: str) -> dict:
    try:
        with open(_cluster_path(store)) as f:
            return json.load(f)
    except FileNotFoundError:
        raise SystemExit(
            f"error: no cluster at {store!r} — run "
            f"`python -m cloudberry_tpu --store {store} init` first")


def _enc_key() -> str | None:
    """TDE cluster key for CLI entry points: --encryption-key or the
    CBTPU_ENCRYPTION_KEY environment (the keyring-unlock analog)."""
    return _ENC_KEY or os.environ.get("CBTPU_ENCRYPTION_KEY") or None


_ENC_KEY: str | None = None


def _store(root: str):
    """A TableStore honoring the TDE key (every direct CLI store open)."""
    from cloudberry_tpu.storage.table_store import TableStore
    from cloudberry_tpu.utils.tde import make_cipher

    ts = TableStore(root)
    ts.cipher = make_cipher(_enc_key())
    return ts


def cluster_config(store: str):
    """The one Config a cluster store implies — every entry point (serve,
    mcp, sql) must build it identically or drift apart."""
    from cloudberry_tpu.config import Config

    cfg = load_cluster(store)
    over = {"storage.root": store}
    if _enc_key():
        over["storage.encryption_key"] = _enc_key()
    return Config(n_segments=cfg["n_segments"]).with_overrides(**over)


def _open_session(store: str):
    import cloudberry_tpu as cb
    from cloudberry_tpu.config import Config

    cfg = load_cluster(store)
    s = cb.Session(Config(n_segments=cfg["n_segments"]))
    ts = _store(store)
    for name in sorted(os.listdir(store)):
        if os.path.isdir(os.path.join(store, name, "_manifests")):
            ts.load_table(s.catalog, name)
    return s, ts


def cmd_init(args) -> int:
    os.makedirs(args.store, exist_ok=True)
    if os.path.exists(_cluster_path(args.store)) and not args.force:
        print(f"error: cluster already initialized at {args.store}",
              file=sys.stderr)
        return 1
    cfg = {"n_segments": args.segments, "created": time.time(),
           "format": 1}
    with open(_cluster_path(args.store), "w") as f:
        json.dump(cfg, f)
    print(f"initialized cluster: {args.segments} segments at {args.store}")
    return 0


def cmd_state(args) -> int:
    import jax

    from cloudberry_tpu.parallel import health

    cfg = load_cluster(args.store)
    devices = jax.devices()
    r = health.probe()
    print(f"cluster store:   {args.store}")
    print(f"segments:        {cfg['n_segments']}")
    print(f"devices visible: {len(devices)} ({devices[0].platform})")
    print(f"health probe:    {'OK' if r.ok else 'FAILED: ' + str(r.error)}"
          f" ({r.latency_s * 1000:.1f} ms)")
    ts = _store(args.store)  # manifests only: no data decode for status
    for name in sorted(os.listdir(args.store)):
        mdir = os.path.join(args.store, name, "_manifests")
        if os.path.isdir(mdir):
            man = ts.read_manifest(name)
            rows = sum(p["num_rows"] - len(p["deleted"])
                       for p in man["partitions"])
            print(f"table {name}: v{man['version']}, "
                  f"{len(man['partitions'])} partitions, {rows} rows")
    for sname in ts.sequence_names():
        s = ts._read_sequences()[sname]
        print(f"sequence {sname}: next {s['next']} (increment {s['inc']})")
    return 0


def cmd_probe(args) -> int:
    from cloudberry_tpu.parallel import health

    r = health.probe()
    print(json.dumps({"ok": r.ok, "devices": r.n_devices,
                      "latency_ms": round(r.latency_s * 1000, 2),
                      "error": r.error}))
    return 0 if r.ok else 1


def cmd_expand(args) -> int:
    import numpy as np

    from cloudberry_tpu.utils import hashing

    cfg = load_cluster(args.store)
    old_n, new_n = cfg["n_segments"], args.segments
    if getattr(args, "online", False):
        return _expand_online(args, cfg, old_n, new_n)
    s, ts = _open_session(args.store)
    moved_frac = []
    for name, t in s.catalog.tables.items():
        if t.policy.kind != "hashed" or t.num_rows == 0:
            continue
        cols = [np.asarray(t.data[k]) for k in t.policy.keys]
        h = hashing.hash_columns_np(cols)
        a = hashing.jump_consistent_hash_np(h, old_n)
        b = hashing.jump_consistent_hash_np(h, new_n)
        moved_frac.append((name, float((a != b).mean())))
    cfg["n_segments"] = new_n
    with open(_cluster_path(args.store), "w") as f:
        json.dump(cfg, f)
    verb = "expanded" if new_n > old_n else "shrunk"
    print(f"{verb} cluster {old_n} → {new_n} segments")
    for name, frac in moved_frac:
        print(f"  {name}: {frac * 100:.1f}% of rows move "
              f"(jump-hash minimal movement)")
    return 0


def _expand_online(args, cfg: dict, old_n: int, new_n: int) -> int:
    """The gpexpand-made-online path (parallel/topology.py): create a
    successor epoch, move the jump-hash delta rows partition-by-
    partition (OCC-committed chunks, journal-resumable, throttled), cut
    over, and report the measured moved-row fraction against the
    delta/N minimal-movement bound. A server process on the same store
    adopts the new epoch at its next statement — no downtime. The
    offline path (no --online) keeps working and lands on the identical
    derived placement (pinned equivalent by test)."""
    import cloudberry_tpu as cb

    if new_n == old_n:
        print(f"cluster already at {new_n} segments")
        return 0
    s = cb.Session(cluster_config(args.store))
    topo = s._topology
    state = topo.begin(new_n)

    def report(st):
        frac = st.moved_rows / max(st.total_rows, 1)
        print(f"  rebalance: {st.tables_done}/{st.tables_total} tables, "
              f"{st.moved_rows} rows moved ({frac * 100:.1f}%)",
              flush=True)

    topo.rebalance(chunk_rows=args.chunk_rows or None,
                   throttle_s=args.throttle_s, progress=report)
    out = topo.cutover()
    cfg["n_segments"] = new_n
    with open(_cluster_path(args.store), "w") as f:
        json.dump(cfg, f)
    verb = "expanded" if new_n > old_n else "shrunk"
    reb = out["rebalance"]
    frac = reb["moved_rows"] / max(reb["total_rows"], 1)
    bound = reb["minimal_bound"]
    print(f"{verb} cluster {old_n} → {new_n} segments ONLINE "
          f"(epoch {out['epoch']}, cutover {out['cutover_ms']:.1f} ms)")
    if reb["total_rows"] and bound:
        print(f"  moved {reb['moved_rows']} of {reb['total_rows']} rows "
              f"({frac * 100:.1f}%) vs delta/N minimal-movement bound "
              f"{bound * 100:.1f}% ({frac / bound:.2f}x)")
    else:
        print("  no hashed rows to move")
    return 0


def cmd_check(args) -> int:
    """Storage consistency scan (gpcheckcat analog): every partition file
    must parse, row counts and dictionary code ranges must agree."""
    from cloudberry_tpu.storage import micropartition as mp

    ts = _store(args.store)
    problems = 0
    for name in sorted(os.listdir(args.store)):
        mdir = os.path.join(args.store, name, "_manifests")
        if not os.path.isdir(mdir):
            continue
        man = ts.read_manifest(name)
        for part in man["partitions"]:
            path = os.path.join(args.store, name, part["file"])
            try:
                footer = mp.read_footer(path, cipher=ts.cipher)
                if footer["num_rows"] != part["num_rows"]:
                    print(f"MISMATCH {name}/{part['file']}: manifest rows "
                          f"{part['num_rows']} != footer {footer['num_rows']}")
                    problems += 1
                cols = mp.read_columns(path, cipher=ts.cipher, verify=True)
                for cname, values in man["dicts"].items():
                    if cname in cols and len(cols[cname]) \
                            and cols[cname].max() >= len(values):
                        print(f"BAD DICT {name}/{part['file']}: column "
                              f"{cname} code {cols[cname].max()} out of "
                              f"range {len(values)}")
                        problems += 1
            except Exception as e:  # noqa: BLE001
                print(f"CORRUPT {name}/{part['file']}: {e}")
                problems += 1
    print(f"check complete: {problems} problem(s)")
    return 0 if problems == 0 else 1


def cmd_fsck(args) -> int:
    """Store integrity scan + orphan GC (pg_checksums / fsck analog):
    manifest closure, store-JSON parse, optional deep checksum sweep,
    and collection of crash residue (orphan partitions, stale tmp
    files) past the grace window."""
    from cloudberry_tpu.storage.fsck import fsck
    from cloudberry_tpu.utils.tde import make_cipher

    report = fsck(args.store, cipher=make_cipher(_enc_key()),
                  deep=args.deep, grace_s=args.grace_s, gc=args.gc)
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        for name, t in sorted(report["tables"].items()):
            print(f"table {name}: v{t['version']}, {t['partitions']} "
                  f"partitions, {t['rows']} live rows"
                  + (f", {t['checked']} deep-checked" if args.deep else ""))
        for p in report["problems"]:
            print(f"PROBLEM {p}")
        for o in report["orphans"]:
            print(f"orphan {o['path']} (age {o['age_s']}s"
                  f"{', collectable' if o['collectable'] else ''})")
        for name in report["census_skipped"]:
            print(f"census skipped for {name}: manifest chain unreadable "
                  "or problems found — orphan GC disabled for this table")
        for c in report["collected"]:
            print(f"collected {c}")
        print(f"fsck {'clean' if report['clean'] else 'NOT CLEAN'}: "
              f"{len(report['problems'])} problem(s), "
              f"{len(report['orphans'])} orphan(s), "
              f"{len(report['collected'])} collected")
    return 0 if report["clean"] else 1


def cmd_serve(args) -> int:
    """Run the socket serving layer (the postmaster/tcop analog): one
    process owns the session; clients connect over TCP."""
    from cloudberry_tpu.serve import Server
    from cloudberry_tpu.utils import faultinject

    # crash-torture arming: the harness launches this very entry point
    # with CBTPU_INJECT set, so the faults land inside the REAL server
    # process it is about to kill (never armed in normal operation)
    n_armed = faultinject.arm_from_env()
    cfg = cluster_config(args.store)
    for kv in getattr(args, "set", None) or []:
        key, _, val = kv.partition("=")
        try:
            val = json.loads(val)
        except ValueError:
            pass  # bare strings stay strings
        cfg = cfg.with_overrides(**{key: val})
    srv = Server(config=cfg,
                 host=args.host, port=args.port,
                 read_only=getattr(args, "standby", False),
                 auth_token=getattr(args, "auth_token", None))
    if n_armed:
        print(f"fault injection armed: {n_armed} seam(s) from "
              "CBTPU_INJECT", flush=True)
    role = "standby (read-only)" if srv.read_only else "primary"
    print(f"serving on {srv.host}:{srv.port} (store {args.store}, "
          f"{srv.session.config.n_segments} segments, {role})", flush=True)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        # smart shutdown: finish accepted work, refuse new requests with
        # the retryable drain error, then close (Ctrl-C twice to force)
        srv.stop(drain_s=10.0)
    return 0


def cmd_sql(args) -> int:
    if args.connect:
        from cloudberry_tpu.serve import Client

        host, _, port = args.connect.rpartition(":")
        with Client(host or "127.0.0.1", int(port)) as c:
            out = c.sql(args.query)
        if "rows" in out:
            print("\t".join(out["columns"]))
            for row in out["rows"]:
                print("\t".join(str(v) for v in row))
        else:
            print(out.get("status", ""))
        return 0
    s, ts = _open_session(args.store)
    versions = {n: getattr(t, "_version", 0)
                for n, t in s.catalog.tables.items()}
    out = s.sql(args.query)
    if hasattr(out, "to_pandas"):
        print(out.to_pandas().to_string(index=False))
    else:
        print(out)  # DDL/DML status tag
        if args.save:
            # persist only tables the statement actually changed
            for n, t in s.catalog.tables.items():
                if getattr(t, "_version", 0) != versions.get(n):
                    ts.save_table(t)
            # dropped tables: remove their store directories too
            import shutil

            for n in set(versions) - set(s.catalog.tables):
                tdir = os.path.join(args.store, n)
                if os.path.isdir(os.path.join(tdir, "_manifests")):
                    shutil.rmtree(tdir)
    return 0


def cmd_fdist(args) -> int:
    from cloudberry_tpu.serve.fdist import main as fdist_main

    fdist_main(args.root, args.port, args.host)
    return 0


def cmd_mcp(args) -> int:
    """Run the MCP stdio server (the mcp-server analog): AI agents speak
    JSON-RPC on stdin/stdout; the engine is this process's cluster store,
    or a running socket server via --connect."""
    from cloudberry_tpu.serve.mcp import (McpServer, SessionEngine,
                                          WireEngine)

    if args.connect:
        host, _, port = args.connect.rpartition(":")
        engine = WireEngine(host or "127.0.0.1", int(port))
    else:
        import cloudberry_tpu as cb

        engine = SessionEngine(cb.Session(cluster_config(args.store)))
    McpServer(engine).serve_stdio()
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="cloudberry_tpu",
        description="TPU-native MPP SQL cluster management")
    p.add_argument("--store", default=os.environ.get("CBTPU_STORE", "./cbtpu"),
                   help="cluster store directory")
    p.add_argument("--encryption-key", default=None,
                   help="TDE cluster key (or CBTPU_ENCRYPTION_KEY env) — "
                        "required to open an encrypted store")
    sub = p.add_subparsers(dest="cmd", required=True)

    pi = sub.add_parser("init", help="create a cluster (gpinitsystem)")
    pi.add_argument("--segments", type=int, default=1)
    pi.add_argument("--force", action="store_true")
    pi.set_defaults(fn=cmd_init)

    ps = sub.add_parser("state", help="cluster status (gpstate)")
    ps.set_defaults(fn=cmd_state)

    pp = sub.add_parser("probe", help="health probe (FTS)")
    pp.set_defaults(fn=cmd_probe)

    pe = sub.add_parser("expand", help="resize segments (gpexpand/gpshrink)")
    pe.add_argument("--segments", type=int, required=True)
    pe.add_argument("--online", action="store_true",
                    help="epoch-versioned online resize: background "
                         "minimal-delta rebalance + atomic cutover; a "
                         "serving cluster adopts without downtime "
                         "(resumable if interrupted)")
    pe.add_argument("--chunk-rows", type=int, default=0,
                    help="rows per rebalance chunk (0 = config default)")
    pe.add_argument("--throttle-s", type=float, default=None,
                    help="sleep between rebalance chunks (background "
                         "politeness on a serving cluster; default: "
                         "config.topology.throttle_s)")
    pe.set_defaults(fn=cmd_expand)

    pc = sub.add_parser("check", help="storage consistency (gpcheckcat)")
    pc.set_defaults(fn=cmd_check)

    pk = sub.add_parser("fsck", help="store integrity + orphan GC "
                                     "(pg_checksums analog)")
    pk.add_argument("--deep", action="store_true",
                    help="re-read every column blob and verify its "
                         "footer content checksum")
    pk.add_argument("--gc", action="store_true",
                    help="collect orphans past the grace window")
    pk.add_argument("--grace-s", type=float, default=300.0,
                    help="age before crash residue becomes collectable "
                         "(protects in-flight commits; default 300)")
    pk.add_argument("--json", action="store_true",
                    help="print the full report as JSON")
    pk.set_defaults(fn=cmd_fsck)

    pq = sub.add_parser("sql", help="run a statement")
    pq.add_argument("query")
    pq.add_argument("--save", action="store_true",
                    help="persist modified tables back to the store")
    pq.add_argument("--connect", default=None, metavar="HOST:PORT",
                    help="send to a running server instead of in-process")
    pq.set_defaults(fn=cmd_sql)

    pv = sub.add_parser("serve", help="run the socket server (tcop analog)")
    pv.add_argument("--host", default="127.0.0.1")
    pv.add_argument("--port", type=int, default=15432)
    pv.add_argument("--standby", action="store_true",
                    help="hot standby: serve reads over the shared store, "
                         "refuse writes")
    pv.add_argument("--auth-token", default=None,
                    help="require {\"auth\": token} before requests "
                         "(failed logins lock the address out)")
    pv.add_argument("--set", action="append", metavar="KEY=VALUE",
                    help="config override (repeatable), e.g. "
                         "--set compact.enabled=true — values parse as "
                         "JSON, falling back to bare strings")
    pv.set_defaults(fn=cmd_serve)

    pf = sub.add_parser("fdist",
                        help="scatter file server (gpfdist analog)")
    pf.add_argument("--root", default=".")
    pf.add_argument("--port", type=int, default=8800)
    pf.add_argument("--host", default="0.0.0.0")
    pf.set_defaults(fn=cmd_fdist)

    pm = sub.add_parser("mcp", help="MCP stdio server (AI-agent surface)")
    pm.add_argument("--connect", default=None, metavar="HOST:PORT",
                    help="back onto a running server instead of in-process")
    pm.set_defaults(fn=cmd_mcp)

    args = p.parse_args(argv)
    if args.encryption_key:
        global _ENC_KEY
        _ENC_KEY = args.encryption_key
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
