"""Transparent data encryption — at-rest protection for the table store.

The reference encrypts cluster files with a keyring unlocked at startup
(TDE; key lifecycle outside the database). Analog: a cluster key string
(config storage.encryption_key — point it at a secret manager value, not
a literal in source) derives a Fernet key (AES-128-CBC + HMAC-SHA256,
from the `cryptography` package); every micro-partition file and
manifest encrypts whole — footers and manifests carry min/max stats and
string dictionaries, which are data. CURRENT pointers and lock files
stay plaintext (they hold only version numbers / pids).

A store written with a key refuses to open its files without one, and a
wrong key fails MAC verification — never silent garbage."""

from __future__ import annotations

import base64
import hashlib


class TdeError(RuntimeError):
    pass


class Cipher:
    """encrypt/decrypt bytes under a cluster key string."""

    def __init__(self, key: str):
        try:
            from cryptography.fernet import Fernet
        except ImportError as e:  # pragma: no cover — baked into image
            raise TdeError(f"TDE needs the 'cryptography' package: {e}")
        digest = hashlib.sha256(key.encode()).digest()
        self._f = Fernet(base64.urlsafe_b64encode(digest))

    def encrypt(self, data: bytes) -> bytes:
        return self._f.encrypt(data)

    def decrypt(self, data: bytes) -> bytes:
        from cryptography.fernet import InvalidToken

        try:
            return self._f.decrypt(data)
        except InvalidToken:
            raise TdeError("decryption failed: wrong encryption key "
                           "(storage.encryption_key) or corrupt file")


def make_cipher(key: str | None):
    return Cipher(key) if key else None
