"""Distribution hashing — the cdbhash analog.

The reference routes tuples to segments by hashing distribution-key columns
(``makeCdbHash`` src/backend/cdb/cdbhash.c:78) and maps hash → segment with
``jump_consistent_hash`` (cdbhash.c:55) so that elastic resize (gpexpand /
gpshrink) moves a minimal fraction of rows; a legacy modulo mapping exists in
cdblegacyhash.c. Both are provided here as vectorized jittable JAX functions
(device-side routing for HASH motion) and as numpy functions (host-side
placement at load time).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# splitmix64 finalizer constants — a well-mixed 64-bit avalanche.
_C1 = np.uint64(0xBF58476D1CE4E5B9)
_C2 = np.uint64(0x94D049BB133111EB)
_JUMP = np.uint64(2862933555777941757)


def splitmix64_jnp(x: jnp.ndarray) -> jnp.ndarray:
    """Vectorized 64-bit avalanche hash (device)."""
    z = x.astype(jnp.uint64)
    z = (z ^ (z >> 30)) * jnp.uint64(_C1)
    z = (z ^ (z >> 27)) * jnp.uint64(_C2)
    return z ^ (z >> 31)


def splitmix64_np(x: np.ndarray) -> np.ndarray:
    with np.errstate(over="ignore"):
        z = x.astype(np.uint64)
        z = (z ^ (z >> np.uint64(30))) * _C1
        z = (z ^ (z >> np.uint64(27))) * _C2
        return z ^ (z >> np.uint64(31))


def combine_hashes_jnp(hs: list[jnp.ndarray]) -> jnp.ndarray:
    """Order-sensitive multi-column hash combine (cdbhash accumulates columns
    into one 32-bit hash; we keep 64 bits)."""
    acc = jnp.zeros_like(hs[0], dtype=jnp.uint64)
    for h in hs:
        acc = splitmix64_jnp(acc ^ h.astype(jnp.uint64))
    return acc


def combine_hashes_np(hs: list[np.ndarray]) -> np.ndarray:
    acc = np.zeros_like(hs[0], dtype=np.uint64)
    for h in hs:
        acc = splitmix64_np(acc ^ h.astype(np.uint64))
    return acc


def hash_columns_jnp(cols: list[jnp.ndarray]) -> jnp.ndarray:
    """Hash one or more integer-valued columns (codes/ints/dates) to uint64."""
    return combine_hashes_jnp([splitmix64_jnp(_col_bits_jnp(c)) for c in cols])


def hash_columns_np(cols: list[np.ndarray]) -> np.ndarray:
    return combine_hashes_np([splitmix64_np(_col_bits_np(c)) for c in cols])


def _col_bits_jnp(c: jnp.ndarray) -> jnp.ndarray:
    if c.dtype == jnp.float64:
        return c.view(jnp.uint64)  # bit-pattern hash; exact-equality semantics
    if c.dtype == jnp.float32:
        return c.view(jnp.uint32).astype(jnp.uint64)
    if c.dtype == jnp.bool_:
        return c.astype(jnp.uint64)
    return c.astype(jnp.int64).view(jnp.uint64)


def _col_bits_np(c: np.ndarray) -> np.ndarray:
    if c.dtype == np.float64:
        return c.view(np.uint64)
    if c.dtype == np.float32:
        return c.view(np.uint32).astype(np.uint64)
    if c.dtype == np.bool_:
        return c.astype(np.uint64)
    return c.astype(np.int64).view(np.uint64)


def modulo_segment(h: jnp.ndarray, n_segments: int) -> jnp.ndarray:
    """Legacy modulo mapping (cdblegacyhash.c) — the device routing default."""
    return (h % jnp.uint64(n_segments)).astype(jnp.int32)


def jump_consistent_hash_jnp(keys: jnp.ndarray, n_buckets: int) -> jnp.ndarray:
    """Device-side jump consistent hash — MUST match the numpy version so
    Motion routing lands rows on the same segment where load-time placement
    (catalog.shard_assignment) put their join partners. Vectorized masked
    while_loop; expected O(ln n) iterations."""
    import jax

    keys = keys.astype(jnp.uint64)
    b0 = jnp.full(keys.shape, -1, dtype=jnp.int64)
    j0 = jnp.zeros(keys.shape, dtype=jnp.int64)

    def cond(state):
        _, j, _ = state
        return (j < n_buckets).any()

    def body(state):
        b, j, k = state
        active = j < n_buckets
        b = jnp.where(active, j, b)
        k = jnp.where(active, k * jnp.uint64(_JUMP) + jnp.uint64(1), k)
        denom = ((k >> jnp.uint64(33)) + jnp.uint64(1)).astype(jnp.float64)
        jn = ((b + 1) * (float(1 << 31) / denom)).astype(jnp.int64)
        j = jnp.where(active, jn, j)
        return b, j, k

    b, _, _ = jax.lax.while_loop(cond, body, (b0, j0, keys))
    return b.astype(jnp.int32)


def jump_consistent_hash_np(keys: np.ndarray, n_buckets: int) -> np.ndarray:
    """Lamping-Veach jump consistent hash, vectorized over keys (host side).

    Used for data placement so a resize from N to N+1 buckets relocates only
    ~1/(N+1) of rows (reference: cdbhash.c:55, gpexpand minimal movement).
    """
    keys = keys.astype(np.uint64)
    b = np.full(keys.shape, -1, dtype=np.int64)
    j = np.zeros(keys.shape, dtype=np.int64)
    active = j < n_buckets
    with np.errstate(over="ignore"):
        while active.any():
            b = np.where(active, j, b)
            keys = np.where(active, keys * _JUMP + np.uint64(1), keys)
            denom = ((keys >> np.uint64(33)) + np.uint64(1)).astype(np.float64)
            j = np.where(
                active,
                ((b + 1) * (float(1 << 31) / denom)).astype(np.int64),
                j,
            )
            active = j < n_buckets
    return b.astype(np.int32)
