"""Deterministic fault injection — the faultinjector.c analog.

The reference compiles ~230 named fault points into the server, armed at
runtime via gp_inject_fault() with actions (error/sleep/skip/suspend) and hit
counts (src/backend/utils/misc/faultinjector.c, SURVEY §4.2). Same model
here: code declares FAULT_POINT("name") at interesting seams; tests arm
actions. Used to provoke races/failures deterministically instead of hoping
load finds them (the reference's stance — no TSan harness, deterministic
provocation, §5.2).

Chaos soaks use the PROBABILISTIC arm (``p`` < 1): each in-window hit
fires with probability p from a per-arm seeded RNG — randomized but
REPRODUCIBLE (same seed → same firing sequence). ``list_faults()``
reports per-arm hit/fire telemetry plus every seam seen this process, so
a soak can state exactly which seams fired.
"""

from __future__ import annotations

import os
import random
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Optional


class InjectedFault(RuntimeError):
    pass


# IO-fault actions (consumed by storage/iofault.py): when one of these
# fires at a seam, fault_point() records it thread-locally and returns;
# the NEXT iofault write primitive on that thread implements the fault
# (torn prefix, short write, dropped fsync, ENOSPC, EIO). Arm them only
# on io_* seams — a seam with no following iofault write would leave
# the pending action to the thread's next unrelated write.
IO_ACTIONS = frozenset({"torn", "short", "fsync_drop", "enospc", "eio"})


@dataclass
class _Arm:
    action: str           # 'error' | 'sleep' | 'skip' | 'hang' |
    #                       'crash' | one of IO_ACTIONS
    sleep_s: float = 0.0
    start_hit: int = 1    # trigger from the Nth hit...
    end_hit: int = 1 << 30  # ...through this hit
    p: float = 1.0        # per-hit firing probability (chaos soaks)
    seed: Optional[int] = None
    hits: int = 0         # times the seam was reached while armed
    fired: int = 0        # times the action actually triggered
    # interruptible wedge: 'hang' blocks on this instead of a raw sleep,
    # so reset_fault() releases a wedged thread immediately
    wake: threading.Event = field(default_factory=threading.Event)
    rng: random.Random = None  # type: ignore[assignment]


# The seam contract of record: every fault_point() call site in the
# engine, by name. Chaos soaks and tests arm seams from this list;
# graftlint's seam pass (lint/passes/seams.py) fails the build when a
# call site is missing here (seam-unknown) or an entry here has no call
# site left (seam-stale) — a renamed seam must never silently drop out
# of soak coverage. Tests may declare ad-hoc seams of their own; those
# live in the tests, not in this inventory.
INVENTORY = frozenset({
    # planner/session dispatch
    "admission_check", "dispatch_start", "dist_execute_start",
    # storage / OCC
    "copy_from", "occ_commit_window", "storage_commit_before_current",
    "store_lock_acquire", "store_read_partition", "sync_store",
    # DML
    "dml_delete", "dml_insert_select", "dml_update",
    # serving / endpoints
    "serve_handler", "endpoint_drain", "fdist_get",
    # matviews
    "matview_maintain", "matview_refresh",
    # scheduler (sched/dispatcher.py)
    "sched_enqueue", "sched_coalesce", "sched_flush",
    # tiled execution + recovery
    "tile_step", "tile_step_dist", "tiled_finalize",
    "ckpt_save", "ckpt_resume", "tile_device_lost",
    # windowed tile dispatch (exec/tilepipe.py): enqueue fires as a
    # tile's step enters the in-flight window, drain fires as its
    # control scalars are forced — 'error'/'sleep' here torture the
    # deferred-failure replay and the drain stall accounting
    "tile_enqueue", "tile_drain",
    # asynchronous scan pipeline (exec/scanpipe.py): the prefetch
    # reader's per-tile seam and the per-partition decode seam
    "scan_prefetch", "scan_decode",
    # HBM buffer pool (exec/bufferpool.py): admission and eviction
    # seams — 'error' provokes mid-offer failures, 'skip' suppresses
    # admission / forces refusal-over-eviction
    "bufpool_admit", "bufpool_evict",
    # feedback-driven re-optimization (plan/feedback.py,
    # exec/tiled_dist.py): 'skip' on feedback_fold suppresses learning
    # after a statement; 'skip' on tile_replan suppresses the
    # mid-statement adaptive replan even when the skew alarm fires
    "feedback_fold", "tile_replan",
    # mesh health
    "exec_device_lost", "probe_degraded",
    # online topology changes (parallel/topology.py)
    "topo_rebalance_chunk", "topo_cutover", "topo_promote",
    # write path (storage/ingest.py, storage/compact.py): 'error' on
    # ingest_flush is device-loss-mid-flush — the WHOLE batch fails
    # before any statement commits (no partial durability); 'hang' on
    # compact_chunk wedges the worker cooperatively (cancel-mid-chunk);
    # 'error' on compact_commit dies inside the locked commit window
    # AFTER the new files exist — the crash-restart journal-resume case
    "ingest_flush", "compact_chunk", "compact_commit",
    # faulty-IO seams (storage/iofault.py, ISSUE 19): each guards ONE
    # durable write primitive — arm an IO_ACTIONS action to corrupt that
    # write (torn/short/fsync_drop/enospc/eio), or 'crash' to hard-kill
    # the process there (the torture-harness matrix).
    # io_partition_write: micro-partition file body
    # io_manifest_write:  v{N}.json snapshot manifest
    # storage_commit_after_current: just AFTER the CURRENT swap — the
    #   committed-but-unacknowledged window
    # io_atomic_json:     every _atomic_json (sequences, matviews,
    #   _TOPOLOGY.json, the compaction journal)
    # io_journal_write:   the compaction journal specifically
    # io_topology_write:  the topology record specifically
    # io_feedback_write:  the learned-stats _FEEDBACK.json write
    "io_partition_write", "io_manifest_write",
    "storage_commit_after_current", "io_atomic_json",
    "io_journal_write", "io_topology_write", "io_feedback_write",
})

_registry: dict[str, _Arm] = {}
_seen: set[str] = set()
_lock = threading.Lock()
# the fired-but-unconsumed IO action (per thread): set by fault_point
# when an IO_ACTIONS arm fires, popped by the next iofault write
_tls = threading.local()


def inject_fault(name: str, action: str = "error", sleep_s: float = 0.0,
                 start_hit: int = 1, end_hit: int = 1 << 30,
                 p: float = 1.0, seed: Optional[int] = None) -> None:
    """Arm a fault point (the gp_inject_fault() analog). ``p`` < 1 makes
    each in-window hit fire probabilistically from a per-arm RNG seeded
    by ``seed`` (default: a hash of the name, so re-arming reproduces
    the same sequence)."""
    arm = _Arm(action, sleep_s, start_hit, end_hit, p, seed)
    arm.rng = random.Random(
        seed if seed is not None else zlib.crc32(name.encode()))
    with _lock:
        old = _registry.get(name)
        _registry[name] = arm
    if old is not None:
        old.wake.set()  # a re-arm releases threads wedged on the old arm


def reset_fault(name: Optional[str] = None) -> None:
    with _lock:
        if name is None:
            arms = list(_registry.values())
            _registry.clear()
        else:
            arm = _registry.pop(name, None)
            arms = [arm] if arm is not None else []
    for arm in arms:  # outside the lock: waking needs no registry state
        arm.wake.set()


def fault_point(name: str) -> bool:
    """Declare a fault point. Returns True if the caller should SKIP the
    guarded step ('skip' action); raises/sleeps for other armed actions.

    The 'hang' action is a COOPERATIVE wedge (the reference's 'suspend'
    with gp_inject_fault resume semantics): it blocks on the arm's event
    — released by reset_fault()/re-arm — while polling the statement's
    cancellation seam, so a watchdog/cancel converts the wedge into a
    StatementTimeout/StatementCancelled and the worker thread survives."""
    with _lock:
        _seen.add(name)  # under the lock: handler threads race discovery
        arm = _registry.get(name)
        if arm is None:
            return False
        arm.hits += 1
        if not (arm.start_hit <= arm.hits <= arm.end_hit):
            return False
        if arm.p < 1.0 and arm.rng.random() >= arm.p:
            return False  # in-window hit that the dice spared
        arm.fired += 1
        action = arm.action
        sleep_s = arm.sleep_s
        wake = arm.wake
    if action == "error":
        raise InjectedFault(f"fault injected at {name!r}")
    if action == "crash":
        # the process-kill arm (ISSUE 19): no atexit, no flush, no
        # cleanup — the closest in-process analog of SIGKILL, so the
        # torture harness can die at ANY seam and restart-verify
        os._exit(137)
    if action in IO_ACTIONS:
        _tls.io_action = (name, action)
        return False
    if action == "sleep":
        time.sleep(sleep_s)
        return False
    if action == "skip":
        return True
    if action == "hang":
        from cloudberry_tpu.lifecycle import check_cancel

        end = time.monotonic() + (sleep_s or 3600.0)
        while not wake.wait(timeout=0.05):
            check_cancel()
            if time.monotonic() >= end:
                break
    return False


def take_io_action() -> Optional[tuple[str, str]]:
    """Pop this thread's pending (seam, io_action) pair, if any — the
    iofault write primitives call this at entry, so the IO fault lands
    on exactly the write the preceding fault_point() guarded."""
    pending = getattr(_tls, "io_action", None)
    _tls.io_action = None
    return pending


def arm_from_env(spec: Optional[str] = None) -> int:
    """Arm seams from a ``CBTPU_INJECT`` spec — how the crash-torture
    harness injects into a REAL server subprocess it is about to kill:
    semicolon-separated ``name=action[@start_hit[-end_hit]]`` entries,
    e.g. ``"io_manifest_write=crash@3"`` (crash on the 3rd hit) or
    ``"io_partition_write=torn"``. Returns the number of seams armed.
    Called once at server start (mgmt/cli.py serve)."""
    spec = spec if spec is not None else os.environ.get("CBTPU_INJECT", "")
    n = 0
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry or "=" not in entry:
            continue
        name, _, act = entry.partition("=")
        start, end = 1, 1 << 30
        if "@" in act:
            act, _, window = act.partition("@")
            lo, _, hi = window.partition("-")
            start = int(lo) if lo else 1
            end = int(hi) if hi else 1 << 30
        inject_fault(name.strip(), act.strip(), start_hit=start,
                     end_hit=end)
        n += 1
    return n


def known_fault_points() -> set[str]:
    """Fault points hit at least once this process (discovery aid)."""
    with _lock:
        return set(_seen)


def list_faults() -> dict:
    """Per-arm telemetry (the gp_inject_fault 'status' analog): which
    seams are armed, how often each was reached, and how often it
    actually fired — the chaos-soak report of record — plus every seam
    this process has seen (armed or not)."""
    with _lock:
        armed = {name: {
            "action": a.action, "p": a.p, "seed": a.seed,
            "start_hit": a.start_hit, "end_hit": a.end_hit,
            "hits": a.hits, "fired": a.fired,
        } for name, a in _registry.items()}
        return {"armed": armed, "seen": sorted(_seen)}
