"""Deterministic fault injection — the faultinjector.c analog.

The reference compiles ~230 named fault points into the server, armed at
runtime via gp_inject_fault() with actions (error/sleep/skip/suspend) and hit
counts (src/backend/utils/misc/faultinjector.c, SURVEY §4.2). Same model
here: code declares FAULT_POINT("name") at interesting seams; tests arm
actions. Used to provoke races/failures deterministically instead of hoping
load finds them (the reference's stance — no TSan harness, deterministic
provocation, §5.2).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Optional


class InjectedFault(RuntimeError):
    pass


@dataclass
class _Arm:
    action: str           # 'error' | 'sleep' | 'skip' | 'hang'
    sleep_s: float = 0.0
    start_hit: int = 1    # trigger from the Nth hit...
    end_hit: int = 1 << 30  # ...through this hit
    hits: int = 0


_registry: dict[str, _Arm] = {}
_seen: set[str] = set()
_lock = threading.Lock()


def inject_fault(name: str, action: str = "error", sleep_s: float = 0.0,
                 start_hit: int = 1, end_hit: int = 1 << 30) -> None:
    """Arm a fault point (the gp_inject_fault() analog)."""
    with _lock:
        _registry[name] = _Arm(action, sleep_s, start_hit, end_hit)


def reset_fault(name: Optional[str] = None) -> None:
    with _lock:
        if name is None:
            _registry.clear()
        else:
            _registry.pop(name, None)


def fault_point(name: str) -> bool:
    """Declare a fault point. Returns True if the caller should SKIP the
    guarded step ('skip' action); raises/sleeps for other armed actions."""
    _seen.add(name)
    with _lock:
        arm = _registry.get(name)
        if arm is None:
            return False
        arm.hits += 1
        if not (arm.start_hit <= arm.hits <= arm.end_hit):
            return False
        action = arm.action
        sleep_s = arm.sleep_s
    if action == "error":
        raise InjectedFault(f"fault injected at {name!r}")
    if action == "sleep":
        time.sleep(sleep_s)
        return False
    if action == "skip":
        return True
    if action == "hang":
        time.sleep(3600.0)
    return False


def known_fault_points() -> set[str]:
    """Fault points hit at least once this process (discovery aid)."""
    return set(_seen)
