"""Z-order (Morton) keys for write clustering.

The reference's PAX storage clusters files by z-order so per-file min/max
statistics become tight multi-column bounding boxes
(contrib/pax_storage/src/cpp/clustering/zorder_clustering.cc); same idea
here: CLUSTER <t> BY (a, b) reorders the table by the interleaved-bit key
below before the snapshot writer chunks rows into micro-partition files —
each file then covers a small rectangle of (a, b) space and manifest
min/max pruning skips most files for predicates on ANY clustered column.

Values are rank-normalized first (position in the column's sorted order,
scaled to the bit budget): z-order quality depends on dimensions having
comparable scales, and ranks are distribution-free — the same reason the
reference normalizes through its encoder rather than interleaving raw
bits. Host-side numpy by design: clustering is a write-path rewrite, not
a query-path op."""

from __future__ import annotations

import numpy as np

_TOTAL_BITS = 62  # stay inside int64


def zorder_key(columns: list[np.ndarray]) -> np.ndarray:
    """Morton key per row from k numeric columns (k >= 1)."""
    k = len(columns)
    if k == 0:
        raise ValueError("z-order needs at least one column")
    n = len(columns[0])
    bits = _TOTAL_BITS // k
    out = np.zeros(n, dtype=np.uint64)
    ranks = []
    for arr in columns:
        # rank-normalize to [0, 2^bits): argsort-of-argsort is the dense
        # row rank; ties keep input order, which is fine for locality
        order = np.argsort(arr, kind="stable")
        r = np.empty(n, dtype=np.int64)
        r[order] = np.arange(n, dtype=np.int64)
        if n > 1:
            r = (r * ((1 << bits) - 1)) // (n - 1)
        ranks.append(r.astype(np.uint64))
    for b in range(bits):
        for j, r in enumerate(ranks):
            out |= ((r >> np.uint64(b)) & np.uint64(1)) \
                << np.uint64(b * k + j)
    return out
