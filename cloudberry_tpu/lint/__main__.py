"""graftlint CLI — ``python -m cloudberry_tpu.lint [paths...]``.

Exit codes: 0 = clean (no unsuppressed findings), 1 = findings,
2 = usage error. Output is one finding per line (``file:line: rule:
message``); ``--json`` switches to a machine-readable document and
``--dot`` prints the static lock-acquisition graph instead.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    from cloudberry_tpu.lint.core import lock_graph_dot, run_lint

    ap = argparse.ArgumentParser(
        prog="python -m cloudberry_tpu.lint",
        description="project-invariant static analysis "
                    "(lock discipline, trace purity, taxonomy, seams)")
    ap.add_argument("paths", nargs="*",
                    help="files/directories to lint "
                         "(default: the cloudberry_tpu package)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings document")
    ap.add_argument("--dot", action="store_true",
                    help="print the lock-acquisition graph (Graphviz)")
    ap.add_argument("--rule", action="append", default=None,
                    help="restrict output to these rule ids")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="include suppressed findings in the output")
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0

    paths = args.paths
    if not paths:
        import cloudberry_tpu

        paths = [os.path.dirname(os.path.abspath(
            cloudberry_tpu.__file__))]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"no such path: {', '.join(missing)}", file=sys.stderr)
        return 2

    result = run_lint(paths)

    # one gate for every output mode: all unsuppressed findings, or —
    # under --rule — only the selected rules (the exit code must never
    # fail on findings the invocation does not report)
    gate = result.unsuppressed
    shown = result.unsuppressed + (
        result.suppressed if args.show_suppressed else [])
    suppressed = result.suppressed
    if args.rule:
        allowed = set(args.rule)
        gate = [f for f in gate if f.rule in allowed]
        shown = [f for f in shown if f.rule in allowed]
        suppressed = [f for f in suppressed if f.rule in allowed]
    shown.sort(key=lambda f: (f.file, f.line, f.rule))

    if args.dot:
        print(lock_graph_dot(result))
        return 1 if gate else 0

    # the summary describes what THIS invocation gates on — a --rule
    # scope must not report global counts next to a filtered list
    rules: dict[str, int] = {}
    for f in gate:
        rules[f.rule] = rules.get(f.rule, 0) + 1
    summary = {"findings": len(gate), "suppressions": len(suppressed),
               "files": len(result.modules),
               "rules": dict(sorted(rules.items()))}
    if args.json:
        print(json.dumps({
            "summary": summary,
            "findings": [f.as_dict() for f in shown],
        }, indent=1))
    else:
        for f in shown:
            print(f.render())
        print(f"graftlint: {summary['findings']} finding(s), "
              f"{summary['suppressions']} suppressed, "
              f"{summary['files']} file(s)")
    return 1 if gate else 0


if __name__ == "__main__":
    sys.exit(main())
