"""graftlint configuration — scope, known-concurrent classes, lock order.

Everything project-specific the passes need lives here so the analyzer
core stays generic: which files are in scope, which attribute names map
to which concurrent classes (the cross-class acquisition edges the AST
cannot type), which modules are kernel/tile scope, and the DECLARED lock
order the runtime witness asserts.
"""

from __future__ import annotations

from dataclasses import dataclass, field

# directories never scanned (relative path components)
EXCLUDE_DIRS = frozenset({
    "__pycache__", ".git", ".pytest_cache", "tests", "build", "dist",
})

# files never scanned (relative path suffixes)
EXCLUDE_FILES = frozenset({
    "conftest.py",
})

# classes whose shared mutable attributes the lock pass audits even when
# the mixed-guard heuristic alone would not select them — the concurrent
# core's known-shared objects (ISSUE 8 / DESIGN.md "Multi-tenant serving
# core"). CacheScope is the shared-cache tier's per-scope record (the
# SharedCacheTier analog).
CONCURRENT_CLASSES = frozenset({
    "Dispatcher", "TenantScheduler", "CacheScope", "StatementLog",
    "RecoveryStore", "CircuitBreaker", "CancelToken", "Watchdog",
    "AdmissionGate", "VmemTracker", "QueueManager", "_Conn", "_IOLoop",
    "MetricsRegistry", "StatementStats", "Trace", "Progress",
    "TopologyManager", "ScanPipeline", "BufferPool", "FeedbackStore",
    "IngestService", "CompactionService",
})

# attribute-name → class-name hints for cross-class lock edges: when a
# method calls ``self.<attr>.m()`` while holding a lock, the pass needs
# the attribute's class to know which locks ``m`` acquires. Python has
# no static types here; these are the project's stable wiring names.
ATTR_CLASS_HINTS = {
    "tenancy": "TenantScheduler",
    "stmt_log": "StatementLog",
    "_breaker": "CircuitBreaker",
    "_recovery": "RecoveryStore",
    "_gate": "AdmissionGate",
    "_vmem": "VmemTracker",
    "_queues_mgr": "QueueManager",
    "dispatcher": "Dispatcher",
    "_dispatcher": "Dispatcher",
    "watchdog": "Watchdog",
    "_rw": "_RWLock",
    "loop": "_IOLoop",
    "conn": "_Conn",
    "token": "CancelToken",
    "_cache_scope": "CacheScope",
    "scope": "CacheScope",
    "_topology": "TopologyManager",
    "topology": "TopologyManager",
    "topo": "TopologyManager",
    "registry": "MetricsRegistry",
    "statements": "StatementStats",
    "session": "Session",
    "sess": "Session",
    "_sched": "TenantScheduler",
    # two-level motion wiring (ISSUE 14): the transport and its derived
    # topology are immutable/lock-free by design, but name them so
    # cross-class call edges resolve when a lock-holding caller touches
    # them (and so a future lock added there is discovered, not missed)
    "tx": "HierarchicalCollectives",
    "hier_topo": "HostTopology",
    # HBM buffer pool (exec/bufferpool.py) — the scan-path consumers
    # and the topology-cutover sweep reach it through these names
    "bpool": "BufferPool",
    "bufpool": "BufferPool",
    "_bufpool": "BufferPool",
    # learned-stats store (plan/feedback.py) — planner consumers reach
    # it through these names while cache-tier locks may be held
    "feedback": "FeedbackStore",
    "_feedback_store": "FeedbackStore",
    # write plane (ISSUE 18): the server and capacity gauges reach the
    # ingest buffers / compactor through these names
    "ingest": "IngestService",
    "_ingest": "IngestService",
    "compactor": "CompactionService",
    "_compactor": "CompactionService",
}

# modules (repo-relative path suffixes) whose jitted / kernel functions
# the trace-purity pass audits
KERNEL_MODULES = (
    "exec/kernels.py",
    "exec/pallas_kernels.py",
    "exec/expr_compile.py",
    "exec/executor.py",
    "exec/dist_executor.py",
    "exec/tiled.py",
    "exec/tiled_dist.py",
    "exec/instrument.py",
)

# functions in kernel scope whose name contains one of these substrings
# implement the int64/DECIMAL limb convention itself — the one place f32
# accumulation of integer limbs is the POINT, not a bug
LIMB_FUNC_MARKERS = ("limb", "decimal")

# modules whose unbounded tile/retry loops must contain a cancel seam
SEAM_LOOP_MODULES = (
    "exec/tiled.py",
    "exec/tiled_dist.py",
    "exec/recovery.py",
    "exec/scanpipe.py",
    "exec/tilepipe.py",
    "storage/ingest.py",
    "storage/compact.py",
)

# calls that count as a cancellation seam inside a loop body;
# drain_one/drain_all route every drained tile through
# _raise_tile_checks, so the windowed dispatcher's drain loops poll
# cancellation once per verified tile
CANCEL_SEAM_CALLS = frozenset({
    "check_cancel", "raise_if_cancelled", "_raise_tile_checks", "check",
    "drain_one", "drain_all",
})

# modules whose wire-response dict literals the taxonomy pass audits
WIRE_MODULES = (
    "serve/server.py",
    "serve/asyncore.py",
    "serve/mcp.py",
)

# where the taxonomy of record lives
TAXONOMY_MODULE = "lifecycle.py"
RETRYABLE_NAMES_CONST = "_RETRYABLE_NAMES"

# where the seam inventory of record lives
FAULTINJECT_MODULE = "utils/faultinject.py"
INVENTORY_CONST = "INVENTORY"

# where the wire metadata verbs live (the obs pass pins describe()'s
# documented Kinds list to its implemented kind == "..." branches)
META_MODULE = "serve/meta.py"

# planprops pass anchors: the plan verifier's rule table of record,
# and the checkpointing/re-placement mode tables it pins together
PLAN_VERIFY_MODULE = "plan/verify.py"
TILED_MODULE = "exec/tiled.py"
RECOVERY_MODULE = "exec/recovery.py"

# ---------------------------------------------------------------- witness

# The DECLARED lock acquisition order (coarse ranks; acquiring a lock of
# rank <= a held lock's rank, other than re-entering the same object, is
# a violation the runtime witness records). Derived from the static
# acquisition graph (`python -m cloudberry_tpu.lint --dot`) — update BOTH
# when the order legitimately changes, and keep DESIGN.md's section in
# sync. Locks not named here are unwitnessed.
WITNESS_ORDER: tuple[tuple[str, ...], ...] = (
    # rank 0 — serving front end (outermost)
    ("Server._inflight_cond", "Server._conn_lock", "Server._login_lock",
     "_RWLock._cond", "_Conn.lock", "_IOLoop._tlock"),
    # rank 1 — scheduling tier + session cache sync + topology epochs
    # (TopologyManager._lock is never held across the session sync
    # lock: pin/cutover capture state under it, release, then adopt)
    ("Dispatcher._cond", "Session._sync_lock", "TopologyManager._lock"),
    # rank 2 — tenancy / breaker / cache-tier locks (Dispatcher._cond
    # and Session._sync_lock callers nest into these). The write-plane
    # conditions live here too: both are NEVER held across a flush /
    # SQL / the store lock (batches are taken under the condition,
    # executed outside it), never nested with each other (the
    # on_commit → wake call runs outside both), and only counter bumps
    # (rank-4 MetricsRegistry) happen while held.
    ("TenantScheduler._lock", "CircuitBreaker._lock",
     "CacheScope.generic_lock", "CacheScope.rung_lock",
     "CacheScope.joinindex_lock", "RecoveryStore._lock",
     "AdmissionGate._lock", "VmemTracker._cond", "QueueManager._cond",
     "Session._stmt_lock", "IngestService._cond",
     "CompactionService._cond"),
    # rank 3 — accounting taken while cache locks are held (the
    # compile-counter bump inside a generic-plan build holds
    # generic_lock → StatementLog._lock; plan-local rung growth nests
    # under the session rung lock)
    ("StatementLog._lock", "GenericPlan._rung_lock"),
    # rank 4 — innermost leaves (never call out while held). The
    # feedback-store locks live HERE, not with the rank-2 cache-tier
    # locks: planning paths reach sketch lookups while holding
    # CacheScope locks (generic-plan builds plan under generic_lock),
    # so FeedbackStore._lock must nest inside them; _io_lock serializes
    # the _FEEDBACK.json write and is never nested with _lock (the
    # snapshot is taken, released, THEN written).
    ("CancelToken._lock", "faultinject._lock", "sharedcache._tier_lock",
     "MetricsRegistry._lock", "StatementStats._lock", "Trace._lock",
     "Progress._lock", "mesh._topo_lock", "ScanPipeline._cond",
     "scanpipe._pool_lock", "BufferPool._lock",
     "bufferpool._create_lock", "FeedbackStore._lock",
     "FeedbackStore._io_lock", "feedback._create_lock"),
    # rank 5 — the storage IO shim's counter lock (storage/iofault.py):
    # every durable write can bump storage_io_errors, and writers reach
    # it while holding rank-4 locks (FeedbackStore._io_lock wraps the
    # _FEEDBACK.json atomic replace), so it nests inside EVERYTHING and
    # never calls out
    ("iofault._lock",),
)


def witness_ranks() -> dict[str, int]:
    return {name: rank
            for rank, tier in enumerate(WITNESS_ORDER)
            for name in tier}


@dataclass
class LintConfig:
    """One run's scope + knobs (tests override paths/excludes to point
    the analyzer at fixture trees)."""

    exclude_dirs: frozenset = EXCLUDE_DIRS
    exclude_files: frozenset = EXCLUDE_FILES
    concurrent_classes: frozenset = CONCURRENT_CLASSES
    attr_class_hints: dict = field(
        default_factory=lambda: dict(ATTR_CLASS_HINTS))
    kernel_modules: tuple = KERNEL_MODULES
    limb_func_markers: tuple = LIMB_FUNC_MARKERS
    seam_loop_modules: tuple = SEAM_LOOP_MODULES
    cancel_seam_calls: frozenset = CANCEL_SEAM_CALLS
    wire_modules: tuple = WIRE_MODULES
    taxonomy_module: str = TAXONOMY_MODULE
    faultinject_module: str = FAULTINJECT_MODULE
    meta_module: str = META_MODULE
    plan_verify_module: str = PLAN_VERIFY_MODULE
    tiled_module: str = TILED_MODULE
    recovery_module: str = RECOVERY_MODULE
    # seam names armed only from tests/tools (not declared at an engine
    # call site) that the inventory still documents
    inventory_extra_ok: frozenset = frozenset()

    def in_scope(self, relpath: str) -> bool:
        parts = relpath.replace("\\", "/").split("/")
        if any(p in self.exclude_dirs for p in parts[:-1]):
            return False
        return parts[-1] not in self.exclude_files
