"""graftlint core — findings, suppressions, file collection, the driver.

The analyzer never crashes on bad input: a file that does not parse
becomes a ``syntax`` FINDING (file:line of the error) and is skipped by
the passes. Suppression is per-site: a ``# graftlint: ignore[rule]``
comment on the finding's line (or on the line above, for findings on
multi-line statements) suppresses that rule there; the text after the
bracket is the justification the clean gate requires.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*ignore\[([a-z0-9_,\s-]+)\]\s*(.*)")


@dataclass
class Finding:
    rule: str
    file: str                # repo-relative path
    line: int
    message: str
    suppressed: bool = False
    justification: str = ""

    def render(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return f"{self.file}:{self.line}: {self.rule}: {self.message}{tag}"

    def as_dict(self) -> dict:
        return {"rule": self.rule, "file": self.file, "line": self.line,
                "message": self.message, "suppressed": self.suppressed,
                "justification": self.justification}


@dataclass
class Suppression:
    rules: tuple
    justification: str
    used: bool = False


class LintModule:
    """One parsed source file: AST + per-line suppressions. ``tree`` is
    None when the file failed to parse (the syntax finding already
    reported it)."""

    def __init__(self, path: str, relpath: str):
        self.path = path
        self.relpath = relpath
        self.tree: ast.AST | None = None
        self.lines: list[str] = []
        self.suppressions: dict[int, Suppression] = {}
        self.parse_error: Finding | None = None
        try:
            with open(path, encoding="utf-8", errors="replace") as f:
                src = f.read()
        except OSError as e:
            self.parse_error = Finding(
                "syntax", relpath, 1, f"unreadable file: {e}")
            return
        self.lines = src.splitlines()
        for i, text in self._comment_lines(src):
            m = _SUPPRESS_RE.search(text)
            if m:
                rules = tuple(r.strip() for r in m.group(1).split(",")
                              if r.strip())
                self.suppressions[i] = Suppression(
                    rules, m.group(2).strip(" —-:"))
        try:
            self.tree = ast.parse(src, filename=relpath)
        except (SyntaxError, ValueError) as e:
            line = getattr(e, "lineno", 1) or 1
            self.parse_error = Finding(
                "syntax", relpath, int(line),
                f"file does not parse: {getattr(e, 'msg', e)}")

    @staticmethod
    def _comment_lines(src: str):
        """(line, text) for REAL comment tokens only — a docstring that
        merely mentions the ``# graftlint: ignore[...]`` syntax must not
        register as a suppression (or as a stale one)."""
        import io
        import tokenize

        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(src).readline):
                if tok.type == tokenize.COMMENT:
                    yield tok.start[0], tok.string
        except (tokenize.TokenError, IndentationError, SyntaxError):
            # unparseable tail: the syntax finding covers the file; any
            # comments tokenized before the error were already yielded
            return

    def suppression_for(self, rule: str, line: int) -> Suppression | None:
        """The suppression governing ``rule`` at ``line``: same line
        first, then the line directly above (for findings anchored to a
        multi-line statement's first line)."""
        for ln in (line, line - 1):
            s = self.suppressions.get(ln)
            if s is not None and (rule in s.rules or "all" in s.rules):
                return s
        return None


@dataclass
class LintResult:
    findings: list[Finding] = field(default_factory=list)
    modules: list[LintModule] = field(default_factory=list)
    # pass artifacts (the lock graph rides here for --dot / DESIGN.md)
    lock_graph: dict = field(default_factory=dict)
    lock_sites: dict = field(default_factory=dict)

    @property
    def unsuppressed(self) -> list[Finding]:
        return [f for f in self.findings if not f.suppressed]

    @property
    def suppressed(self) -> list[Finding]:
        return [f for f in self.findings if f.suppressed]

    def rule_counts(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for f in self.unsuppressed:
            out[f.rule] = out.get(f.rule, 0) + 1
        return dict(sorted(out.items()))

    def summary(self) -> dict:
        return {"findings": len(self.unsuppressed),
                "suppressions": len(self.suppressed),
                "files": len(self.modules),
                "rules": self.rule_counts()}


def collect_files(paths, cfg) -> list[tuple[str, str]]:
    """(abs path, repo-relative path) for every in-scope .py file. The
    relative root is the deepest common parent so rule scoping by
    module suffix (serve/server.py, ...) works from any invocation
    directory."""
    out = []
    for root in paths:
        root = os.path.abspath(root)
        if os.path.isfile(root):
            # anchor the relative path at the package root (walk up
            # through __init__.py parents) so suffix-scoped rules
            # (serve/server.py, exec/kernels.py, ...) still apply to a
            # single-file invocation
            base = os.path.dirname(root)
            while os.path.exists(os.path.join(base, "__init__.py")):
                base = os.path.dirname(base)
            rel = os.path.relpath(root, base).replace(os.sep, "/")
            if cfg.in_scope(rel):
                out.append((root, rel))
            continue
        base = os.path.dirname(root.rstrip(os.sep))
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in cfg.exclude_dirs)
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                p = os.path.join(dirpath, fn)
                rel = os.path.relpath(p, base).replace(os.sep, "/")
                if cfg.in_scope(rel):
                    out.append((p, rel))
    return out


def run_lint(paths, cfg=None) -> LintResult:
    """Run every pass over ``paths`` (files or directories). Never
    raises for bad INPUT (syntax errors become findings); programming
    errors in the passes themselves do propagate — the gate must fail
    loudly, not mask itself."""
    from cloudberry_tpu.lint.config import LintConfig
    from cloudberry_tpu.lint.passes import locks, obs, planprops, seams
    from cloudberry_tpu.lint.passes import taxonomy, tracepurity

    cfg = cfg if cfg is not None else LintConfig()
    result = LintResult()
    raw: list[Finding] = []
    for path, rel in collect_files(paths, cfg):
        mod = LintModule(path, rel)
        result.modules.append(mod)
        if mod.parse_error is not None:
            raw.append(mod.parse_error)
    parsed = [m for m in result.modules if m.tree is not None]
    raw += locks.run(parsed, cfg, result)
    raw += tracepurity.run(parsed, cfg)
    raw += taxonomy.run(parsed, cfg)
    raw += seams.run(parsed, cfg)
    raw += obs.run(parsed, cfg)
    raw += planprops.run(parsed, cfg)

    by_file = {m.relpath: m for m in result.modules}
    for f in raw:
        mod = by_file.get(f.file)
        if mod is not None:
            s = mod.suppression_for(f.rule, f.line)
            if s is not None:
                f.suppressed = True
                f.justification = s.justification
                s.used = True
    # suppression hygiene is part of the gate, not just the test suite:
    # a suppression that matched nothing is itself a finding (the code
    # it excused was refactored away, and leaving the comment would
    # silently swallow the NEXT finding on that line), and a matching
    # suppression with NO justification fails too — the policy is
    # "a bare tag fails", and the CLI/CI gate must enforce it exactly
    # like tests/test_lint_clean.py does
    for mod in result.modules:
        for ln, s in sorted(mod.suppressions.items()):
            if not s.used:
                raw.append(Finding(
                    "unused-suppression", mod.relpath, ln,
                    f"suppression for [{', '.join(s.rules)}] matches no "
                    "finding — delete the stale ignore comment"))
            elif not s.justification.strip():
                raw.append(Finding(
                    "unjustified-suppression", mod.relpath, ln,
                    f"suppression for [{', '.join(s.rules)}] has no "
                    "justification — say WHY the site is deliberately "
                    "exempt after the bracket"))
    result.findings = sorted(raw, key=lambda f: (f.file, f.line, f.rule))
    return result


def lock_graph_dot(result: LintResult) -> str:
    """The static acquisition-order graph as Graphviz dot (documentation
    artifact for DESIGN.md; cycles would have been findings)."""
    lines = ["digraph lock_order {", "  rankdir=LR;",
             '  node [shape=box, fontsize=10];']
    nodes = set()
    for a, edges in sorted(result.lock_graph.items()):
        nodes.add(a)
        for b in edges:
            nodes.add(b)
    for n in sorted(nodes):
        lines.append(f'  "{n}";')
    for a, edges in sorted(result.lock_graph.items()):
        for b, site in sorted(edges.items()):
            lines.append(f'  "{a}" -> "{b}" '
                         f'[label="{site[0]}:{site[1]}", fontsize=8];')
    lines.append("}")
    return "\n".join(lines)
