"""graftlint — project-invariant static analysis for the concurrent core.

Generic linters check style; this one checks the INVARIANTS this engine's
concurrency and kernel layers rely on but that, until now, lived only in
DESIGN.md prose and reviewers' heads (the reference enforces the same
class of discipline with compiled-in assertions and the faultinjector —
SURVEY §4.2/§5.2):

- **lock discipline** (``lock-order``, ``lock-unguarded``, ``lock-held-call``)
  — every ``threading.Lock/RLock/Condition`` attribute is discovered, the
  static acquisition-order graph is built from nested ``with`` blocks and
  calls made while holding a lock, and cycles (potential deadlock), calls
  that re-acquire a held non-reentrant lock, and writes to mixed-guard
  shared attributes outside any lock are findings;
- **trace purity** (``purity-*``) — inside jitted/Pallas-kernel functions,
  host-side escapes are findings: ``np.*`` on traced values,
  ``.item()``/``float()``/``int()`` coercions, Python branching on tracer
  values, f32 accumulation of int64/DECIMAL values outside the limb
  convention;
- **taxonomy integrity** (``tax-*``) — every error dict serialized to the
  wire carries the ``retryable`` stamp, and every name the client retries
  BY NAME (lifecycle._RETRYABLE_NAMES) exists and round-trips;
- **seam integrity** (``seam-*``) — every ``fault_point`` call site appears
  in the faultinject INVENTORY (and vice versa), and every unbounded
  tile/retry loop contains a ``check_cancel()`` seam.

Per-site suppressions: ``# graftlint: ignore[rule]`` (with a justification
after the bracket — the clean gate requires one). Machine-readable output:
``python -m cloudberry_tpu.lint --json``; the lock graph:
``python -m cloudberry_tpu.lint --dot``.

The static passes are complemented by a RUNTIME lock-order witness
(lint/witness.py): a debug-mode wrapper asserting the declared acquisition
order on dynamic paths the AST cannot see, enabled under the
lifecycle/tenancy/shared-cache test suites.
"""

from cloudberry_tpu.lint.core import (  # noqa: F401
    Finding,
    LintResult,
    run_lint,
)
