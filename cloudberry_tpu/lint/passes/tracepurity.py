"""Trace-purity pass — host-side escapes inside jitted/kernel functions.

A function body that executes UNDER TRACE (jax.jit, shard_map, a Pallas
kernel) must stay on-device: ``np.*`` on a traced value silently falls
back to host numpy (wrong under jit — it either fails on tracers or
constant-folds stale data), ``.item()``/``float()``/``int()`` coercions
force a concretization error, Python ``if``/``while`` on a tracer raises
``TracerBoolConversionError`` at runtime, and f32 accumulation of
int64/DECIMAL values silently rounds past 2^24 unless it rides the limb
convention (DESIGN.md "Exact grouped aggregation").

Kernel scope — in config.KERNEL_MODULES only:

- functions decorated with ``jax.jit`` (incl. ``functools.partial``);
- functions passed BY NAME to ``jax.jit(...)`` / ``_shard_map(...)`` /
  ``pl.pallas_call(...)`` in the same module;
- Pallas kernels (name ends with ``_kernel``).

Rules: ``purity-host-np``, ``purity-coerce``, ``purity-branch``,
``purity-f32-accum``.
"""

from __future__ import annotations

import ast

from cloudberry_tpu.lint.core import Finding

# np.* calls that are trace-legal (shape/dtype metadata, not data)
_NP_META_OK = frozenset({
    "dtype", "shape", "ndim", "iinfo", "finfo", "result_type",
    "promote_types", "can_cast", "issubdtype", "sctype2char",
    # dtype constructors used as static arguments
    "int8", "int16", "int32", "int64", "uint8", "uint16", "uint32",
    "uint64", "float16", "float32", "float64", "bool_", "integer",
    "floating", "number", "generic", "signedinteger", "unsignedinteger",
})

_TRACED_JIT_CALLS = ("jit", "pallas_call", "shard_map", "_shard_map",
                     "pjit", "vmap", "pmap")


def _is_jit_decorator(dec: ast.AST) -> bool:
    """@jax.jit / @jit / @functools.partial(jax.jit, ...)."""

    def names(n: ast.AST) -> str:
        if isinstance(n, ast.Attribute):
            return n.attr
        if isinstance(n, ast.Name):
            return n.id
        return ""

    if names(dec) == "jit":
        return True
    if isinstance(dec, ast.Call):
        if names(dec.func) == "jit":
            return True
        if names(dec.func) == "partial" and dec.args \
                and names(dec.args[0]) == "jit":
            return True
    return False


def _collect_kernel_funcs(tree: ast.Module) -> dict[str, ast.FunctionDef]:
    """name -> FunctionDef for every function in kernel scope, at any
    nesting depth (tiled executors define step_fn inside methods)."""
    all_funcs: dict[str, list[ast.FunctionDef]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            all_funcs.setdefault(node.name, []).append(node)

    kernel: dict[str, ast.FunctionDef] = {}
    for name, defs in all_funcs.items():
        for fn in defs:
            if name.endswith("_kernel"):
                kernel[name] = fn
            elif any(_is_jit_decorator(d) for d in fn.decorator_list):
                kernel[name] = fn
    # functions passed by name into jit/pallas_call/shard_map
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fname = node.func.attr if isinstance(node.func, ast.Attribute) \
            else (node.func.id if isinstance(node.func, ast.Name) else "")
        if fname not in _TRACED_JIT_CALLS:
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Name) and arg.id in all_funcs:
                for fn in all_funcs[arg.id]:
                    kernel[arg.id] = fn
    return kernel


def _const_args_only(call: ast.Call) -> bool:
    return all(isinstance(a, (ast.Constant, ast.UnaryOp))
               for a in call.args)


def _expr_text(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return ""


class _PurityWalker(ast.NodeVisitor):
    def __init__(self, file: str, fn: ast.FunctionDef, limb_ok: bool,
                 findings: list):
        self.file = file
        self.fn = fn
        self.limb_ok = limb_ok
        self.findings = findings
        # static/config parameters (keyword-only or *, defaults of int)
        # are python values — int()/float() on them is fine
        self.static_names = {a.arg for a in fn.args.kwonlyargs}

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        # np.something(...) on traced values
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id in ("np", "numpy") \
                and f.attr not in _NP_META_OK:
            self.findings.append(Finding(
                "purity-host-np", self.file, node.lineno,
                f"host-side numpy call np.{f.attr}(...) inside traced "
                f"function {self.fn.name!r} — use jnp (np falls off the "
                "device and breaks under jit)"))
        # .item() concretization
        if isinstance(f, ast.Attribute) and f.attr in ("item", "tolist"):
            self.findings.append(Finding(
                "purity-coerce", self.file, node.lineno,
                f".{f.attr}() inside traced function {self.fn.name!r} "
                "forces a device→host concretization "
                "(TracerArrayConversionError under jit)"))
        # float(x)/int(x)/bool(x) on non-literal args
        if isinstance(f, ast.Name) and f.id in ("float", "int", "bool") \
                and node.args and not _const_args_only(node):
            arg = node.args[0]
            if not (isinstance(arg, ast.Name)
                    and arg.id in self.static_names):
                self.findings.append(Finding(
                    "purity-coerce", self.file, node.lineno,
                    f"{f.id}(...) coercion inside traced function "
                    f"{self.fn.name!r} concretizes a traced value; use "
                    f"jnp casts (x.astype) or mark the arg static"))
        self.generic_visit(node)

    def _test_is_traced(self, test: ast.AST) -> bool:
        """A branch test that CALLS jnp (jnp.any(x) > 0, jnp.all(...))
        is branching on a tracer — the one form we can prove statically."""
        for sub in ast.walk(test):
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and isinstance(sub.func.value, ast.Name) \
                    and sub.func.value.id == "jnp":
                return True
        return False

    def visit_If(self, node: ast.If) -> None:
        if self._test_is_traced(node.test):
            self.findings.append(Finding(
                "purity-branch", self.file, node.lineno,
                f"Python branch on a jnp expression inside traced "
                f"function {self.fn.name!r} — use jnp.where / lax.cond "
                "(a tracer has no truth value)"))
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        if self._test_is_traced(node.test):
            self.findings.append(Finding(
                "purity-branch", self.file, node.lineno,
                f"Python while-loop on a jnp expression inside traced "
                f"function {self.fn.name!r} — use lax.while_loop"))
        self.generic_visit(node)

    def check_f32_accum(self) -> None:
        if self.limb_ok:
            return
        for node in ast.walk(self.fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            # x.astype(jnp.float32) where x mentions int64/i64
            if isinstance(f, ast.Attribute) and f.attr == "astype" \
                    and node.args:
                dst = _expr_text(node.args[0])
                src = _expr_text(f.value)
                if dst.endswith(("float32", "f32")) and any(
                        m in src for m in ("int64", "i64")):
                    self.findings.append(Finding(
                        "purity-f32-accum", self.file, node.lineno,
                        f"int64 value cast to f32 inside traced function "
                        f"{self.fn.name!r} outside the limb convention — "
                        "sums silently round past 2^24 (use the 13-bit "
                        "limb path, kernels group_layout)"))
            # jnp.sum(..., dtype=jnp.float32) over an int64 expression
            if isinstance(f, ast.Attribute) \
                    and f.attr in ("sum", "cumsum") :
                for kw in node.keywords:
                    if kw.arg == "dtype" \
                            and _expr_text(kw.value).endswith("float32"):
                        args_text = " ".join(
                            _expr_text(a) for a in node.args)
                        if any(m in args_text for m in ("int64", "i64")):
                            self.findings.append(Finding(
                                "purity-f32-accum", self.file,
                                node.lineno,
                                "f32-dtype reduction over an int64 "
                                f"expression in {self.fn.name!r} — "
                                "exactness requires the limb path"))


def run(modules, cfg) -> list[Finding]:
    findings: list[Finding] = []
    for mod in modules:
        if not any(mod.relpath.endswith(k) for k in cfg.kernel_modules):
            continue
        kernels = _collect_kernel_funcs(mod.tree)
        for name, fn in sorted(kernels.items()):
            limb_ok = any(m in name.lower()
                          for m in cfg.limb_func_markers)
            w = _PurityWalker(mod.relpath, fn, limb_ok, findings)
            for stmt in fn.body:
                w.visit(stmt)
            w.check_f32_accum()
    return findings
