"""graftlint passes — one module per rule family (locks, tracepurity,
taxonomy, seams). Each exposes ``run(modules, cfg, ...) -> [Finding]``."""
