"""planprops pass — the plan verifier's rule table, machine-checked.

ISSUE 11 built plan/verify.py: a per-node-class rule table deriving and
checking every plan node's distribution/capacity properties. The table
is only a net if it is EXHAUSTIVE — a PlanNode subclass without a rule
row is a node class the verifier silently cannot check, which is
exactly the failure mode the verifier exists to close. Rules:

- ``planprops-unruled``: a class deriving from PlanNode (anywhere in
  the package — plan/nodes.py or an executor-private leaf like
  exec/tiled.py's _AccLeaf) with no ``@rule("<Class>")`` registration
  in plan/verify.py. Anchored at the class definition.
- ``planprops-orphan-rule``: a ``@rule("<Name>")`` registration naming
  no existing PlanNode subclass — a stale row that would mask the
  unruled finding when the class is later re-added under a different
  shape. Anchored at the registration.
- ``planprops-ckpt-mode``: exec/tiled.py ``CHECKPOINT_MODES`` and
  exec/recovery.py ``REPLACEABLE`` must cover each other BOTH ways —
  a checkpointing tiled mode without a declared degraded-mesh
  re-placement rule resumes into a wrong answer; a re-placement rule
  for a mode nobody checkpoints is a stale contract.

Cross-module rules only fire when BOTH sides of a contract are in the
linted set (a single-file invocation of plan/nodes.py must not claim
every class is unruled just because verify.py was not handed in).
"""

from __future__ import annotations

import ast

from cloudberry_tpu.lint.core import Finding


def _plannode_classes(tree: ast.AST) -> list[tuple[str, int]]:
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for b in node.bases:
            name = b.id if isinstance(b, ast.Name) \
                else getattr(b, "attr", "")
            if name == "PlanNode":
                out.append((node.name, node.lineno))
    return out


def _rule_rows(tree: ast.AST) -> list[tuple[str, int]]:
    """(class name, line) per ``@rule("Name", ...)`` registration in
    the verify module."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            if not isinstance(dec, ast.Call):
                continue
            fname = dec.func.id if isinstance(dec.func, ast.Name) \
                else getattr(dec.func, "attr", "")
            if fname != "rule":
                continue
            for a in dec.args:
                if isinstance(a, ast.Constant) and isinstance(a.value,
                                                              str):
                    out.append((a.value, dec.lineno))
    return out


def _const_tuple(tree: ast.AST, name: str):
    """(values, line) of a module-level ``NAME = ("a", "b", ...)``."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == name \
                and isinstance(node.value, (ast.Tuple, ast.List)):
            vals = [e.value for e in node.value.elts
                    if isinstance(e, ast.Constant)]
            return vals, node.lineno
    return None


def _const_dict_keys(tree: ast.AST, name: str):
    """(keys, line) of a module-level ``NAME = {"a": ..., ...}``."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == name \
                and isinstance(node.value, ast.Dict):
            keys = [k.value for k in node.value.keys
                    if isinstance(k, ast.Constant)]
            return keys, node.lineno
    return None


def run(modules, cfg) -> list[Finding]:
    findings: list[Finding] = []
    verify_mod = next((m for m in modules
                       if m.relpath.endswith(cfg.plan_verify_module)),
                      None)
    classes: list[tuple[str, int, str]] = []   # (name, line, relpath)
    for mod in modules:
        for name, line in _plannode_classes(mod.tree):
            classes.append((name, line, mod.relpath))

    if verify_mod is not None:
        rows = _rule_rows(verify_mod.tree)
        ruled = {n for n, _ in rows}
        class_names = {n for n, _, _ in classes}
        for name, line, rel in classes:
            if name not in ruled:
                findings.append(Finding(
                    "planprops-unruled", rel, line,
                    f"PlanNode subclass {name!r} has no @rule row in "
                    "plan/verify.py — the plan verifier cannot derive "
                    "or check its properties; add a rule (or it ships "
                    "unverifiable)"))
        # classes must be visible for the orphan direction too — a
        # verify.py-only invocation has no class inventory to judge by
        if classes:
            for name, line in rows:
                if name not in class_names:
                    findings.append(Finding(
                        "planprops-orphan-rule", verify_mod.relpath,
                        line,
                        f"@rule({name!r}) names no PlanNode subclass "
                        "— delete the stale row (it would mask the "
                        "unruled finding if the class returns under a "
                        "different shape)"))

    tiled = next((m for m in modules
                  if m.relpath.endswith(cfg.tiled_module)), None)
    recov = next((m for m in modules
                  if m.relpath.endswith(cfg.recovery_module)), None)
    if tiled is not None and recov is not None:
        ck = _const_tuple(tiled.tree, "CHECKPOINT_MODES")
        rp = _const_dict_keys(recov.tree, "REPLACEABLE")
        if ck is None:
            findings.append(Finding(
                "planprops-ckpt-mode", tiled.relpath, 1,
                "exec/tiled.py no longer declares CHECKPOINT_MODES — "
                "the checkpointing-mode contract is unverifiable"))
        elif rp is None:
            findings.append(Finding(
                "planprops-ckpt-mode", recov.relpath, 1,
                "exec/recovery.py no longer declares REPLACEABLE — "
                "the re-placement contract is unverifiable"))
        else:
            modes, ck_line = ck
            keys, rp_line = rp
            for m in modes:
                if m not in keys:
                    findings.append(Finding(
                        "planprops-ckpt-mode", tiled.relpath, ck_line,
                        f"tiled mode {m!r} checkpoints but has no "
                        "re-placement rule in exec/recovery.py "
                        "REPLACEABLE — a degraded-mesh resume would "
                        "be wrong"))
            for k in keys:
                if k not in modes:
                    findings.append(Finding(
                        "planprops-ckpt-mode", recov.relpath, rp_line,
                        f"REPLACEABLE declares mode {k!r} which no "
                        "tiled executor checkpoints (stale rule)"))
    return findings
