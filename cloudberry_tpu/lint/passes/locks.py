"""Lock-discipline pass — discovery, acquisition graph, cycles, guards.

Three rules:

- ``lock-order``: the static acquisition-order graph (edge A→B when B is
  acquired while A is held, including through calls) contains a cycle —
  two threads taking the cycle from different entry points can deadlock.
- ``lock-held-call``: a non-reentrant ``threading.Lock`` is (possibly
  transitively) re-acquired while already held on the same path — a
  guaranteed self-deadlock if the path executes.
- ``lock-unguarded``: a write to a MIXED-GUARD shared attribute outside
  any lock. An attribute of a lock-owning (or known-concurrent) class
  that is written under a lock somewhere and bare somewhere else has an
  inconsistent discipline; the bare site is the finding. Deliberate
  lock-free writes (single-owner-thread fields, monotonic flags) carry
  ``# graftlint: ignore[lock-unguarded]`` with a justification.

Discovery understands ``self.x = threading.Lock()/RLock()/Condition()``,
dataclass ``field(default_factory=threading.Lock)``, module-level locks,
and the ``Condition(self._lock)`` aliasing idiom (the condition IS the
lock). Cross-class edges resolve through parameter annotations and the
project wiring table (config.ATTR_CLASS_HINTS).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from cloudberry_tpu.lint.core import Finding

_LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "cond"}

_MUTATING_METHODS = frozenset({
    "append", "appendleft", "extend", "extendleft", "add", "discard",
    "remove", "pop", "popleft", "popitem", "update", "clear",
    "setdefault", "insert", "rotate",
})

# lock-object methods that are synchronization, not attribute mutation
_SYNC_METHODS = frozenset({
    "acquire", "release", "wait", "wait_for", "notify", "notify_all",
    "locked", "set", "is_set",
})


@dataclass
class LockDef:
    node: str            # canonical graph-node name, "Class.attr"
    kind: str            # lock | rlock | cond
    file: str
    line: int
    alias_of: str | None = None   # Condition(self._lock) → the lock


@dataclass
class MethodInfo:
    cls: str              # owning class name ("" for module functions)
    name: str
    file: str
    module: str           # module stem, for module-level lock scoping
    # every lock node acquired anywhere in the body (with line numbers)
    acquires: dict = field(default_factory=dict)   # node -> line
    # calls: (held-locks-tuple, callee-key, line)
    calls: list = field(default_factory=list)
    # self-attribute writes: attr -> [(guarded_by_tuple, line)]
    writes: dict = field(default_factory=dict)
    # intra-class call sites into this method: [held-locks-tuple]
    called_with: list = field(default_factory=list)


def _ctor_kind(call: ast.AST) -> str | None:
    """'lock'/'rlock'/'cond' when ``call`` constructs a threading
    primitive (directly, via an import dance, or via
    field(default_factory=...))."""
    if not isinstance(call, ast.Call):
        return None
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr in _LOCK_CTORS:
        return _LOCK_CTORS[f.attr]
    if isinstance(f, ast.Name) and f.id in _LOCK_CTORS:
        return _LOCK_CTORS[f.id]
    if isinstance(f, ast.Name) and f.id == "field":
        for kw in call.keywords:
            if kw.arg == "default_factory":
                v = kw.value
                if isinstance(v, ast.Attribute) \
                        and isinstance(v.value, ast.Name) \
                        and v.value.id == "threading" \
                        and v.attr in _LOCK_CTORS:
                    return _LOCK_CTORS[v.attr]
    return None


def _self_attr(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


class _Discovery(ast.NodeVisitor):
    """Collect lock definitions + class names for one module."""

    def __init__(self, mod, hints):
        self.mod = mod
        self.hints = hints
        self.module = mod.relpath.rsplit("/", 1)[-1][:-3]
        self.locks: dict[str, LockDef] = {}
        self.classes: set[str] = set()
        self._cls: str | None = None

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        prev, self._cls = self._cls, node.name
        self.classes.add(node.name)
        for stmt in node.body:
            # dataclass field declaration: x: threading.Lock = field(...)
            if isinstance(stmt, ast.AnnAssign) and stmt.value is not None \
                    and isinstance(stmt.target, ast.Name):
                kind = _ctor_kind(stmt.value)
                if kind:
                    self._add(f"{node.name}.{stmt.target.id}", kind,
                              stmt.lineno, stmt.value)
            # property aliasing a nested object's lock:
            #   @property
            #   def _rung_lock(self): return self._cache_scope.rung_lock
            if isinstance(stmt, ast.FunctionDef) and any(
                    isinstance(d, ast.Name) and d.id == "property"
                    for d in stmt.decorator_list) \
                    and len(stmt.body) == 1 \
                    and isinstance(stmt.body[0], ast.Return):
                v = stmt.body[0].value
                if isinstance(v, ast.Attribute):
                    inner = _self_attr(v.value)
                    if inner is not None:
                        cls = self.hints.get(inner)
                        if cls:
                            self.locks[f"{node.name}.{stmt.name}"] = \
                                LockDef(f"{node.name}.{stmt.name}",
                                        "lock", self.mod.relpath,
                                        stmt.lineno,
                                        alias_of=f"{cls}.{v.attr}")
        self.generic_visit(node)
        self._cls = prev

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        if self._cls is None:
            return  # module funcs hold no self-lock definitions
        for stmt in ast.walk(node):
            target = value = None
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                target, value = stmt.targets[0], stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                # annotated form: self._lock: threading.Lock = ...
                target, value = stmt.target, stmt.value
            if target is None:
                continue
            attr = _self_attr(target)
            kind = _ctor_kind(value)
            if attr and kind:
                self._add(f"{self._cls}.{attr}", kind, stmt.lineno,
                          value)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Assign(self, node: ast.Assign) -> None:
        # module-level lock: _lock = threading.Lock()
        if self._cls is None and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            kind = _ctor_kind(node.value)
            if kind:
                self._add(f"{self.module}.{node.targets[0].id}", kind,
                          node.lineno, node.value)

    def _add(self, name: str, kind: str, line: int, ctor: ast.AST) -> None:
        alias = None
        if kind == "cond" and isinstance(ctor, ast.Call) and ctor.args:
            attr = _self_attr(ctor.args[0])
            if attr and self._cls:
                alias = f"{self._cls}.{attr}"
        self.locks[name] = LockDef(name, kind, self.mod.relpath, line,
                                   alias)


class _MethodWalker:
    """Walk one function body tracking the held-lock stack."""

    def __init__(self, info: MethodInfo, ctx: "_Context"):
        self.info = info
        self.ctx = ctx
        self.held: list[str] = []
        # parameter annotations → class names (for conn.lock etc.)
        self.param_cls: dict[str, str] = {}
        # local aliases: lock = session._generic_lock; with lock: ...
        self.local_locks: dict[str, str] = {}

    # -------------------------------------------------- name resolution

    def lock_node(self, expr: ast.AST) -> str | None:
        """Resolve an expression used as a context manager (or
        acquire() target) to a canonical lock-graph node, or None."""
        attr = _self_attr(expr)
        if attr is not None and self.info.cls:
            return self.ctx.canonical(f"{self.info.cls}.{attr}")
        if isinstance(expr, ast.Name):
            if expr.id in self.local_locks:
                return self.local_locks[expr.id]
            return self.ctx.canonical(f"{self.info.module}.{expr.id}")
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name):
            base = expr.value.id
            cls = self.param_cls.get(base) \
                or self.ctx.hints.get(base)
            if cls:
                return self.ctx.canonical(f"{cls}.{expr.attr}")
        return None

    def callee_key(self, func: ast.AST) -> tuple[str, str] | None:
        """(class, method) or ("", module:function) for a call target we
        can resolve statically; None otherwise."""
        if isinstance(func, ast.Name):
            return self.ctx.resolve_func(self.info.module, func.id)
        if not isinstance(func, ast.Attribute):
            return None
        base = func.value
        attr = _self_attr(base)
        if attr is not None:
            # self.attr.m() — resolve attr's class via the wiring table
            cls = self.ctx.hints.get(attr)
            return (cls, func.attr) if cls else None
        if isinstance(base, ast.Name):
            if base.id == "self" and self.info.cls:
                return (self.info.cls, func.attr)
            cls = self.param_cls.get(base.id) or self.ctx.hints.get(base.id)
            if cls and cls in self.ctx.known_classes:
                return (cls, func.attr)
            if base.id in self.ctx.known_modules:
                # sharedcache.scope_for(...) — module-qualified call
                return ("", f"{base.id}:{func.attr}")
        return None

    # ---------------------------------------------------------- walking

    def walk_function(self, node: ast.FunctionDef) -> None:
        for a in list(node.args.args) + list(node.args.kwonlyargs):
            ann = a.annotation
            name = None
            if isinstance(ann, ast.Name):
                name = ann.id
            elif isinstance(ann, ast.Constant) and isinstance(ann.value,
                                                              str):
                name = ann.value.strip('"')
            elif isinstance(ann, ast.Attribute):
                name = ann.attr
            if name and name in self.ctx.known_classes:
                self.param_cls[a.arg] = name
        for stmt in node.body:
            self.visit(stmt)

    def visit(self, node: ast.AST) -> None:
        if isinstance(node, ast.With):
            self.visit_with(node)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # nested def/lambda: analyzed as part of this method (its
            # writes/acquisitions are the class's), but with a FRESH
            # held stack — the closure runs later, not under the locks
            # lexically held at its definition site
            saved, self.held = self.held, []
            body = node.body if isinstance(node.body, list) \
                else [node.body]
            for stmt in body:
                self.visit(stmt)
            self.held = saved
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            self.record_write(node)
            # track `lock = <something resolvable to a lock>` aliases
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, (ast.Attribute, ast.Name)):
                ln = self.lock_node(node.value)
                if ln is not None:
                    self.local_locks[node.targets[0].id] = ln
        if isinstance(node, ast.Call):
            self.record_call(node)
        for child in ast.iter_child_nodes(node):
            self.visit(child)

    def visit_with(self, node: ast.With) -> None:
        pushed = []
        for item in node.items:
            ln = self.lock_node(item.context_expr)
            if ln is not None:
                self.record_acquire(ln, item.context_expr.lineno)
                self.held.append(ln)
                pushed.append(ln)
            else:
                # a non-lock context manager may still CALL things
                self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        for _ in pushed:
            self.held.pop()

    # --------------------------------------------------------- recording

    def record_acquire(self, node_name: str, line: int) -> None:
        self.info.acquires.setdefault(node_name, line)
        for held in self.held:
            self.ctx.add_edge(held, node_name, self.info.file, line)
        if node_name in self.held:
            kind = self.ctx.kind_of(node_name)
            if kind == "lock":
                self.ctx.findings.append(Finding(
                    "lock-held-call", self.info.file, line,
                    f"non-reentrant lock {node_name} re-acquired while "
                    f"already held in {self.info.cls or self.info.module}"
                    f".{self.info.name} — self-deadlock"))

    def record_call(self, call: ast.Call) -> None:
        # container mutation counts as a write: self.X.append(...) /
        # self.X[k].pop() / self.stats.update(...)
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in _MUTATING_METHODS:
            base = call.func.value
            if isinstance(base, ast.Subscript):
                base = base.value
            attr = _self_attr(base)
            if attr is not None:
                self._note_write(attr, call.lineno)
        # manual acquire: self.X.acquire()
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr == "acquire":
            ln = self.lock_node(call.func.value)
            if ln is not None:
                self.record_acquire(ln, call.lineno)
                return
        key = self.callee_key(call.func)
        if key is not None:
            # calls on lock objects themselves are synchronization
            if isinstance(call.func, ast.Attribute) \
                    and call.func.attr in _SYNC_METHODS:
                base_ln = self.lock_node(call.func.value)
                if base_ln is not None:
                    return
            self.info.calls.append((tuple(self.held), key, call.lineno))

    def record_write(self, node: ast.AST) -> None:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for t in targets:
            attr = None
            if isinstance(t, ast.Tuple):
                for el in t.elts:
                    a = self._write_attr(el)
                    if a:
                        self._note_write(a, node.lineno)
                continue
            attr = self._write_attr(t)
            if attr:
                self._note_write(attr, node.lineno)

    def _write_attr(self, t: ast.AST) -> str | None:
        if isinstance(t, ast.Subscript):
            t = t.value
        return _self_attr(t)

    def _note_write(self, attr: str, line: int) -> None:
        self.info.writes.setdefault(attr, []).append(
            (tuple(self.held), line))


class _Context:
    def __init__(self, cfg):
        self.cfg = cfg
        self.hints = dict(cfg.attr_class_hints)
        self.locks: dict[str, LockDef] = {}
        self.known_classes: set[str] = set()
        self.known_modules: set[str] = set()
        # module-level function name -> {module stems defining it}
        self.func_homes: dict[str, set[str]] = {}
        self.graph: dict[str, dict[str, tuple]] = {}
        self.findings: list[Finding] = []
        self.methods: dict[tuple[str, str], MethodInfo] = {}

    def resolve_func(self, caller_module: str,
                     name: str) -> tuple[str, str] | None:
        """A bare f() call: same-module function first, else a uniquely
        named project function (imported via ``from x import f``);
        ambiguous names stay unresolved rather than guessing."""
        homes = self.func_homes.get(name, set())
        if caller_module in homes:
            return ("", f"{caller_module}:{name}")
        if len(homes) == 1:
            return ("", f"{next(iter(homes))}:{name}")
        return None

    def canonical(self, name: str) -> str | None:
        d = self.locks.get(name)
        if d is None:
            return None
        return d.alias_of if d.alias_of and d.alias_of in self.locks \
            else name

    def kind_of(self, name: str) -> str:
        d = self.locks.get(name)
        return d.kind if d else "lock"

    def add_edge(self, a: str, b: str, file: str, line: int) -> None:
        if a == b:
            return
        self.graph.setdefault(a, {}).setdefault(b, (file, line))


def run(modules, cfg, result) -> list[Finding]:
    ctx = _Context(cfg)

    # ---- phase 1: discovery across all modules
    discos = []
    for mod in modules:
        d = _Discovery(mod, ctx.hints)
        d.visit(mod.tree)
        discos.append((mod, d))
        ctx.known_classes |= d.classes
        ctx.known_modules.add(d.module)
        ctx.locks.update(d.locks)
        for item in mod.tree.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                ctx.func_homes.setdefault(item.name, set()).add(d.module)

    # ---- phase 2: per-method walks
    for mod, d in discos:
        module = d.module

        def walk_func(fn: ast.FunctionDef, cls: str) -> None:
            info = MethodInfo(cls, fn.name, mod.relpath, module)
            w = _MethodWalker(info, ctx)
            w.walk_function(fn)
            ctx.methods[(cls, fn.name) if cls else
                        ("", f"{module}:{fn.name}")] = info

        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        walk_func(item, node.name)
            elif isinstance(node, ast.Module):
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        walk_func(item, "")

    # ---- phase 3: transitive acquisition edges through calls
    eff_cache: dict[tuple, frozenset] = {}

    def effective_acquires(key, stack=()) -> frozenset:
        if key in eff_cache:
            return eff_cache[key]
        if key in stack or len(stack) > 12:
            return frozenset()
        info = ctx.methods.get(key)
        if info is None:
            return frozenset()
        out = set(info.acquires)
        for _held, callee, _line in info.calls:
            out |= effective_acquires(callee, stack + (key,))
        eff_cache[key] = frozenset(out)
        return eff_cache[key]

    for key, info in ctx.methods.items():
        for held, callee, line in info.calls:
            if not held:
                continue
            for lock in effective_acquires(callee):
                for h in held:
                    if h == lock:
                        if ctx.kind_of(lock) == "lock":
                            callee_name = ".".join(
                                k for k in callee if k) or str(callee)
                            ctx.findings.append(Finding(
                                "lock-held-call", info.file, line,
                                f"{lock} held here, and the call into "
                                f"{callee_name} can re-acquire it — "
                                "self-deadlock"))
                    else:
                        ctx.add_edge(h, lock, info.file, line)

    # record for callers (witness + --dot)
    result.lock_graph = ctx.graph
    result.lock_sites = {
        name: (d.file, d.line, d.kind, d.alias_of)
        for name, d in ctx.locks.items()}

    # ---- phase 4: cycle detection (iterative DFS, deterministic order)
    color: dict[str, int] = {}
    stack_path: list[str] = []
    cycles: list[list[str]] = []

    def dfs(n: str) -> None:
        color[n] = 1
        stack_path.append(n)
        for m in sorted(ctx.graph.get(n, ())):
            if color.get(m, 0) == 0:
                dfs(m)
            elif color.get(m) == 1:
                i = stack_path.index(m)
                cyc = stack_path[i:] + [m]
                cycles.append(cyc)
        stack_path.pop()
        color[n] = 2

    for n in sorted(ctx.graph):
        if color.get(n, 0) == 0:
            dfs(n)
    seen_cycles = set()
    for cyc in cycles:
        key = frozenset(cyc)
        if key in seen_cycles:
            continue
        seen_cycles.add(key)
        a, b = cyc[0], cyc[1]
        file, line = ctx.graph[a][b]
        ctx.findings.append(Finding(
            "lock-order", file, line,
            "lock acquisition cycle (potential deadlock): "
            + " -> ".join(cyc)))

    # ---- phase 5: mixed-guard unguarded writes
    lock_owning = {name.split(".")[0] for name in ctx.locks
                   if "." in name}
    audited = (lock_owning & ctx.known_classes) | (
        set(cfg.concurrent_classes) & ctx.known_classes)
    # inherited guards: a private helper only ever called under a lock
    # inherits that guard at its call sites
    inherited: dict[tuple, frozenset] = {}
    init_only: set[tuple] = set()
    # fixpoint over a few rounds so guards propagate through helper
    # chains (pick → _group_locked → _add_group)
    for _round in range(4):
        call_sites: dict[tuple, list] = {}
        for key, info in ctx.methods.items():
            caller_key = (info.cls, info.name)
            inh = inherited.get(caller_key, frozenset())
            for held, callee, _line in info.calls:
                if callee[0] and callee[0] == info.cls:
                    call_sites.setdefault(callee, []).append(
                        (info.name, frozenset(held) | inh,
                         caller_key in init_only))
        changed = False
        for key, sites in call_sites.items():
            cls, name = key
            if not name.startswith("_"):
                continue
            # construction is single-threaded: an __init__ call site is
            # not evidence of an unguarded concurrent path
            concurrent_sites = [
                h for caller, h, caller_init in sites
                if caller not in ("__init__", "__post_init__")
                and not caller_init]
            if concurrent_sites:
                guard = frozenset.intersection(*concurrent_sites)
                if guard and inherited.get(key) != guard:
                    inherited[key] = guard
                    changed = True
            elif sites and key not in init_only:
                # only ever called during construction: everything it
                # writes is pre-publication
                init_only.add(key)
                changed = True
        if not changed:
            break

    lock_attr_names = {name.split(".", 1)[1] for name in ctx.locks
                       if "." in name}
    writes_by_attr: dict[tuple, list] = {}
    for key, info in ctx.methods.items():
        if info.cls not in audited:
            continue
        if info.name in ("__init__", "__post_init__", "__del__",
                         "__enter__"):
            continue
        if (info.cls, info.name) in init_only:
            continue  # construction helpers write pre-publication state
        inh = inherited.get((info.cls, info.name), frozenset())
        for attr, sites in info.writes.items():
            if attr in lock_attr_names:
                continue  # installing/replacing the lock object itself
            for held, line in sites:
                guards = frozenset(held) | inh
                writes_by_attr.setdefault((info.cls, attr), []).append(
                    (guards, info.file, line, info.name))
    for (cls, attr), sites in sorted(writes_by_attr.items()):
        guarded = [s for s in sites if s[0]]
        bare = [s for s in sites if not s[0]]
        if not guarded or not bare:
            continue
        lock_names = sorted({ln for s in guarded for ln in s[0]})
        for _g, file, line, meth in bare:
            ctx.findings.append(Finding(
                "lock-unguarded", file, line,
                f"{cls}.{attr} is written under {'/'.join(lock_names)} "
                f"elsewhere but bare here (in {meth}) — racy "
                "read-modify-write or torn publish"))

    return ctx.findings
