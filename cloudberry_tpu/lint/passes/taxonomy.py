"""Taxonomy-integrity pass — the wire-error contract, machine-checked.

The serving layer stamps every wire error with ``retryable`` and the
client trusts the taxonomy BY NAME (lifecycle._RETRYABLE_NAMES); these
invariants are what makes client auto-retry safe. Rules:

- ``tax-unstamped``: an error response dict literal (``"ok": False``)
  in a wire module without an explicit ``"retryable"`` key. An omitted
  stamp silently defaults to non-retryable on the client — a load-
  shedding refusal that forgets the stamp strands clients that should
  have failed over.
- ``tax-name-unknown``: a name in ``_RETRYABLE_NAMES`` (or the client's
  ``_CONN_SEVERING``) with no class definition anywhere in scope — the
  by-name contract would never match a live exception, so the retry
  silently stops applying.
- ``tax-retryable-mismatch``: a StatementError subclass whose
  ``retryable`` class attribute disagrees with its membership in
  ``_RETRYABLE_NAMES`` — the two classifier channels (isinstance walk
  and name registry) must give one verdict.
- ``tax-retryable-missing``: a StatementError subclass that never sets
  ``retryable`` explicitly — inheriting the default silently flips
  semantics when the hierarchy is refactored.
"""

from __future__ import annotations

import ast

from cloudberry_tpu.lint.core import Finding


def _dict_keys(node: ast.Dict) -> dict[str, ast.AST]:
    out = {}
    for k, v in zip(node.keys, node.values):
        if isinstance(k, ast.Constant) and isinstance(k.value, str):
            out[k.value] = v
    return out


def _str_set_literal(node: ast.AST) -> set[str] | None:
    """The string elements of a frozenset({...}) / {...} / (...) literal."""
    if isinstance(node, ast.Call) and node.args:
        f = node.func
        name = f.id if isinstance(f, ast.Name) else getattr(f, "attr", "")
        if name in ("frozenset", "set", "tuple"):
            node = node.args[0]
    if isinstance(node, (ast.Set, ast.Tuple, ast.List)):
        out = set()
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                out.add(el.value)
        return out
    return None


def run(modules, cfg) -> list[Finding]:
    from cloudberry_tpu.lint.config import RETRYABLE_NAMES_CONST

    findings: list[Finding] = []

    # ---- collect: every class name defined in scope, the taxonomy
    # module's name registry, StatementError subclasses + their stamps
    all_classes: set[str] = set()
    retryable_names: set[str] = set()
    retryable_src: tuple[str, int] | None = None
    conn_severing: dict[str, tuple[str, int]] = {}
    stmt_err_classes: list[tuple] = []  # (name, bases, stamp, file, line)

    for mod in modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                all_classes.add(node.name)
                # attribute bases (lifecycle.StatementError) count by
                # their terminal name — the subtree rules must not be
                # dodged by importing the module instead of the class
                bases = [b.id if isinstance(b, ast.Name) else b.attr
                         for b in node.bases
                         if isinstance(b, (ast.Name, ast.Attribute))]
                stamp = None
                for stmt in node.body:
                    tgt = None
                    if isinstance(stmt, ast.Assign) \
                            and len(stmt.targets) == 1:
                        tgt, val = stmt.targets[0], stmt.value
                    elif isinstance(stmt, ast.AnnAssign) \
                            and stmt.value is not None:
                        # annotated form: retryable: bool = True
                        tgt, val = stmt.target, stmt.value
                    if isinstance(tgt, ast.Name) \
                            and tgt.id == "retryable" \
                            and isinstance(val, ast.Constant):
                        stamp = bool(val.value)
                stmt_err_classes.append(
                    (node.name, bases, stamp, mod.relpath, node.lineno))
            elif isinstance(node, ast.Assign) \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                tname = node.targets[0].id
                if tname == RETRYABLE_NAMES_CONST \
                        and mod.relpath.endswith(cfg.taxonomy_module):
                    vals = _str_set_literal(node.value)
                    if vals is not None:
                        retryable_names = vals
                        retryable_src = (mod.relpath, node.lineno)
                elif tname == "_CONN_SEVERING":
                    vals = _str_set_literal(node.value)
                    if vals is not None:
                        for v in vals:
                            conn_severing[v] = (mod.relpath, node.lineno)

    # ---- rule: names must round-trip to real classes
    if retryable_src is not None:
        for name in sorted(retryable_names):
            if name not in all_classes:
                findings.append(Finding(
                    "tax-name-unknown", retryable_src[0],
                    retryable_src[1],
                    f"_RETRYABLE_NAMES entry {name!r} has no class "
                    "definition in scope — the by-name retry contract "
                    "can never match it"))
    for name, (file, line) in sorted(conn_severing.items()):
        if retryable_names and name not in retryable_names:
            findings.append(Finding(
                "tax-name-unknown", file, line,
                f"_CONN_SEVERING entry {name!r} is not in "
                "_RETRYABLE_NAMES — a severing refusal the client "
                "will not retry"))

    # ---- rule: StatementError subtree consistency with the registry
    base_of = {name: set(bases)
               for name, bases, _s, _f, _l in stmt_err_classes}

    def descends_stmt_error(name: str, seen=()) -> bool:
        if name == "StatementError":
            return True
        if name in seen:
            return False
        return any(descends_stmt_error(b, seen + (name,))
                   for b in base_of.get(name, ()))

    if retryable_names:
        for name, bases, stamp, file, line in stmt_err_classes:
            if name == "StatementError" \
                    or not descends_stmt_error(name):
                continue
            if stamp is None:
                findings.append(Finding(
                    "tax-retryable-missing", file, line,
                    f"StatementError subclass {name} never sets "
                    "``retryable`` explicitly — the wire verdict would "
                    "silently follow whatever the hierarchy inherits"))
                continue
            in_registry = name in retryable_names
            if stamp != in_registry:
                findings.append(Finding(
                    "tax-retryable-mismatch", file, line,
                    f"{name}.retryable={stamp} but the name "
                    f"{'is' if in_registry else 'is NOT'} in "
                    "_RETRYABLE_NAMES — the isinstance and by-name "
                    "classifier channels disagree"))

    # ---- rule: wire error dicts carry the explicit stamp
    for mod in modules:
        if not any(mod.relpath.endswith(w) for w in cfg.wire_modules):
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Dict):
                continue
            keys = _dict_keys(node)
            ok = keys.get("ok")
            if ok is None or not isinstance(ok, ast.Constant) \
                    or ok.value is not False:
                continue
            if "retryable" not in keys:
                findings.append(Finding(
                    "tax-unstamped", mod.relpath, node.lineno,
                    "wire error response without an explicit "
                    "\"retryable\" stamp — the client defaults the "
                    "verdict to non-retryable; stamp it (False is a "
                    "decision, omission is an accident)"))
    return findings
