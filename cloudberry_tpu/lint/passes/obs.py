"""Observability-integrity pass — the telemetry plane, machine-checked.

ISSUE 9 made obs/metrics.MetricsRegistry the ONE home for engine
counters (StatementLog.counters is a view into it) and made the wire's
``meta`` verb list the observability contract thin clients discover.
Rules:

- ``obs-counter-home``: a ``collections.Counter(...)`` instantiation
  outside ``obs/`` — a new ad-hoc counter store would fork the metric
  plane (no exposition, no ``meta "metrics"`` visibility, no bound).
  Count on the registry (``stmt_log.bump`` / ``registry.bump``) or a
  plain dict with an explicit snapshot surface instead.
- ``obs-gauge-home``: a ``gauge(...)``/``gauge_max(...)`` write outside
  ``obs/`` (ISSUE 12, same contract as ``obs-counter-home``). Gauges
  are point-in-time values: one scattered across the engine goes stale
  invisibly the day its call site stops running. They live in
  obs/capacity.py's read-time refresh (or another obs/ module), where
  staleness is structurally impossible.
- ``obs-meta-verbs``: ``serve/meta.py``'s describe() docstring lists
  its kinds ("Kinds: a | b | ..."); the implemented ``kind == "..."``
  comparisons must match the documented list BOTH ways — an
  undocumented verb is invisible to clients, a documented-but-missing
  one is a lie in the contract.
"""

from __future__ import annotations

import ast
import re

from cloudberry_tpu.lint.core import Finding


def _counter_calls(tree: ast.AST):
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = f.id if isinstance(f, ast.Name) else getattr(f, "attr", "")
        if name == "Counter":
            yield node


def _gauge_calls(tree: ast.AST):
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = f.id if isinstance(f, ast.Name) else getattr(f, "attr", "")
        if name in ("gauge", "gauge_max"):
            yield node


def _documented_kinds(doc: str) -> set[str] | None:
    """The 'Kinds: a | b | c.' list from describe()'s docstring."""
    m = re.search(r"Kinds:\s*(.*?)\.", doc, flags=re.S)
    if m is None:
        return None
    return {w.strip() for w in m.group(1).split("|") if w.strip()}


def _implemented_kinds(fn: ast.FunctionDef) -> set[str]:
    out = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Compare):
            continue
        left = node.left
        if not (isinstance(left, ast.Name) and left.id == "kind"):
            continue
        for op, comp in zip(node.ops, node.comparators):
            if isinstance(op, ast.Eq) and isinstance(comp, ast.Constant) \
                    and isinstance(comp.value, str):
                out.add(comp.value)
    return out


def run(modules, cfg) -> list[Finding]:
    findings: list[Finding] = []
    for mod in modules:
        parts = mod.relpath.replace("\\", "/").split("/")
        in_obs = "obs" in parts[:-1]
        if not in_obs:
            for call in _counter_calls(mod.tree):
                findings.append(Finding(
                    "obs-counter-home", mod.relpath, call.lineno,
                    "collections.Counter instantiated outside obs/ — "
                    "engine counters live on the MetricsRegistry "
                    "(stmt_log.bump / registry.bump); an ad-hoc Counter "
                    "is invisible to meta \"metrics\" and the "
                    "Prometheus exposition"))
            for call in _gauge_calls(mod.tree):
                findings.append(Finding(
                    "obs-gauge-home", mod.relpath, call.lineno,
                    "gauge written outside obs/ — gauges are "
                    "point-in-time values that go stale invisibly when "
                    "scattered; set them from obs/capacity.py's "
                    "read-time refresh (or another obs/ module)"))
        if mod.relpath.endswith(cfg.meta_module):
            findings += _check_meta_verbs(mod)
    return findings


def _check_meta_verbs(mod) -> list[Finding]:
    findings: list[Finding] = []
    describe = next(
        (n for n in ast.walk(mod.tree)
         if isinstance(n, ast.FunctionDef) and n.name == "describe"),
        None)
    if describe is None:
        return findings
    doc = ast.get_docstring(describe) or ""
    documented = _documented_kinds(doc)
    if documented is None:
        return [Finding(
            "obs-meta-verbs", mod.relpath, describe.lineno,
            "describe() has no 'Kinds: ...' docstring list — the meta "
            "verb contract must be documented")]
    implemented = _implemented_kinds(describe)
    for kind in sorted(implemented - documented):
        findings.append(Finding(
            "obs-meta-verbs", mod.relpath, describe.lineno,
            f"meta kind {kind!r} is implemented but missing from "
            "describe()'s documented Kinds list"))
    for kind in sorted(documented - implemented):
        findings.append(Finding(
            "obs-meta-verbs", mod.relpath, describe.lineno,
            f"meta kind {kind!r} is documented but not implemented "
            "(no `kind == ...` branch)"))
    return findings
