"""Seam-integrity pass — FAULT_POINT inventory sync + cancel seams.

The fault-injection seams are load-bearing test surface: chaos soaks arm
them by NAME, so a renamed call site silently stops being covered. The
inventory (utils/faultinject.INVENTORY) is the contract of record; this
pass keeps it honest in both directions. Rules:

- ``seam-unknown``: a ``fault_point("name")`` call site whose name is
  not in the inventory — the seam exists but no soak can know about it.
- ``seam-stale``: an inventory entry with no remaining call site — a
  test arming it would silently never fire.
- ``seam-dynamic``: a ``fault_point(expr)`` call with a non-literal
  name — unverifiable statically, and unarmable by a fixed soak config.
- ``seam-loop``: an unbounded ``while True`` tile/retry loop (in
  config.SEAM_LOOP_MODULES) with no cancellation seam in its body —
  cooperative cancellation has a blind spot exactly where statements
  spend their time. Pure structural walks (no calls beyond a small
  builtin whitelist) are exempt: they terminate with the plan tree.
"""

from __future__ import annotations

import ast

from cloudberry_tpu.lint.core import Finding

# calls a bounded structural walk may make (plan-tree descent loops)
_WALK_OK_CALLS = frozenset({
    "isinstance", "len", "id", "append", "add", "index", "extend",
    "pop", "insert", "tuple", "list", "set", "str", "int", "getattr",
    "hasattr", "max", "min", "abs",
})


def _call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def run(modules, cfg) -> list[Finding]:
    findings: list[Finding] = []

    # ---- collect fault_point call sites + the inventory literal
    sites: dict[str, list[tuple[str, int]]] = {}
    inventory: set[str] | None = None
    inv_src: tuple[str, int] | None = None
    for mod in modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) \
                    and _call_name(node) == "fault_point" and node.args:
                # skip the declaration itself (def fault_point is not a
                # Call; recursive mentions inside faultinject are real)
                arg = node.args[0]
                if isinstance(arg, ast.Constant) \
                        and isinstance(arg.value, str):
                    sites.setdefault(arg.value, []).append(
                        (mod.relpath, node.lineno))
                else:
                    findings.append(Finding(
                        "seam-dynamic", mod.relpath, node.lineno,
                        "fault_point() with a non-literal name — the "
                        "inventory cannot verify it and soaks cannot "
                        "arm it by name"))
            elif isinstance(node, ast.Assign) \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == "INVENTORY" \
                    and mod.relpath.endswith(cfg.faultinject_module):
                from cloudberry_tpu.lint.passes.taxonomy import (
                    _str_set_literal,
                )

                vals = _str_set_literal(node.value)
                if vals is not None:
                    inventory = vals
                    inv_src = (mod.relpath, node.lineno)

    if inventory is not None:
        for name in sorted(sites):
            if name not in inventory:
                file, line = sites[name][0]
                findings.append(Finding(
                    "seam-unknown", file, line,
                    f"fault_point({name!r}) is not in the faultinject "
                    "INVENTORY — add it so soaks and the chaos ladder "
                    "can arm it"))
        for name in sorted(inventory - set(sites)
                           - set(cfg.inventory_extra_ok)):
            findings.append(Finding(
                "seam-stale", inv_src[0], inv_src[1],
                f"INVENTORY entry {name!r} has no fault_point call "
                "site — a soak arming it would never fire; delete or "
                "re-declare the seam"))
    elif any(mod.relpath.endswith(cfg.faultinject_module)
             for mod in modules):
        for mod in modules:
            if mod.relpath.endswith(cfg.faultinject_module):
                findings.append(Finding(
                    "seam-stale", mod.relpath, 1,
                    "faultinject module has no INVENTORY literal — the "
                    "seam contract has no record"))

    # ---- unbounded loops must poll a cancel seam
    for mod in modules:
        if not any(mod.relpath.endswith(s)
                   for s in cfg.seam_loop_modules):
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.While):
                continue
            test = node.test
            unbounded = isinstance(test, ast.Constant) \
                and test.value is True
            if not unbounded:
                continue
            has_seam = False
            saw_call = False
            only_walk_calls = True
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    saw_call = True
                    name = _call_name(sub)
                    if name in cfg.cancel_seam_calls:
                        has_seam = True
                        break
                    if name not in _WALK_OK_CALLS:
                        only_walk_calls = False
            # the walk exemption needs EVIDENCE of a walk (at least one
            # whitelisted call): a call-free while-True is a busy spin,
            # exactly what the rule exists to catch
            if has_seam or (saw_call and only_walk_calls):
                continue
            findings.append(Finding(
                "seam-loop", mod.relpath, node.lineno,
                "unbounded while-True loop without a cancellation seam "
                "(check_cancel/_raise_tile_checks) — a cancelled or "
                "over-deadline statement cannot stop here"))
    return findings
