"""Runtime lock-order witness — the dynamic half of the lock pass.

The static pass proves the acquisition graph it can SEE is acyclic; this
module asserts the declared order on paths the AST cannot see (callbacks,
locals aliasing locks, cross-thread handoffs). ``install()`` patches
``threading.Lock``/``RLock`` with a factory that, per allocation, walks
the creation stack to the first engine frame and matches it against the
static lock table (the same discovery the lint pass runs): locks created
at KNOWN sites come back wrapped with their declared rank
(config.WITNESS_ORDER); everything else stays a raw primitive — zero
overhead outside the engine's own locks.

Each wrapped acquisition checks the per-thread held stack: acquiring a
lock whose rank is LOWER OR EQUAL to the top of the stack (other than
re-entering the very same object) is an order violation, recorded in
``violations()`` (and raised immediately in ``strict`` mode). The
lifecycle/tenancy/shared-cache suites enable the witness around their
tests and assert the violation list stays empty.

``threading.Condition`` needs no patch: a Condition built over a wrapped
lock synchronizes THROUGH the wrapper (acquire/release fall back to the
proxy's methods), and a bare ``Condition()`` builds its internal RLock
via the patched ``threading.RLock`` — so condition waits release and
re-acquire under witness too.
"""

from __future__ import annotations

import contextlib
import os
import threading
from dataclasses import dataclass


@dataclass
class Violation:
    thread: str
    holding: tuple          # (name, rank) stack at the time
    acquiring: str
    rank: int
    site: str               # file:line of the acquiring call

    def render(self) -> str:
        held = " > ".join(f"{n}(r{r})" for n, r in self.holding)
        return (f"[{self.thread}] acquired {self.acquiring}"
                f"(r{self.rank}) while holding {held} at {self.site}")


class _State:
    def __init__(self):
        self.installed = False
        self.depth = 0          # nested install() refcount
        self.strict = False
        self.real_lock = None
        self.real_rlock = None
        self.site_map: dict[tuple, tuple] = {}   # (file,line)->(name,rank)
        # module-level declared locks ("faultinject._lock"): created at
        # IMPORT time, usually before install() patches threading — they
        # are wrapped in place by swapping the module attribute
        self.module_locks: list[tuple] = []      # (name, rank, stem, attr)
        self.wrapped_module_attrs: list[tuple] = []  # (module, attr, raw)
        self.violations: list[Violation] = []
        self.vlock = threading.Lock()  # guards the violations list only
        self.tls = threading.local()


_state = _State()


def _held_stack():
    st = getattr(_state.tls, "stack", None)
    if st is None:
        st = _state.tls.stack = []
    return st


class WitnessedLock:
    """Order-checking proxy over a real Lock/RLock. Exposes the full
    primitive protocol (acquire/release/locked/context manager) so
    Condition(lock=proxy) and bare with-blocks both ride through it."""

    __slots__ = ("_real", "name", "rank", "_reentrant")

    def __init__(self, real, name: str, rank: int, reentrant: bool):
        self._real = real
        self.name = name
        self.rank = rank
        self._reentrant = reentrant

    # ------------------------------------------------------------ checks

    def _check_order(self) -> None:
        stack = _held_stack()
        if not stack:
            return
        if self._reentrant and any(obj is self for _n, _r, obj in stack):
            return  # re-entry of a held RLock is not an ordering event
        # check against EVERY held lock, not just the top: after a
        # first violation the stack is no longer monotonic, and a
        # top-only comparison would swallow the rest of the cascade
        offending = any(r >= self.rank for _n, r, obj in stack
                        if obj is not self)
        if offending:
            v = Violation(
                thread=threading.current_thread().name,
                holding=tuple((n, r) for n, r, _o in stack),
                acquiring=self.name, rank=self.rank,
                site=_caller_site())
            with _state.vlock:
                _state.violations.append(v)
            if _state.strict:
                raise AssertionError("lock-order violation: " + v.render())

    # --------------------------------------------------------- primitive

    def acquire(self, blocking=True, timeout=-1):
        self._check_order()
        got = self._real.acquire(blocking, timeout)
        if got:
            _held_stack().append((self.name, self.rank, self))
        return got

    def release(self):
        stack = _held_stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][2] is self:
                del stack[i]
                break
        self._real.release()

    def locked(self):
        return self._real.locked()

    # --------------------------- threading.Condition integration
    # Condition(lock=proxy) and Condition() (whose internal RLock the
    # patched factory wrapped) synchronize through these; without them
    # Condition falls back to acquire(0) probing, which misreads a HELD
    # re-entrant lock as un-owned (RLock.acquire(0) succeeds for the
    # owning thread) and raises "cannot notify on un-acquired lock".

    def _is_owned(self):
        if hasattr(self._real, "_is_owned"):
            return self._real._is_owned()
        if self._real.acquire(False):
            self._real.release()
            return False
        return True

    def _release_save(self):
        if hasattr(self._real, "_release_save"):
            state = self._real._release_save()
        else:
            self._real.release()
            state = None
        stack = _held_stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][2] is self:
                del stack[i]
                break
        return state

    def _acquire_restore(self, state):
        if hasattr(self._real, "_acquire_restore"):
            self._real._acquire_restore(state)
        else:
            self._real.acquire()
        _held_stack().append((self.name, self.rank, self))

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<WitnessedLock {self.name} r{self.rank} {self._real!r}>"


def _caller_site() -> str:
    import sys

    try:
        f = sys._getframe(3)
    except ValueError:
        return "?"
    for _ in range(12):
        if f is None:
            break
        fn = f.f_code.co_filename
        if "cloudberry_tpu" in fn and "lint" not in fn.split(os.sep)[-2:]:
            rel = fn[fn.rfind("cloudberry_tpu"):].replace(os.sep, "/")
            return f"{rel}:{f.f_lineno}"
        f = f.f_back
    return "?"


def _creation_site_key():
    """Walk the creation stack (past threading.py / dataclasses) to the
    first engine frame; returns (relpath, lineno) to match the static
    lock table."""
    import sys

    f = sys._getframe(2)
    for _ in range(16):
        if f is None:
            return None
        fn = f.f_code.co_filename
        base = os.path.basename(fn)
        if "cloudberry_tpu" in fn and base != "witness.py" \
                and "threading" not in base:
            rel = fn[fn.rfind("cloudberry_tpu"):].replace(os.sep, "/")
            return (rel, f.f_lineno)
        f = f.f_back
    return None


def _factory(real_ctor, reentrant: bool):
    def make():
        real = real_ctor()
        key = _creation_site_key()
        if key is None:
            return real
        hit = _state.site_map.get(key)
        if hit is None:
            return real
        name, rank = hit
        return WitnessedLock(real, name, rank, reentrant)

    return make


def _build_site_map() -> dict[tuple, tuple]:
    """Static lock discovery (the lint pass) → creation-site map with
    declared ranks. Aliased locks (Condition(self._lock)) inherit their
    canonical lock's rank; undeclared locks stay unwitnessed. Declared
    MODULE-LEVEL locks are also collected for in-place wrapping (their
    creation ran at import, before any install())."""
    import cloudberry_tpu
    from cloudberry_tpu.lint.config import LintConfig, witness_ranks
    from cloudberry_tpu.lint.core import run_lint

    pkg_dir = os.path.dirname(os.path.abspath(cloudberry_tpu.__file__))
    result = run_lint([pkg_dir], LintConfig())
    ranks = witness_ranks()
    out: dict[tuple, tuple] = {}
    _state.module_locks = []
    for name, (file, line, _kind, alias_of) in result.lock_sites.items():
        rank = ranks.get(name)
        if rank is None and alias_of is not None:
            rank = ranks.get(alias_of)
        if rank is None:
            continue
        # file is relative to the package parent; creation frames give
        # paths containing "cloudberry_tpu/..."
        out[(file, line)] = (name, rank)
        stem, attr = name.split(".", 1)
        if file.rsplit("/", 1)[-1] == f"{stem}.py" and "." not in attr:
            # "<modstem>.<attr>" where the stem IS the defining file:
            # a module-global lock, wrappable by attribute swap
            _state.module_locks.append((name, rank, stem, attr))
    return out


def _wrap_module_locks() -> None:
    """Swap already-created module-global locks (faultinject._lock,
    sharedcache._tier_lock) for witnessed proxies: every use site reads
    the module global at acquisition time, so the swap takes effect
    immediately. Modules imported AFTER install() need no swap — their
    creation goes through the patched factory."""
    import sys

    for name, rank, stem, attr in _state.module_locks:
        for mkey, module in list(sys.modules.items()):
            if not mkey.startswith("cloudberry_tpu") \
                    or mkey.rsplit(".", 1)[-1] != stem:
                continue
            raw = getattr(module, attr, None)
            if raw is None or isinstance(raw, WitnessedLock):
                continue
            if not (hasattr(raw, "acquire") and hasattr(raw, "release")):
                continue
            setattr(module, attr, WitnessedLock(raw, name, rank,
                                                reentrant=False))
            _state.wrapped_module_attrs.append((module, attr, raw))


def install(strict: bool = False) -> None:
    """Enable the witness: new engine locks created at declared sites
    come back wrapped. REFCOUNTED: nested installs (a test calling
    install() inside a suite whose fixture already did) stack, and only
    the matching outermost ``uninstall()`` restores threading — an
    inner scope can never silently disarm an outer one. Only locks
    created AFTER the first install are witnessed — suites install it
    before building their servers/schedulers."""
    if _state.installed:
        _state.depth += 1
        _state.strict = strict
        return
    if not _state.site_map:
        # one static discovery per process: the lock table only changes
        # with the source tree, and repeated installs (per-suite test
        # fixtures) must not pay the scan again
        _state.site_map = _build_site_map()
    _state.real_lock = threading.Lock
    _state.real_rlock = threading.RLock
    _state.strict = strict
    _state.violations = []
    threading.Lock = _factory(_state.real_lock, reentrant=False)
    threading.RLock = _factory(_state.real_rlock, reentrant=True)
    _wrap_module_locks()
    _state.installed = True
    _state.depth = 1


def uninstall() -> None:
    if not _state.installed:
        return
    _state.depth -= 1
    if _state.depth > 0:
        return  # an outer watching()/install scope is still active
    threading.Lock = _state.real_lock
    threading.RLock = _state.real_rlock
    for module, attr, raw in _state.wrapped_module_attrs:
        # only restore what we put there (a reload may have replaced it)
        if isinstance(getattr(module, attr, None), WitnessedLock):
            setattr(module, attr, raw)
    _state.wrapped_module_attrs = []
    # module globals wrapped by the FACTORY (module imported after
    # install) unwrap here too, so no proxy outlives the session
    import sys

    for _name, _rank, stem, attr in _state.module_locks:
        for mkey, module in list(sys.modules.items()):
            if mkey.startswith("cloudberry_tpu") \
                    and mkey.rsplit(".", 1)[-1] == stem:
                cur = getattr(module, attr, None)
                if isinstance(cur, WitnessedLock):
                    setattr(module, attr, cur._real)
    _state.installed = False


def violations() -> list[Violation]:
    with _state.vlock:
        return list(_state.violations)


def reset_violations() -> None:
    with _state.vlock:
        _state.violations.clear()


def witnessed_site_count() -> int:
    """How many declared lock sites the witness knows (0 means the
    static discovery failed — suites assert this is non-zero so the
    witness can never silently watch nothing)."""
    return len(_state.site_map)


@contextlib.contextmanager
def watching(strict: bool = False):
    """The test-suite harness in one place: install, watch, and FAIL on
    any recorded violation at exit. Suites wrap their module in
    ``with witness.watching(): yield`` from an autouse fixture.

    Known limit: locks built by dataclass ``field(default_factory=
    threading.Lock)`` bind the REAL constructor at class-definition
    time (import), so they are never wrapped — keep such locks out of
    WITNESS_ORDER (the static pass still audits them)."""
    install(strict=strict)
    reset_violations()
    assert witnessed_site_count() > 0, \
        "witness site map is empty — static lock discovery failed"
    try:
        yield
    finally:
        vs = violations()
        uninstall()
        reset_violations()
        assert not vs, "lock-order violations:\n" + "\n".join(
            v.render() for v in vs)
