"""Socket serving layer — the engine as a database, not a library.

The reference's serving surface is the libpq wire protocol into a
PER-CONNECTION backend process (exec_simple_query,
src/backend/tcop/postgres.c:506, 1655) over shared storage. Here the same
shape: when the server runs over a durable store (config.storage.root),
every connection gets its OWN Session — the backend analog — over the
shared TableStore, so wire transactions (BEGIN/COMMIT/ROLLBACK) ride the
storage layer's multi-session OCC exactly like in-process sessions do, and
a dropped connection rolls its open transaction back (the backend-exit
abort). Resource governance stays engine-wide: every connection session
shares the server's admission gate, resource queues, and vmem tracker, and
parallel-retrieve-cursor endpoints live in a server-shared registry so a
cursor declared on one connection drains from any other (the shmem
endpoint directory, cdbendpoint.c).

Without a store there is nothing durable for backends to share, so all
connections fall back to ONE shared Session: reads run concurrently,
catalog mutations serialize behind a WRITER-PRIORITY rw-lock (a stream of
readers can never starve DDL/DML), and wire transactions are refused —
one client's BEGIN would absorb other clients' autocommit writes.

Clients speak a newline-delimited JSON protocol:

    → {"sql": "select ..."}
    ← {"ok": true, "columns": [...], "rows": [[...]], "rowcount": N}
    ← {"ok": true, "status": "CREATE TABLE t"}          (DDL/DML)
    ← {"ok": false, "error": "...", "etype": "BindError"}
"""

from __future__ import annotations

import json
import socket
import socketserver
import threading
from typing import Optional

import numpy as np

from cloudberry_tpu.sql.classify import read_only as _is_read  # noqa: E402
# shared classifier (sql/classify.py): the standby gate, the rw-lock
# choice, and the Session retry policy must agree on what a "read" is —
# notably `select nextval(...)` is a WRITE (plan-time sequence allocation)

_TXN_STARTERS = ("begin", "commit", "rollback", "abort", "start", "end")


def _first_word(sql: str) -> str:
    s = sql.lstrip()
    if s.startswith("("):
        return "("
    head = s.split(None, 1)
    return head[0].lower() if head else ""


class _RWLock:
    """Readers-writer lock with WRITER PRIORITY: reads share, catalog
    mutations exclude, and a waiting writer blocks NEW readers — a stream
    of reads can delay a write by at most the in-flight readers (the
    lock-queue fairness ProcSleep gives the reference's lmgr)."""

    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    def acquire_read(self):
        with self._cond:
            while self._writer or self._writers_waiting:
                self._cond.wait()
            self._readers += 1

    def release_read(self):
        with self._cond:
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    def acquire_write(self):
        with self._cond:
            self._writers_waiting += 1
            try:
                while self._writer or self._readers:
                    self._cond.wait()
            finally:
                self._writers_waiting -= 1
            self._writer = True

    def release_write(self):
        with self._cond:
            self._writer = False
            self._cond.notify_all()


def _resp_bytes(resp: dict) -> int:
    """Estimated wire bytes of one response WITHOUT re-serializing big
    row sets: sample the first rows and scale (the transport serializes
    exactly once; this estimate feeds the statements table's wire_bytes
    aggregate, where ±a few percent on huge results is fine)."""
    rows = resp.get("rows")
    if not rows:
        try:
            return len(json.dumps(resp))
        except (TypeError, ValueError):
            return 0
    k = min(len(rows), 64)
    try:
        per = len(json.dumps(rows[:k])) / k
    except (TypeError, ValueError):
        return 0
    return int(per * len(rows)) + 64


def _json_safe(v):
    if v is None:
        return None
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating, float)):
        f = float(v)
        return None if f != f else f  # NaN (NULL rendering) → null
    if isinstance(v, np.bool_):
        return bool(v)
    if isinstance(v, np.datetime64):
        return str(v)
    if hasattr(v, "isoformat"):
        return v.isoformat()
    return v


class Server:
    """One engine process serving many clients over TCP.

    ``read_only=True`` runs the process as a HOT STANDBY (the
    hot_standby / mirroring analog): a second server over the SAME store
    serves reads while refusing writes. No WAL ships and nothing
    promotes-on-command — immutable snapshot manifests ARE the
    replication stream (the standby's epoch sync picks up every commit),
    and "promotion" is restarting without the flag.

    ``auth_token`` enables authentication: clients must send
    {"auth": "<token>"} before anything else. Repeated failures from one
    client address lock that address out for ``lockout_s`` seconds (the
    login-monitor analog — the reference disables accounts after
    consecutive failed logins)."""

    def __init__(self, session=None, config=None,
                 host: str = "127.0.0.1", port: int = 0,
                 read_only: bool = False,
                 auth_token: Optional[str] = None,
                 max_login_failures: int = 3,
                 lockout_s: float = 60.0,
                 watchdog_interval_s: float = 0.05):
        import cloudberry_tpu as cb

        self.session = session if session is not None else cb.Session(config)
        # per-connection backends need shared durable storage to see each
        # other's commits; an explicit session= pins legacy shared mode
        self._config = self.session.config
        self.per_connection = (session is None
                               and self.session.store is not None)
        self.read_only = read_only
        self.auth_token = auth_token
        self.max_login_failures = max_login_failures
        self.lockout_s = lockout_s
        # login monitor state: client address -> (failures, locked_until)
        self._login_failures: dict[str, list] = {}
        self._login_lock = threading.Lock()
        self._rw = _RWLock()
        # statement-lifecycle state (lifecycle.py): the watchdog cancels
        # over-deadline statements (statement_timeout enforcement even
        # when the worker thread is wedged at an interruptible seam);
        # _draining + the in-flight request count drive graceful drain
        from cloudberry_tpu.lifecycle import Watchdog

        self.watchdog = Watchdog(self.session.stmt_log,
                                 interval_s=watchdog_interval_s)
        self._draining = False
        self._inflight = 0
        self._inflight_cond = threading.Condition()
        # accept-path connection cap (config.serve.max_connections): past
        # it, a new connection gets ONE retryable SERVER_BUSY line and
        # closes — bounded fds/threads instead of unbounded accept growth
        self.max_connections = self._config.serve.max_connections
        self._conn_count = 0
        self._conn_lock = threading.Lock()
        # per-tenant workload governance (sched/tenancy.py): named
        # resource groups with DWRR weights, concurrency slots, and
        # bounded queues; requests pick their group via {"tenant": name}
        self.tenancy = None
        if self._config.tenancy.enabled:
            from cloudberry_tpu.sched.tenancy import TenantScheduler

            self.tenancy = TenantScheduler(self._config.tenancy)
        # tenancy observability spans the wire (serve/meta.py "tenants")
        self.session._tenancy = self.tenancy
        # transport: the event-loop front end (serve/asyncore.py) is the
        # default — a handful of I/O threads multiplex every connection;
        # config.serve.threaded keeps the thread-per-connection path
        if self._config.serve.threaded:
            self._transport = _ThreadedTransport(self, host, port)
        else:
            from cloudberry_tpu.serve.asyncore import AsyncFrontEnd

            self._transport = AsyncFrontEnd(self, host, port)
        self.host, self.port = self._transport.host, self._transport.port
        # scheduled statements (pg_cron analog): jobs persist in the store
        # and run in the serving process's session
        from cloudberry_tpu.serve.cron import Scheduler

        self.cron = Scheduler(self.session,
                              execute=self._cron_execute).load()
        # continuous micro-batch dispatcher (sched/dispatcher.py, the
        # gang-dispatch analog): opt-in via config.sched.enabled — read
        # statements coalesce into stacked launches on the SERVER session;
        # executions hold the same statement-level lock scope direct
        # dispatch would, and the tenancy scheduler (when enabled) owns
        # the pick order inside its tick
        self.dispatcher = None
        if self.session.config.sched.enabled:
            from cloudberry_tpu.sched import Dispatcher

            self.dispatcher = Dispatcher(self.session,
                                         exec_scope=self._locked,
                                         tenancy=self.tenancy)
        # streaming ingest plane (storage/ingest.py): ONE service on the
        # SERVER session in both sharing modes — group commit must span
        # connections (per-connection backends see the flushed commits
        # through the store's epoch sync like any other writer's)
        self.ingest = None
        if self._config.ingest.enabled:
            from cloudberry_tpu.storage.ingest import IngestService

            self.ingest = IngestService(self.session,
                                        exec_scope=self._locked)
            self.session._ingest = self.ingest
        # background compaction (storage/compact.py): opt-in (a read-
        # mostly server pays nothing) and store-backed only; committed
        # ingest flushes poke it so write bursts fold promptly
        self.compactor = None
        if self._config.compact.enabled and self.session.store is not None:
            from cloudberry_tpu.storage.compact import CompactionService

            self.compactor = CompactionService(self.session)
            self.session._compactor = self.compactor
            if self.ingest is not None:
                self.ingest.on_commit = self.compactor.wake

    # -------------------------------------------------- lifecycle plumbing

    def _request_begin(self) -> None:
        with self._inflight_cond:
            self._inflight += 1

    def _request_end(self) -> None:
        with self._inflight_cond:
            self._inflight -= 1
            self._inflight_cond.notify_all()

    # ------------------------------------------------- connection admission

    def _try_admit_conn(self) -> bool:
        """Accept-path cap: True admits (counted), False means the caller
        must send the SERVER_BUSY line and close."""
        with self._conn_lock:
            if self.max_connections and \
                    self._conn_count >= self.max_connections:
                return False
            self._conn_count += 1
            return True

    def _conn_closed(self) -> None:
        with self._conn_lock:
            self._conn_count -= 1

    def _busy_resp(self) -> dict:
        from cloudberry_tpu.lifecycle import ServerBusy

        return {"ok": False, "etype": ServerBusy.__name__,
                "retryable": True,
                "fatal": True,
                "error": f"SERVER_BUSY: connection limit "
                         f"({self.max_connections}) reached; retry "
                         "shortly"}

    def _busy_line(self) -> bytes:
        return json.dumps(self._busy_resp()).encode() + b"\n"

    def _process_line(self, line: bytes, sess, authed: bool, addr: str,
                      async_cb=None):
        """One wire line → (response dict | None, authed'): the
        transport-independent request core. ``None`` means an async
        completion owns the response (``async_cb`` will fire exactly
        once with it — event-loop transport only)."""
        try:
            req = json.loads(line)
            if not authed:
                resp, authed = self._authenticate(req, addr)
            else:
                resp = self._execute(req, sess, async_cb=async_cb)
        except Exception as e:
            # bad client/statement must not kill the connection handler
            resp = self._error_resp(e)
        return resp, authed

    @staticmethod
    def _error_resp(e: BaseException) -> dict:
        """Wire error with the shared taxonomy: ``etype`` names the
        error class, ``retryable`` is the server's verdict (the client's
        auto-retry trusts it — one classifier, lifecycle.is_retryable,
        for both sides)."""
        from cloudberry_tpu.lifecycle import is_retryable

        return {"ok": False, "etype": type(e).__name__,
                "retryable": is_retryable(e),
                "error": f"{type(e).__name__}: {e}"}

    def _locked(self, write: bool = False):
        """Statement-level lock scope: a no-op in per-connection mode
        (each backend has its own catalog; the store's OCC arbitrates),
        shared read/exclusive write otherwise. Every path that touches
        the shared session — wire SQL, meta, retrieve, cron jobs — must
        go through this one helper so the lock discipline has a single
        home."""
        import contextlib

        if self.per_connection:
            return contextlib.nullcontext()

        @contextlib.contextmanager
        def scope():
            acq = self._rw.acquire_write if write else self._rw.acquire_read
            rel = self._rw.release_write if write else self._rw.release_read
            acq()
            try:
                yield
            finally:
                rel()

        return scope()

    def _cron_execute(self, sql: str):
        """Run a cron job's statement under the same statement-level
        locking a wire client would get: in shared-session mode a
        scheduled write must exclude concurrent reader threads."""
        with self._locked(write=not _is_read(sql)):
            return self.session.sql(sql)

    # ----------------------------------------------------- authentication

    def _authenticate(self, req: dict, addr: str) -> tuple[dict, bool]:
        """First-request auth + the login-monitor lockout. Returns
        (response, now_authenticated); a lockout or bad token closes the
        connection (resp["fatal"])."""
        import time

        with self._login_lock:
            fails, until = self._login_failures.get(addr, [0, 0.0])
            if time.monotonic() < until:
                return ({"ok": False, "fatal": True, "retryable": False,
                         "error": "too many failed logins; address locked "
                                  f"for {self.lockout_s:.0f}s"}, False)
        import hmac

        # bytes, not str: compare_digest on str raises for non-ASCII,
        # which would lock out any server with a non-ASCII token
        token = req.get("auth")
        if hmac.compare_digest(str(token or "").encode(),
                               str(self.auth_token).encode()):
            with self._login_lock:
                self._login_failures.pop(addr, None)
            return ({"ok": True, "status": "authenticated"}, True)
        with self._login_lock:
            fails, until = self._login_failures.get(addr, [0, 0.0])
            fails += 1
            if fails >= self.max_login_failures:
                until = time.monotonic() + self.lockout_s
            self._login_failures[addr] = [fails, until]
        msg = ("authentication required: send {\"auth\": \"<token>\"} first"
               if "auth" not in req else "authentication failed")
        return ({"ok": False, "fatal": True, "retryable": False,
                 "error": msg}, False)

    @staticmethod
    def _parameterizable(sql: str) -> bool:
        """Reads worth coalescing: the skeleton normalizer hoists at
        least one literal (same-shape statements can share a launch)."""
        from cloudberry_tpu.sched import paramplan

        norm = paramplan.normalize(sql)
        return norm is not None and bool(norm[1])

    # ------------------------------------------------- connection sessions

    def _connection_session(self):
        """A backend for one connection (postgres.c:1655 fork analog):
        its own Session/catalog over the shared store, sharing the
        server's resource governance and endpoint registry."""
        if not self.per_connection:
            return self.session
        import cloudberry_tpu as cb

        s = cb.Session(self._config)
        s.parallel_cursors = self.session.parallel_cursors
        s._gate = self.session._gate
        s._queues = self.session._queues
        s._vmem = self.session._vmem
        # one activity/history log across ALL backends: "who runs what"
        # must span connections (pg_stat_activity is cluster-wide)
        s.stmt_log = self.session.stmt_log
        # one circuit breaker: device-loss flapping is an ENGINE
        # condition, so read-only-degraded spans backends like the gate
        s._breaker = self.session._breaker
        # one topology manager (parallel/topology.py): the cluster shape
        # is engine state — a cutover on any backend's statement flips
        # every backend at its next epoch pin
        s._topology = self.session._topology
        # dispatcher + tenancy observability (serve/meta.py "sched" /
        # "tenants") spans backends
        s._dispatcher = getattr(self.session, "_dispatcher", None)
        s._tenancy = self.tenancy
        # one checkpoint store: recovery.max_statements bounds the
        # ENGINE's held checkpoints, not each backend's (statement ids
        # come from the shared stmt_log, so keys never collide)
        s._recovery = self.session._recovery
        # write-plane services live on the server session (group commit
        # and the compaction census span backends); meta "ingest" /
        # "compaction" answered by any backend must see them
        s._ingest = getattr(self.session, "_ingest", None)
        s._compactor = getattr(self.session, "_compactor", None)
        # memory-gauge anchor (obs/capacity.refresh_gauges): session-
        # private holders (stmt/store-scan caches) report the SERVING
        # session's, not whichever backend answered meta "metrics" —
        # stable values instead of per-connection flapping
        s._obs_root = self.session
        return s

    def _end_connection(self, sess) -> None:
        """Backend exit: an open wire transaction aborts (the reference
        rolls back on backend death — no orphaned prepared state)."""
        if sess is self.session:
            return
        if getattr(sess, "_txn_snapshot", None) is not None:
            try:
                sess.txn("rollback")
            except Exception:
                pass

    # --------------------------------------------------------------- control

    def start(self) -> "Server":
        self._transport.start()
        if not self.read_only:
            # a standby never runs jobs: the primary owns the schedule
            # (pg_cron likewise runs on the primary only)
            self.cron.start()
        if self.dispatcher is not None:
            self.dispatcher.start()
        if self.compactor is not None and not self.read_only:
            self.compactor.start()
        self.watchdog.start()
        return self

    def serve_forever(self) -> None:
        if not self.read_only:
            self.cron.start()  # foreground entry point runs jobs too
        if self.dispatcher is not None:
            self.dispatcher.start()
        if self.compactor is not None and not self.read_only:
            # parity with start(): the CLI serve path must run the
            # background compactor too, or `--set compact.enabled=true`
            # silently does nothing (caught by the crash-torture matrix)
            self.compactor.start()
        self.watchdog.start()
        self._transport.serve_forever()

    def stop(self, drain_s: float = 0.0) -> None:
        """Shut down; with ``drain_s`` > 0, gracefully (smart shutdown):
        new requests refuse with the retryable SERVER_DRAINING error
        while accepted in-flight work (handler threads AND the
        dispatcher queue) finishes; whatever is still running at the
        budget's end is CANCELLED with the same retryable drain error —
        every accepted request gets an answer, never a silent drop."""
        import time as _t

        self._draining = True
        if drain_s > 0:
            end = _t.monotonic() + drain_s
            with self._inflight_cond:
                while self._inflight and _t.monotonic() < end:
                    self._inflight_cond.wait(
                        timeout=min(0.1, max(end - _t.monotonic(), 0.01)))
            if self.dispatcher is not None:
                self.dispatcher.drain(max(0.0, end - _t.monotonic()))
            # stragglers past the budget: cancel cooperatively so their
            # handlers write the retryable drain error before we close
            for _sid, h in self.session.stmt_log.active_handles():
                h.token.cancel(
                    "drain", "statement abandoned by server drain; "
                    "retry against the serving primary")
            with self._inflight_cond:
                grace = _t.monotonic() + 2.0
                while self._inflight and _t.monotonic() < grace:
                    self._inflight_cond.wait(timeout=0.1)
        self.cron.stop()
        if self.dispatcher is not None:
            self.dispatcher.stop()
        if self.ingest is not None:
            # drain flush-on-stop: buffered rows whose appenders are
            # still blocked commit now (their acks turn true), and the
            # append verb has been refusing since _draining flipped
            self.ingest.stop()
        if self.compactor is not None:
            self.compactor.stop()
        self.watchdog.stop()
        self._transport.stop()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # ------------------------------------------------------------- execution

    def _tenant_slot(self, tenant):
        """Per-tenant concurrency gate for statements that bypass the
        dispatcher (writes, non-parameterizable reads): a no-op without
        tenancy; otherwise bounded-wait admission that refuses with the
        retryable TenantQueueFull (sched/tenancy.py)."""
        import contextlib

        if self.tenancy is None:
            return contextlib.nullcontext()
        return self.tenancy.slot(tenant)

    def _execute(self, req: dict, sess, async_cb=None) -> Optional[dict]:
        if "cancel" in req:
            # the pg_cancel_backend analog: cancel a running statement by
            # its activity id ({"meta": "activity"} lists them). The
            # target fails with StatementCancelled at its next seam.
            # Deliberately ABOVE the drain gate: cancelling your own
            # straggler is most useful exactly while the server drains.
            try:
                sid = int(req["cancel"])
            except (TypeError, ValueError):
                return {"ok": False, "etype": "ValueError",
                        "retryable": False,
                        "error": "cancel needs an integer statement id"}
            if sess.stmt_log.cancel(sid):
                return {"ok": True, "status": f"CANCEL {sid}"}
            return {"ok": False, "etype": "UnknownStatement",
                    "retryable": False,
                    "error": f"no active statement {sid} "
                             "(already finished, or never started)"}
        if self._draining:
            # smart shutdown: accepted in-flight work finishes, NEW work
            # is refused with the RETRYABLE drain error so clients fail
            # over (the promoted standby / restarted primary serves it)
            return {"ok": False, "etype": "ServerDraining",
                    "retryable": True,
                    "error": "SERVER_DRAINING: server is draining for "
                             "shutdown; retry against the serving "
                             "primary"}
        if "meta" in req:
            # catalog metadata over the wire (the pg_catalog role for thin
            # clients — the MCP analog, serve/mcp.py, is the main consumer)
            from cloudberry_tpu.serve.meta import describe

            with self._locked():
                return {"ok": True,
                        "meta": describe(sess, req["meta"],
                                         req.get("arg"))}
        if "cron" in req:
            # scheduled statements over the wire (cron.schedule role)
            from cloudberry_tpu.serve.cron import CronError

            c = req["cron"] if isinstance(req["cron"], dict) else {}
            op = c.get("op")
            try:
                if op == "status":
                    return {"ok": True, "jobs": self.cron.status()}
                if self.read_only:
                    return {"ok": False, "etype": "ReadOnlyError",
                            "retryable": False,
                            "error": "read-only standby: the primary "
                                     "owns the cron schedule"}
                if op == "schedule":
                    self.cron.schedule(c.get("name", ""),
                                       float(c.get("interval_s", 0)),
                                       c.get("sql", ""))
                    return {"ok": True, "status": f"SCHEDULE {c['name']}"}
                if op == "unschedule":
                    self.cron.unschedule(c.get("name", ""))
                    return {"ok": True,
                            "status": f"UNSCHEDULE {c['name']}"}
                return {"ok": False, "retryable": False,
                        "error": f"unknown cron op {op!r}"}
            except (CronError, ValueError) as e:
                return {"ok": False, "etype": type(e).__name__,
                        "retryable": False, "error": str(e)}
        if "retrieve" in req:
            # retrieve-mode request (cdbendpointretrieve.c analog): drain
            # one endpoint of a parallel cursor; token REQUIRED on the wire
            r = req["retrieve"]
            if not isinstance(r, dict) or "token" not in r:
                return {"ok": False, "retryable": False,
                        "error": "retrieve needs cursor/segment/token"}
            with self._locked():
                out = sess.retrieve(
                    r.get("cursor", ""), int(r.get("segment", 0)),
                    r.get("limit"), r["token"])
            out["rows"] = [[_json_safe(v) for v in row]
                           for row in out["rows"]]
            return {"ok": True, **out}
        if "append" in req:
            # streaming ingest verb: rows buffer server-side and the
            # response is written only when the covering flush COMMITS
            # (durability-at-ack, same contract as a successful INSERT).
            # Works on both transports — the handler blocks for at most
            # the flush latency, which is the point of group commit.
            a = req["append"]
            if not isinstance(a, dict) or "table" not in a \
                    or "rows" not in a:
                return {"ok": False, "retryable": False,
                        "error": "append needs "
                                 "{table, rows[, columns]}"}
            if self.read_only:
                return {"ok": False, "etype": "ReadOnlyError",
                        "retryable": False,
                        "error": "read-only standby: route appends to "
                                 "the primary server"}
            if self.ingest is None:
                return {"ok": False, "etype": "IngestDisabled",
                        "retryable": False,
                        "error": "streaming ingest is disabled "
                                 "(config.ingest.enabled)"}
            dl = req.get("deadline_s")
            n = self.ingest.append(
                a["table"], a["rows"], columns=a.get("columns"),
                tenant=req.get("tenant"),
                deadline_s=float(dl) if dl is not None else None)
            return {"ok": True, "status": f"APPEND {n}", "rows": n}
        sql = req.get("sql")
        if not isinstance(sql, str):
            return {"ok": False, "retryable": False,
                    "error": "request must carry a 'sql' string"}
        # per-request deadline: every dispatch path converts it to the
        # session's monotonic deadline, so it governs execution (cancel
        # seams, watchdog), not just the dispatcher queue
        deadline = None
        if req.get("deadline_s") is not None:
            import time as _t

            deadline = _t.monotonic() + float(req["deadline_s"])
        if self.read_only and not _is_read(sql):
            # hot standby: reads only; the store's epoch sync delivers the
            # primary's commits, nothing here may produce one
            return {"ok": False, "etype": "ReadOnlyError",
                    "retryable": False,
                    "error": "read-only standby: route writes to the "
                             "primary server"}
        tenant = req.get("tenant")
        if self.dispatcher is not None and _is_read(sql) \
                and _first_word(sql) not in _TXN_STARTERS \
                and getattr(sess, "_txn_snapshot", None) is None \
                and self._parameterizable(sql):
            # micro-batch dispatch: PARAMETERIZABLE reads coalesce on the
            # server session (same committed snapshot a fresh backend
            # would read); a connection holding an open transaction keeps
            # its own session so its snapshot stays visible.
            # Non-parameterizable reads keep the concurrent handler-thread
            # path — routing them through the single dispatcher worker
            # would head-of-line-block point lookups behind heavy scans.
            if async_cb is not None:
                # event-loop serving: the worker hands the request to the
                # dispatcher and RETURNS — thousands of queued reads cost
                # queue slots, not blocked worker threads; the response
                # is rendered and written when the batch lands
                def _done(r):
                    if r.error is not None:
                        async_cb(self._error_resp(r.error))
                        return
                    try:
                        async_cb(self._finish_render(sql, r.result,
                                                     tenant=tenant))
                    except Exception as e:
                        async_cb(self._error_resp(e))

                self.dispatcher.submit_nowait(
                    sql, deadline_s=req.get("deadline_s"),
                    tenant=tenant, on_done=_done)
                return None
            result = self.dispatcher.submit(
                sql, deadline_s=req.get("deadline_s"), tenant=tenant)
        elif self.per_connection:
            # each connection is its own backend: statement-level locking
            # is unnecessary (no shared catalog objects) and transactions
            # ride the store's multi-session OCC
            with self._tenant_slot(tenant):
                result = sess.sql(sql, _deadline=deadline)
        elif _first_word(sql) in _TXN_STARTERS:
            # all connections share ONE session: a wire-level BEGIN would
            # absorb other clients' autocommit writes into its rollback
            # scope — refuse rather than silently break their durability
            return {"ok": False, "retryable": False, "error":
                    "transactions over the wire need a durable store "
                    "(connections share one session); start the server "
                    "with config.storage.root set, or use the in-process "
                    "API for BEGIN/COMMIT/ROLLBACK"}
        else:
            # shared session: reads share, catalog mutations exclude —
            # concurrent readers would race the data/stats swap (the OCC
            # layer handles cross-PROCESS writers; this lock, threads)
            with self._tenant_slot(tenant), \
                    self._locked(write=not _is_read(sql)):
                result = sess.sql(sql, _deadline=deadline)
        return self._finish_render(sql, result, tenant=tenant)

    def _finish_render(self, sql: str, result, tenant=None) -> dict:
        """Render one SQL result with serving-side observability
        (ISSUE 9): render time feeds the stage histogram and the
        response's estimated wire bytes feed the per-skeleton
        statements table (obs/statements.py)."""
        import time as _t

        t0 = _t.perf_counter()
        resp = self._render(result)
        log = self.session.stmt_log
        if log.obs_enabled:
            from cloudberry_tpu.obs.metrics import observe_stage

            observe_stage(log, "render", _t.perf_counter() - t0)
            log.statements.add_wire(sql, _resp_bytes(resp))
            # tenant-labeled served counter: the registry's per-tenant
            # attribution (obs/metrics.py bump tenant=) without a new
            # snapshot surface
            log.bump("requests_served", tenant=tenant)
        return resp

    def _render(self, result) -> dict:
        """One execution result → the wire response dict (shared by the
        synchronous paths and the dispatcher's async completion)."""
        if isinstance(result, dict):
            # DECLARE PARALLEL RETRIEVE CURSOR: endpoint directory + token
            return {"ok": True, **{k: _json_safe(v) if not isinstance(
                v, (list, dict)) else v for k, v in result.items()}}
        if hasattr(result, "decoded_columns"):
            # pandas-free serialization: DataFrame construction with arrow
            # string dtypes is not thread-safe, and handlers run threaded
            cols = result.decoded_columns()
            names = list(cols)
            arrays = list(cols.values())
            n = len(arrays[0]) if arrays else 0
            return {
                "ok": True,
                "columns": names,
                "rows": [[_json_safe(a[i]) for a in arrays]
                         for i in range(n)],
                "rowcount": n,
            }
        return {"ok": True, "status": str(result)}


# --------------------------------------------------------------- transports


class _ThreadedTransport:
    """The legacy thread-per-connection transport (socketserver), kept
    behind ``config.serve.threaded``: one OS thread per connection,
    blocking line reads, the same request core (Server._process_line)
    the event-loop front end uses — plus the shared accept-path
    connection cap."""

    def __init__(self, server: Server, host: str, port: int):
        outer = server

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                from cloudberry_tpu.utils.faultinject import fault_point

                fault_point("serve_handler")
                addr = self.client_address[0]
                authed = outer.auth_token is None
                sess = None
                try:
                    # inside the try: a failed backend-session creation
                    # must still release the admitted connection slot
                    sess = outer._connection_session()
                    for line in self.rfile:
                        line = line.strip()
                        if not line:
                            continue
                        # in-flight window covers compute AND response
                        # write: drain waits until every accepted request
                        # has its answer on the wire
                        outer._request_begin()
                        try:
                            resp, authed = outer._process_line(
                                line, sess, authed, addr)
                            self.wfile.write(
                                json.dumps(resp).encode() + b"\n")
                            self.wfile.flush()
                        finally:
                            outer._request_end()
                        if resp.get("fatal"):
                            return
                finally:
                    try:
                        if sess is not None:
                            outer._end_connection(sess)
                    finally:
                        outer._conn_closed()

        class TCP(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True
            # bound the kernel accept queue too (socketserver's default
            # is 5 — too small under bursts; unbounded is the other sin)
            request_queue_size = max(16, outer._config.serve.listen_backlog)

            def verify_request(self, request, client_address):
                # the connection cap, enforced at accept: past it the
                # client gets ONE retryable SERVER_BUSY line and a close
                if outer._try_admit_conn():
                    return True
                try:
                    # best-effort, non-blocking: the refusal must never
                    # stall the accept thread on an unresponsive peer
                    request.setblocking(False)
                    request.send(outer._busy_line())
                except OSError:
                    pass
                return False

        self._server = TCP((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()

    def serve_forever(self) -> None:
        self._server.serve_forever()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10)
