"""Socket serving layer — the engine as a database, not a library.

The reference's serving surface is the libpq wire protocol into a
per-connection backend (exec_simple_query, src/backend/tcop/postgres.c:506,
1655). Here one server process owns ONE Session (the QD); clients speak a
newline-delimited JSON protocol:

    → {"sql": "select ..."}
    ← {"ok": true, "columns": [...], "rows": [[...]], "rowcount": N}
    ← {"ok": true, "status": "CREATE TABLE t"}          (DDL/DML)
    ← {"ok": false, "error": "..."}

Read statements run concurrently under the session's admission gate (the
resgroup slot pool); catalog-mutating statements serialize behind a write
lock — the single-writer discipline the storage layer's OCC assumes.
"""

from __future__ import annotations

import json
import socket
import socketserver
import threading
from typing import Optional

import numpy as np

_READ_STARTERS = ("select", "with", "explain", "show")
_TXN_STARTERS = ("begin", "commit", "rollback", "abort", "start", "end")


def _first_word(sql: str) -> str:
    s = sql.lstrip()
    if s.startswith("("):
        return "("
    head = s.split(None, 1)
    return head[0].lower() if head else ""


def _is_read(sql: str) -> bool:
    w = _first_word(sql)
    return w == "(" or w in _READ_STARTERS


class _RWLock:
    """Readers-writer lock: reads share, catalog mutations exclude — the
    session's catalog/data swaps are only safe against concurrent readers
    at statement granularity."""

    def __init__(self):
        self._readers = 0
        self._r = threading.Lock()
        self._w = threading.Lock()

    def acquire_read(self):
        with self._r:
            self._readers += 1
            if self._readers == 1:
                self._w.acquire()

    def release_read(self):
        with self._r:
            self._readers -= 1
            if self._readers == 0:
                self._w.release()

    def acquire_write(self):
        self._w.acquire()

    def release_write(self):
        self._w.release()


def _json_safe(v):
    if v is None:
        return None
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating, float)):
        f = float(v)
        return None if f != f else f  # NaN (NULL rendering) → null
    if isinstance(v, np.bool_):
        return bool(v)
    if isinstance(v, np.datetime64):
        return str(v)
    if hasattr(v, "isoformat"):
        return v.isoformat()
    return v


class Server:
    """One engine process serving many clients over TCP."""

    def __init__(self, session=None, config=None,
                 host: str = "127.0.0.1", port: int = 0):
        import cloudberry_tpu as cb

        self.session = session if session is not None else cb.Session(config)
        self._rw = _RWLock()
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                for line in self.rfile:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        req = json.loads(line)
                        resp = outer._execute(req)
                    except Exception as e:  # a bad client must not kill us
                        resp = {"ok": False,
                                "error": f"{type(e).__name__}: {e}"}
                    self.wfile.write(json.dumps(resp).encode() + b"\n")
                    self.wfile.flush()

        class TCP(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = TCP((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    # --------------------------------------------------------------- control

    def start(self) -> "Server":
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self._server.serve_forever()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # ------------------------------------------------------------- execution

    def _execute(self, req: dict) -> dict:
        if "retrieve" in req:
            # retrieve-mode request (cdbendpointretrieve.c analog): drain
            # one endpoint of a parallel cursor; token REQUIRED on the wire
            r = req["retrieve"]
            if not isinstance(r, dict) or "token" not in r:
                return {"ok": False,
                        "error": "retrieve needs cursor/segment/token"}
            self._rw.acquire_read()
            try:
                out = self.session.retrieve(
                    r.get("cursor", ""), int(r.get("segment", 0)),
                    r.get("limit"), r["token"])
            finally:
                self._rw.release_read()
            out["rows"] = [[_json_safe(v) for v in row]
                           for row in out["rows"]]
            return {"ok": True, **out}
        sql = req.get("sql")
        if not isinstance(sql, str):
            return {"ok": False, "error": "request must carry a 'sql' string"}
        if _first_word(sql) in _TXN_STARTERS:
            # all connections share ONE session: a wire-level BEGIN would
            # absorb other clients' autocommit writes into its rollback
            # scope — refuse rather than silently break their durability
            return {"ok": False, "error":
                    "transactions over the wire are not supported yet "
                    "(connections share one session); use the in-process "
                    "API for BEGIN/COMMIT/ROLLBACK"}
        if _is_read(sql):
            self._rw.acquire_read()
            try:
                result = self.session.sql(sql)
            finally:
                self._rw.release_read()
        else:
            # catalog mutation: exclusive — concurrent readers would race
            # the data/stats swap (the OCC layer handles cross-PROCESS
            # writers; this lock handles threads)
            self._rw.acquire_write()
            try:
                result = self.session.sql(sql)
            finally:
                self._rw.release_write()
        if isinstance(result, dict):
            # DECLARE PARALLEL RETRIEVE CURSOR: endpoint directory + token
            return {"ok": True, **{k: _json_safe(v) if not isinstance(
                v, (list, dict)) else v for k, v in result.items()}}
        if hasattr(result, "decoded_columns"):
            # pandas-free serialization: DataFrame construction with arrow
            # string dtypes is not thread-safe, and handlers run threaded
            cols = result.decoded_columns()
            names = list(cols)
            arrays = list(cols.values())
            n = len(arrays[0]) if arrays else 0
            return {
                "ok": True,
                "columns": names,
                "rows": [[_json_safe(a[i]) for a in arrays]
                         for i in range(n)],
                "rowcount": n,
            }
        return {"ok": True, "status": str(result)}
