"""Client for the JSON-over-TCP serving layer (the libpq analog).

Errors carry the server's lifecycle taxonomy (lifecycle.py): ``etype``
names the engine error class and ``retryable`` is the server's verdict —
True for failures about WHEN the statement ran (drain, backpressure,
deadline pressure), False for failures about the statement itself.
``retry_reads=True`` opts into automatic retries of IDEMPOTENT reads on
retryable errors with jittered exponential backoff (writes never retry:
the engine does not replay DML, and neither may the client).
"""

from __future__ import annotations

import json
import random
import socket
import time


class ServerError(RuntimeError):
    """An error response from the server. ``etype`` is the engine error
    class name; ``retryable`` is the server's taxonomy verdict."""

    def __init__(self, message: str, etype: str | None = None,
                 retryable: bool = False):
        super().__init__(message)
        self.etype = etype
        self.retryable = retryable


class Client:
    def __init__(self, host: str, port: int, timeout: float = 120.0,
                 token: str | None = None, retry_reads: bool = False,
                 max_retries: int = 3, backoff_s: float = 0.05):
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._r = self._sock.makefile("rb")
        self._w = self._sock.makefile("wb")
        self.retry_reads = retry_reads
        self.max_retries = max_retries
        self.backoff_s = backoff_s
        if token is not None:
            self._request({"auth": token})

    def _request(self, req: dict) -> dict:
        self._w.write(json.dumps(req).encode() + b"\n")
        self._w.flush()
        line = self._r.readline()
        if not line:
            raise ServerError("server closed the connection")
        resp = json.loads(line)
        if not resp.get("ok"):
            raise ServerError(resp.get("error", "unknown server error"),
                              etype=resp.get("etype"),
                              retryable=bool(resp.get("retryable")))
        resp.pop("ok")
        return resp

    def sql(self, query: str, deadline_s: float | None = None) -> dict:
        """Execute one statement; returns {"columns", "rows", "rowcount"}
        for queries or {"status": ...} for DDL/DML; raises ServerError on
        engine errors. ``deadline_s`` bounds the statement end to end
        (queueing AND execution — the per-request statement_timeout).

        With ``retry_reads`` enabled, a READ that fails with a retryable
        error (server draining, queue backpressure, deadline pressure)
        retries up to ``max_retries`` times with jittered exponential
        backoff. Writes are never auto-retried — a retried write could
        double-apply."""
        req: dict = {"sql": query}
        if deadline_s is not None:
            req["deadline_s"] = deadline_s
        if not self.retry_reads:
            return self._request(req)
        from cloudberry_tpu.sql.classify import read_only

        if not read_only(query):
            return self._request(req)
        delay = self.backoff_s
        for attempt in range(self.max_retries + 1):
            try:
                return self._request(req)
            except ServerError as e:
                if not e.retryable or attempt == self.max_retries:
                    raise
                # full jitter: desynchronize a thundering herd of
                # retrying clients (they all saw the same drain/overload)
                time.sleep(delay * (0.5 + random.random()))
                delay *= 2
        raise AssertionError("unreachable")

    def rows(self, query: str) -> list[list]:
        return self.sql(query)["rows"]

    def cancel(self, statement_id: int) -> dict:
        """Cancel a running statement by its activity id (the
        pg_cancel_backend analog; ids via meta("activity"))."""
        return self._request({"cancel": statement_id})

    def meta(self, kind: str, arg=None):
        """Catalog metadata snapshot (tables/columns/stats/views/matviews/
        sequences/info/summary) — the pg_catalog role for thin clients."""
        return self._request({"meta": kind, "arg": arg})["meta"]

    def retrieve(self, cursor: str, segment: int, token: str,
                 limit: int | None = None) -> dict:
        """Drain one endpoint of a PARALLEL RETRIEVE CURSOR (the
        retrieve-mode connection, cdbendpointretrieve.c)."""
        return self._request({"retrieve": {"cursor": cursor,
                                           "segment": segment,
                                           "token": token,
                                           "limit": limit}})

    def close(self) -> None:
        try:
            self._r.close()
            self._w.close()
        finally:
            self._sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
