"""Client for the JSON-over-TCP serving layer (the libpq analog)."""

from __future__ import annotations

import json
import socket


class ServerError(RuntimeError):
    pass


class Client:
    def __init__(self, host: str, port: int, timeout: float = 120.0,
                 token: str | None = None):
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._r = self._sock.makefile("rb")
        self._w = self._sock.makefile("wb")
        if token is not None:
            self._request({"auth": token})

    def _request(self, req: dict) -> dict:
        self._w.write(json.dumps(req).encode() + b"\n")
        self._w.flush()
        line = self._r.readline()
        if not line:
            raise ServerError("server closed the connection")
        resp = json.loads(line)
        if not resp.get("ok"):
            raise ServerError(resp.get("error", "unknown server error"))
        resp.pop("ok")
        return resp

    def sql(self, query: str) -> dict:
        """Execute one statement; returns {"columns", "rows", "rowcount"}
        for queries or {"status": ...} for DDL/DML; raises ServerError on
        engine errors."""
        return self._request({"sql": query})

    def rows(self, query: str) -> list[list]:
        return self.sql(query)["rows"]

    def meta(self, kind: str, arg=None):
        """Catalog metadata snapshot (tables/columns/stats/views/matviews/
        sequences/info/summary) — the pg_catalog role for thin clients."""
        return self._request({"meta": kind, "arg": arg})["meta"]

    def retrieve(self, cursor: str, segment: int, token: str,
                 limit: int | None = None) -> dict:
        """Drain one endpoint of a PARALLEL RETRIEVE CURSOR (the
        retrieve-mode connection, cdbendpointretrieve.c)."""
        return self._request({"retrieve": {"cursor": cursor,
                                           "segment": segment,
                                           "token": token,
                                           "limit": limit}})

    def close(self) -> None:
        try:
            self._r.close()
            self._w.close()
        finally:
            self._sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
