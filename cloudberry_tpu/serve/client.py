"""Client for the JSON-over-TCP serving layer (the libpq analog).

Errors carry the server's lifecycle taxonomy (lifecycle.py): ``etype``
names the engine error class and ``retryable`` is the server's verdict —
True for failures about WHEN the statement ran (drain, backpressure,
deadline pressure), False for failures about the statement itself.
``retry_reads=True`` opts into automatic retries of IDEMPOTENT reads on
retryable errors with jittered exponential backoff (writes never retry:
the engine does not replay DML, and neither may the client). The retry
policy honors the taxonomy BY NAME too (lifecycle.is_retryable), so the
per-tenant backpressure refusal (``TenantQueueFull``) and the accept-path
connection cap (``ServerBusy`` — which CLOSES the connection after its
one refusal line) retry even against a server build that did not stamp
the verdict; connection-severing refusals transparently reconnect before
the next attempt.

``tenant`` stamps every statement with a workload-tenant name
(sched/tenancy.py): the server's fair scheduler charges the request to
that named resource group.
"""

from __future__ import annotations

import json
import random
import socket
import time


class ServerError(RuntimeError):
    """An error response from the server. ``etype`` is the engine error
    class name; ``retryable`` is the server's taxonomy verdict."""

    def __init__(self, message: str, etype: str | None = None,
                 retryable: bool = False):
        super().__init__(message)
        self.etype = etype
        self.retryable = retryable


# errors that sever the connection as they are raised: a retry must
# reconnect first (the busy refusal is written at accept time and the
# socket closed right after)
_CONN_SEVERING = ("ServerBusy",)


class Client:
    # class-level default: harnesses that bypass __init__ (tests' flaky
    # transports) still read a tenant
    tenant: str | None = None

    def __init__(self, host: str, port: int, timeout: float = 120.0,
                 token: str | None = None, retry_reads: bool = False,
                 max_retries: int = 3, backoff_s: float = 0.05,
                 tenant: str | None = None):
        self._host = host
        self._port = port
        self._timeout = timeout
        self._token = token
        self.tenant = tenant
        self.retry_reads = retry_reads
        self.max_retries = max_retries
        self.backoff_s = backoff_s
        self._connect()

    def _connect(self) -> None:
        self._sock = socket.create_connection(
            (self._host, self._port), timeout=self._timeout)
        self._r = self._sock.makefile("rb")
        self._w = self._sock.makefile("wb")
        if self._token is not None:
            self._request({"auth": self._token})

    def _reconnect(self) -> None:
        try:
            self.close()
        except OSError:
            pass
        self._connect()

    def _request(self, req: dict) -> dict:
        self._w.write(json.dumps(req).encode() + b"\n")
        self._w.flush()
        line = self._r.readline()
        if not line:
            raise ServerError("server closed the connection")
        resp = json.loads(line)
        if not resp.get("ok"):
            raise ServerError(resp.get("error", "unknown server error"),
                              etype=resp.get("etype"),
                              retryable=bool(resp.get("retryable")))
        resp.pop("ok")
        return resp

    def sql(self, query: str, deadline_s: float | None = None) -> dict:
        """Execute one statement; returns {"columns", "rows", "rowcount"}
        for queries or {"status": ...} for DDL/DML; raises ServerError on
        engine errors. ``deadline_s`` bounds the statement end to end
        (queueing AND execution — the per-request statement_timeout).

        With ``retry_reads`` enabled, a READ that fails with a retryable
        error (server draining, queue/tenant backpressure, the
        connection cap, deadline pressure) retries up to ``max_retries``
        times with jittered exponential backoff, reconnecting when the
        refusal severed the connection. Writes are never auto-retried —
        a retried write could double-apply."""
        req: dict = {"sql": query}
        if deadline_s is not None:
            req["deadline_s"] = deadline_s
        if self.tenant is not None:
            req["tenant"] = self.tenant
        if not self.retry_reads:
            return self._request(req)
        from cloudberry_tpu.sql.classify import read_only

        if not read_only(query):
            return self._request(req)
        from cloudberry_tpu.lifecycle import is_retryable

        delay = self.backoff_s
        for attempt in range(self.max_retries + 1):
            reconnect = False
            try:
                return self._request(req)
            except ServerError as e:
                # the taxonomy by NAME backs up the server's stamped
                # verdict: TenantQueueFull / ServerBusy / SchedQueueFull
                # ... retry even if a response lacked "retryable". A
                # clean close (no response line at all — e.g. the busy
                # refusal's own write failed) is retryable for READS:
                # nothing executed, and reconnecting is the only out.
                closed = str(e).startswith("server closed the connection")
                retry = e.retryable or closed \
                    or (e.etype is not None and is_retryable(e.etype))
                if not retry or attempt == self.max_retries:
                    raise
                reconnect = closed or e.etype in _CONN_SEVERING
            except (OSError, ValueError):
                # connection dropped mid-request (ValueError: writing a
                # file object a failed reconnect closed): reads are
                # idempotent, so reconnect-and-retry is safe
                if attempt == self.max_retries:
                    raise
                reconnect = True
            # full jitter: desynchronize a thundering herd of retrying
            # clients (they all saw the same drain/overload)
            time.sleep(delay * (0.5 + random.random()))
            delay *= 2
            if reconnect:
                try:
                    self._reconnect()
                except (OSError, ServerError):
                    # still down/full: the next loop iteration retries
                    # (a broken half-connected state re-raises there)
                    pass
        raise AssertionError("unreachable")

    def rows(self, query: str) -> list[list]:
        return self.sql(query)["rows"]

    def append(self, table: str, rows: list, columns: list | None = None,
               deadline_s: float | None = None) -> int:
        """Streaming append: buffer ``rows`` server-side and return only
        when the covering group-commit flush has made them durable —
        bit-identical to issuing the equivalent INSERTs, at a fraction
        of the per-statement cost. Raises ServerError; IngestQueueFull
        (etype, retryable) is the back-off-and-retry signal. Appends are
        writes, so like sql() writes they are never auto-retried — the
        caller owns idempotency."""
        a: dict = {"table": table, "rows": rows}
        if columns is not None:
            a["columns"] = columns
        req: dict = {"append": a}
        if deadline_s is not None:
            req["deadline_s"] = deadline_s
        if self.tenant is not None:
            req["tenant"] = self.tenant
        return int(self._request(req).get("rows", 0))

    def cancel(self, statement_id: int) -> dict:
        """Cancel a running statement by its activity id (the
        pg_cancel_backend analog; ids via meta("activity"))."""
        return self._request({"cancel": statement_id})

    def meta(self, kind: str, arg=None):
        """Catalog metadata snapshot (tables/columns/stats/views/matviews/
        sequences/info/tenants/summary) — the pg_catalog role for thin
        clients."""
        return self._request({"meta": kind, "arg": arg})["meta"]

    def retrieve(self, cursor: str, segment: int, token: str,
                 limit: int | None = None) -> dict:
        """Drain one endpoint of a PARALLEL RETRIEVE CURSOR (the
        retrieve-mode connection, cdbendpointretrieve.c)."""
        return self._request({"retrieve": {"cursor": cursor,
                                           "segment": segment,
                                           "token": token,
                                           "limit": limit}})

    def close(self) -> None:
        try:
            self._r.close()
            self._w.close()
        finally:
            self._sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
