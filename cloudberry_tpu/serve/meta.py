"""Catalog metadata snapshots for thin clients — the pg_catalog role.

The reference's MCP server answers metadata questions with catalog SQL
(mcp-server/src/cbmcp/database.py: information_schema / pg_class joins);
here the catalog IS in-process state, so metadata is read directly and
shipped as JSON-safe dicts. Consumed by the wire protocol's {"meta": ...}
request (serve/server.py) and the MCP analog (serve/mcp.py).
"""

from __future__ import annotations


def _policy(t) -> str:
    p = t.policy
    if p.kind == "hashed":
        return f"DISTRIBUTED BY ({', '.join(p.keys)})"
    return f"DISTRIBUTED {p.kind.upper()}"


def _table_row(name: str, t) -> dict:
    return {
        "name": name,
        "columns": len(t.schema.fields),
        "rows": int(t.num_rows),
        "distribution": _policy(t),
        "partitioned": t.partition_spec is not None,
        "cold": bool(getattr(t, "cold", False)),
        "external": bool(getattr(t, "external", None)),
    }


def _int_arg(kind: str, arg, default: int) -> int:
    """Integer limit argument with a clear wire error for bad input (a
    client copying the "prom" arg onto the wrong verb should read WHY)."""
    if arg is None or arg == "":
        return default
    try:
        return int(arg)
    except (TypeError, ValueError):
        raise ValueError(
            f"meta {kind!r} takes an integer limit argument, "
            f"got {arg!r}") from None


def describe(session, kind: str, arg=None):
    """One metadata answer. Kinds: tables | columns | stats | views |
    matviews | sequences | info | activity | sched | tenants |
    metrics | statements | trace | progress | flight | topology |
    ingest | compaction | summary.

    (graftlint's ``obs-meta-verbs`` rule pins this docstring list to the
    implemented kinds BOTH ways — document new verbs here.)"""
    # metadata must see other sessions' committed DDL — a thin client may
    # only ever ask metadata questions, so sync here, not just in sql()
    session._sync_store()
    cat = session.catalog
    if kind == "tables":
        return [_table_row(n, t) for n, t in sorted(cat.tables.items())]
    if kind == "columns":
        t = cat.table(str(arg))
        uniq = t.stats.unique or {}
        return [{"name": f.name, "type": str(f.type),
                 # DECLARED nullability (information_schema semantics) —
                 # the in-RAM validity mask is absent for cold tables and
                 # says nothing about the declaration
                 "nullable": bool(f.nullable),
                 "unique": bool(uniq.get(f.name, False))}
                for f in t.schema.fields]
    if kind == "stats":
        t = cat.table(str(arg))
        return {
            "rows": int(t.num_rows),
            "ndv": {c: int(v) for c, v in (t.stats.ndv or {}).items()},
            "min_max": {c: [float(lo), float(hi)]
                        for c, (lo, hi) in (t.stats.min_max or {}).items()},
            "distribution": _policy(t),
        }
    if kind == "views":
        return sorted(cat.views)
    if kind == "matviews":
        return [{"name": n,
                 "base_table": getattr(d, "base_table", None),
                 "incremental": bool(getattr(d, "incremental", False)),
                 "fresh": getattr(d, "fresh_token", None) is not None}
                for n, d in sorted(cat.matviews.items())]
    if kind == "sequences":
        return sorted(getattr(cat, "sequences", {}) or ())
    if kind == "info":
        breaker = getattr(session, "_breaker", None)
        return {
            "engine": "cloudberry_tpu",
            "n_segments": int(session.config.n_segments),
            "durable": session.store is not None,
            "tables": len(cat.tables),
            "views": len(cat.views),
            "matviews": len(cat.matviews),
            # admission circuit breaker (lifecycle.py): closed | open
            # (read-only-degraded) | half-open, with trip counters
            "breaker": breaker.snapshot() if breaker is not None else None,
            # mid-statement recovery (exec/recovery.py): device-loss
            # retries, tile checkpoints/resumes, and the replay cost
            "recovery": {k: session.stmt_log.counter(k) for k in (
                "recoveries", "tile_checkpoints", "tile_resumes",
                "tiles_replayed", "tile_resume_declined",
                "tile_ckpt_failed", "recovery_wall_ms",
                "watchdog_timeouts")},
        }
    if kind == "sched":
        # scheduler observability: queue depth / batch occupancy from the
        # micro-batch dispatcher (when one is attached) plus the engine's
        # compile-hit / parameterization counters (sched/paramplan.py via
        # exec/instrument.py StatementLog) and the shared cache tier's
        # scope (sched/sharedcache.py)
        from cloudberry_tpu.sched import sharedcache

        disp = getattr(session, "_dispatcher", None)
        return {
            "generic_plans": bool(session.config.sched.generic_plans),
            "dispatcher": disp.snapshot() if disp is not None else None,
            "counters": session.stmt_log.counter_snapshot(),
            "shared_cache": sharedcache.tier_snapshot(session),
        }
    if kind == "tenants":
        # per-tenant workload governance (sched/tenancy.py): weights,
        # queue depth, running/served/rejected counters, queue-wait
        # stats, and the weight-normalized fairness index
        sched = getattr(session, "_tenancy", None)
        if sched is None:
            disp = getattr(session, "_dispatcher", None)
            sched = getattr(disp, "tenancy", None) if disp else None
        if sched is None:
            return {"enabled": False}
        return {"enabled": True,
                "groups": sched.snapshot(),
                "fairness_index": round(sched.fairness_index(), 4)}
    if kind == "metrics":
        # engine-wide metrics registry (obs/metrics.py): counters,
        # gauges, log2-bucket histograms. Every engine memory-holder
        # gauge refreshes at READ time (obs/capacity.py) so the
        # snapshot shows where host+device memory actually sits.
        # arg="prom" returns the Prometheus-style text exposition
        # instead of the JSON snapshot.
        from cloudberry_tpu.obs import capacity

        capacity.refresh_gauges(session)
        if arg == "prom":
            return session.stmt_log.registry.exposition()
        return session.stmt_log.registry.snapshot()
    if kind == "progress":
        # live statement progress (obs/progress.py): every active
        # statement's monotone tiles/rows fraction — the
        # pg_stat_progress_* role
        return {"statements": session.stmt_log.progress_rows()}
    if kind == "flight":
        # slow-statement flight recorder (obs/flightrec.py): the most
        # recent captured debug bundles, newest first; arg bounds how
        # many ship (bundles embed plans + traces — they are not small)
        return {"flights": session.stmt_log.flights(
            _int_arg(kind, arg, 8))}
    if kind == "topology":
        # versioned cluster topology (parallel/topology.py): the
        # serving epoch, any pending change + its rebalance progress
        # (moved rows vs the jump-hash minimal-movement bound), flip /
        # promotion counters, and the recent epoch history — the
        # gp_segment_configuration + gpexpand-status role
        topo = getattr(session, "_topology", None)
        if topo is None:
            return {"enabled": False}
        out = topo.snapshot()
        out["enabled"] = True
        return out
    if kind == "ingest":
        # streaming ingest plane (storage/ingest.py): buffer occupancy
        # per (table, tenant), flush thresholds, drain state, and the
        # append/flush/backpressure counter story — the write-plane
        # half of the AO-table dashboard
        ing = getattr(session, "_ingest", None)
        if ing is None:
            return {"enabled": False}
        return ing.snapshot()
    if kind == "compaction":
        # background compaction (storage/compact.py): per-table
        # delta-partition census against the bounded invariant, worker
        # state, and the chunk/conflict/journal counters — the VACUUM
        # progress role
        comp = getattr(session, "_compactor", None)
        if comp is None:
            return {"enabled": False}
        return comp.snapshot()
    if kind == "statements":
        # pg_stat_statements analog (obs/statements.py): per-skeleton
        # calls / wall / rows / compiles / generic-hit rate / wire
        # bytes, heaviest first; arg bounds the row count
        return session.stmt_log.statements.snapshot(
            _int_arg(kind, arg, 50))
    if kind == "trace":
        # statement trace spans (obs/trace.py): the most recent
        # completed span trees, newest first, plus the assembled
        # Chrome-trace document (Perfetto-loadable); arg bounds how
        # many traces ship
        from cloudberry_tpu.obs.trace import chrome_trace

        traces = session.stmt_log.traces(_int_arg(kind, arg, 8))
        return {"traces": traces, "chrome": chrome_trace(traces)}
    if kind == "activity":
        # pg_stat_activity role: running + recent statements across every
        # backend of this server (one shared StatementLog)
        return {"active": session.stmt_log.activity(),
                "recent": session.stmt_log.recent(
                    int(arg) if arg else 50)}
    if kind == "summary":
        return {n: {"rows": int(t.num_rows),
                    "columns": [f.name for f in t.schema.fields]}
                for n, t in sorted(cat.tables.items())}
    raise ValueError(f"unknown meta kind {kind!r}")
