"""Scheduled statements — the pg_cron analog.

The reference schedules SQL inside the database (pg_cron:
cron.schedule('job', '*/5 * * * *', 'REFRESH ...') running via a
background worker). Analog sized for this engine: jobs are
(name, interval seconds, SQL) triples persisted in the store
(``_cron/jobs.json`` — they survive restarts, like the cron catalog),
and a ``Scheduler`` thread owned by the serving process runs each job's
statement against its session when due. Failures record per-job (last
error + consecutive failure count) instead of killing the scheduler —
the bgworker restart discipline.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Optional


class CronError(RuntimeError):
    pass


@dataclass
class Job:
    name: str
    interval_s: float
    sql: str
    next_run: float = 0.0
    runs: int = 0
    failures: int = 0
    last_error: Optional[str] = None
    last_started: Optional[float] = None


@dataclass
class Scheduler:
    """Background job runner over one session (the cron bgworker).

    ``execute`` (when given) replaces the raw ``session.sql`` call so the
    owner can interpose its own statement-level locking — the Server
    passes a callback that takes its readers-writer lock, because in
    shared-session mode a scheduled write would otherwise race concurrent
    client reads on the same Session (the data/stats swap the lock
    exists to serialize)."""

    session: object
    execute: Optional[object] = None
    tick_s: float = 0.5
    jobs: dict[str, Job] = field(default_factory=dict)
    _stop: threading.Event = field(default_factory=threading.Event)
    _thread: Optional[threading.Thread] = None
    _lock: threading.Lock = field(default_factory=threading.Lock)

    # ------------------------------------------------------- persistence

    def _path(self) -> Optional[str]:
        store = getattr(self.session, "store", None)
        if store is None:
            return None
        return os.path.join(store.root, "_cron", "jobs.json")

    def _persist(self) -> None:
        path = self._path()
        if path is None:
            return
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            json.dump([{"name": j.name, "interval_s": j.interval_s,
                        "sql": j.sql} for j in self.jobs.values()], f)

    def load(self) -> "Scheduler":
        path = self._path()
        if path is not None and os.path.exists(path):
            with open(path) as f:
                for d in json.load(f):
                    # graftlint: ignore[lock-unguarded] startup-only: load() runs before start() spawns the tick thread
                    self.jobs[d["name"]] = Job(d["name"], d["interval_s"],
                                               d["sql"])
        return self

    # --------------------------------------------------------------- api

    def schedule(self, name: str, interval_s: float, sql: str) -> Job:
        """cron.schedule analog; re-scheduling a name replaces the job."""
        if interval_s <= 0:
            raise CronError("interval must be positive")
        with self._lock:
            job = Job(name, float(interval_s), sql,
                      next_run=time.monotonic() + float(interval_s))
            self.jobs[name] = job
            self._persist()
        return job

    def unschedule(self, name: str) -> None:
        with self._lock:
            if self.jobs.pop(name, None) is None:
                raise CronError(f"unknown cron job {name!r}")
            self._persist()

    def status(self) -> list[dict]:
        with self._lock:
            return [{"name": j.name, "interval_s": j.interval_s,
                     "sql": j.sql, "runs": j.runs, "failures": j.failures,
                     "last_error": j.last_error}
                    for j in self.jobs.values()]

    # ------------------------------------------------------------ runner

    def run_due(self, now: Optional[float] = None) -> int:
        """Run every due job once; returns how many ran. Exposed for
        deterministic tests (the loop just calls this on a tick)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            due = [j for j in self.jobs.values() if j.next_run <= now]
        ran = 0
        for j in due:
            j.last_started = now
            j.next_run = now + j.interval_s
            run = self.execute if self.execute is not None \
                else self.session.sql
            try:
                run(j.sql)
                j.runs += 1
                j.failures = 0
                j.last_error = None
            except Exception as e:  # noqa: BLE001 — job isolation
                j.failures += 1
                j.last_error = f"{type(e).__name__}: {e}"
            ran += 1
        return ran

    def start(self) -> "Scheduler":
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.tick_s):
                self.run_due()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="cb-cron")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
