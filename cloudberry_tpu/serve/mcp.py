"""MCP server analog — the engine as an AI-agent tool surface.

The reference ships an MCP (Model Context Protocol) server exposing the
database to AI agents: metadata resources plus read-only query tools over
a security layer (mcp-server/src/cbmcp/server.py:56-175, security.py).
This is the tpu-native analog with zero dependencies: the MCP wire format
is JSON-RPC 2.0 over newline-delimited stdio (the protocol's stdio
transport), implemented directly — ``handle()`` takes one request dict,
``serve_stdio()`` runs the transport loop — and the engine side is either
an in-process Session or a wire connection to a running server
(serve/server.py), whose {"meta": ...} requests carry the catalog
snapshots (serve/meta.py).

Security model (security.py role): tools execute READ-ONLY statements
only — the statement head must be a query starter, and statement bodies
are single statements (no stacked ';'). DDL/DML through an agent goes
through a human-operated connection instead.
"""

from __future__ import annotations

import json
import sys
from typing import Any, Optional

PROTOCOL_VERSION = "2024-11-05"
SERVER_INFO = {"name": "cloudberry-tpu-mcp", "version": "1.0"}

class McpError(RuntimeError):
    pass


def _check_read_only(sql: str) -> None:
    from cloudberry_tpu.sql.classify import read_only, \
        strip_string_literals

    s = sql.strip()
    if not read_only(s):
        head = s.split(None, 1)[0].lower() if s else ""
        raise McpError(f"only read-only statements are allowed "
                       f"(got {head or 'empty'!r})")
    # stacked-statement check on the literal-stripped text: a ';' inside
    # a string ('a;b') is data, not a second statement
    bare = strip_string_literals(s).rstrip().rstrip(";")
    if ";" in bare:
        raise McpError("stacked statements are not allowed")


# ------------------------------------------------------------- engines


class SessionEngine:
    """In-process engine: a Session owned by this process."""

    def __init__(self, session):
        self.session = session

    def sql(self, query: str) -> dict:
        result = self.session.sql(query)
        if hasattr(result, "decoded_columns"):
            cols = result.decoded_columns()
            from cloudberry_tpu.serve.server import _json_safe

            names = list(cols)
            arrays = list(cols.values())
            n = len(arrays[0]) if arrays else 0
            return {"columns": names,
                    "rows": [[_json_safe(a[i]) for a in arrays]
                             for i in range(n)],
                    "rowcount": n}
        return {"status": str(result)}

    def explain(self, query: str) -> str:
        return self.session.explain(query)

    def meta(self, kind: str, arg=None):
        from cloudberry_tpu.serve.meta import describe

        return describe(self.session, kind, arg)


class WireEngine:
    """Remote engine: a serve/server.py instance over TCP."""

    def __init__(self, host: str, port: int):
        from cloudberry_tpu.serve.client import Client

        self.client = Client(host, port)

    def sql(self, query: str) -> dict:
        return self.client.sql(query)

    def explain(self, query: str) -> str:
        out = self.client.sql(f"explain {query}")
        if "rows" in out:
            return "\n".join(r[0] for r in out["rows"])
        return out.get("status", "")

    def meta(self, kind: str, arg=None):
        return self.client.meta(kind, arg)


# ---------------------------------------------------------------- tools


def _tool(name, desc, props, required):
    return {"name": name, "description": desc,
            "inputSchema": {"type": "object", "properties": props,
                            "required": required}}


_STR = {"type": "string"}
_INT = {"type": "integer"}

TOOLS = [
    _tool("list_tables", "List tables with row counts and distribution",
          {}, []),
    _tool("list_columns", "Columns of one table: name/type/nullable/unique",
          {"table": _STR}, ["table"]),
    _tool("list_views", "List view names", {}, []),
    _tool("list_matviews",
          "List materialized views with freshness and maintenance mode",
          {}, []),
    _tool("get_table_stats",
          "Statistics for one table: rows, per-column NDV and min/max",
          {"table": _STR}, ["table"]),
    _tool("execute_query",
          "Run a READ-ONLY SQL statement; returns columns and rows "
          "(row count capped by max_rows)",
          {"sql": _STR, "max_rows": _INT}, ["sql"]),
    _tool("explain_query", "The engine's distributed plan for a statement",
          {"sql": _STR}, ["sql"]),
    _tool("list_large_tables", "Largest tables by row count",
          {"limit": _INT}, []),
    _tool("get_activity",
          "Running and recently-completed statements across all "
          "connections (the pg_stat_activity role)",
          {"limit": _INT}, []),
]

RESOURCES = [
    {"uri": "cbtpu://database/info", "name": "database-info",
     "description": "Engine identity, segment count, object counts",
     "mimeType": "application/json"},
    {"uri": "cbtpu://database/summary", "name": "database-summary",
     "description": "Every table with its columns and row count",
     "mimeType": "application/json"},
    {"uri": "cbtpu://schemas", "name": "schemas",
     "description": "Table names (the flat-namespace schema list)",
     "mimeType": "application/json"},
]


class McpServer:
    """One MCP endpoint over an engine. ``handle`` maps a JSON-RPC request
    dict to a response dict (None for notifications)."""

    def __init__(self, engine):
        self.engine = engine

    # --------------------------------------------------------- dispatch

    def handle(self, req: dict) -> Optional[dict]:
        rid = req.get("id")
        method = req.get("method", "")
        if method.startswith("notifications/"):
            return None
        try:
            result = self._dispatch(method, req.get("params") or {})
            return {"jsonrpc": "2.0", "id": rid, "result": result}
        except McpError as e:
            return {"jsonrpc": "2.0", "id": rid,
                    "error": {"code": -32602, "message": str(e)}}
        except Exception as e:  # noqa: BLE001 — agent-facing boundary
            return {"jsonrpc": "2.0", "id": rid,
                    "error": {"code": -32603,
                              "message": f"{type(e).__name__}: {e}"}}

    def _dispatch(self, method: str, params: dict) -> Any:
        if method == "initialize":
            return {"protocolVersion": PROTOCOL_VERSION,
                    "capabilities": {"tools": {}, "resources": {}},
                    "serverInfo": SERVER_INFO}
        if method == "ping":
            return {}
        if method == "tools/list":
            return {"tools": TOOLS}
        if method == "tools/call":
            return self._call_tool(params.get("name", ""),
                                   params.get("arguments") or {})
        if method == "resources/list":
            return {"resources": RESOURCES}
        if method == "resources/read":
            return self._read_resource(params.get("uri", ""))
        raise McpError(f"unknown method {method!r}")

    # ------------------------------------------------------------ tools

    def _call_tool(self, name: str, args: dict) -> dict:
        try:
            out = self._tool_impl(name, args)
            return {"content": [{"type": "text",
                                 "text": json.dumps(out, default=str)}],
                    "isError": False}
        except McpError:
            raise
        except Exception as e:  # noqa: BLE001 — tool errors flow to agent
            return {"content": [{"type": "text",
                                 "text": f"{type(e).__name__}: {e}"}],
                    "isError": True}

    def _tool_impl(self, name: str, args: dict) -> Any:
        eng = self.engine
        if name == "list_tables":
            return eng.meta("tables")
        if name == "list_columns":
            return eng.meta("columns", args["table"])
        if name == "list_views":
            return eng.meta("views")
        if name == "list_matviews":
            return eng.meta("matviews")
        if name == "get_table_stats":
            return eng.meta("stats", args["table"])
        if name == "execute_query":
            _check_read_only(args["sql"])
            out = eng.sql(args["sql"])
            cap = int(args.get("max_rows", 1000))
            if "rows" in out and len(out["rows"]) > cap:
                out["rows"] = out["rows"][:cap]
                out["truncated"] = True
            return out
        if name == "explain_query":
            _check_read_only(args["sql"])
            return {"plan": eng.explain(args["sql"])}
        if name == "get_activity":
            return eng.meta("activity", args.get("limit"))
        if name == "list_large_tables":
            tables = eng.meta("tables")
            tables.sort(key=lambda t: -t["rows"])
            return tables[:int(args.get("limit", 10))]
        raise McpError(f"unknown tool {name!r}")

    # -------------------------------------------------------- resources

    def _read_resource(self, uri: str) -> dict:
        kinds = {"cbtpu://database/info": "info",
                 "cbtpu://database/summary": "summary",
                 "cbtpu://schemas": "tables"}
        kind = kinds.get(uri)
        if kind is None:
            raise McpError(f"unknown resource {uri!r}")
        body = self.engine.meta(kind)
        if kind == "tables":
            body = [t["name"] for t in body]
        return {"contents": [{"uri": uri, "mimeType": "application/json",
                              "text": json.dumps(body, default=str)}]}

    # -------------------------------------------------------- transport

    def serve_stdio(self, stdin=None, stdout=None) -> None:
        """The MCP stdio transport: one JSON-RPC message per line."""
        stdin = stdin if stdin is not None else sys.stdin
        stdout = stdout if stdout is not None else sys.stdout
        for line in stdin:
            line = line.strip()
            if not line:
                continue
            try:
                req = json.loads(line)
            except json.JSONDecodeError:
                resp = {"jsonrpc": "2.0", "id": None,
                        "error": {"code": -32700, "message": "parse error"}}
            else:
                resp = self.handle(req)
            if resp is not None:
                stdout.write(json.dumps(resp) + "\n")
                stdout.flush()
