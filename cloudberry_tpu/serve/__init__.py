from cloudberry_tpu.serve.client import Client, ServerError
from cloudberry_tpu.serve.server import Server

__all__ = ["Server", "Client", "ServerError"]
