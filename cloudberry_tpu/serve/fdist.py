"""cbfdist — the gpfdist analog: a standalone scatter file server.

The reference's gpfdist (src/bin/gpfdist/gpfdist.c, libevent HTTP) serves
delimited files to every segment in parallel, handing each requester a
disjoint slice so the cluster reads the file exactly once. This analog
speaks plain HTTP (stdlib, threaded): ``GET /<relpath>?segment=i&nseg=N``
returns line stripes ``idx % N == i`` — deterministic scatter, so N
segment fetches partition the file with no coordination state on the
server. Without query args the whole file returns.

Run standalone: ``python -m cloudberry_tpu fdist --root DIR --port P``.
External-table scans (plan/planner.py refresh_external_table) fetch their
per-segment stripes from it concurrently.
"""

from __future__ import annotations

import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse


class _Handler(BaseHTTPRequestHandler):
    root = "."

    def log_message(self, *a):  # quiet by default
        pass

    def do_GET(self):
        from cloudberry_tpu.utils.faultinject import fault_point

        fault_point("fdist_get")
        u = urlparse(self.path)
        rel = u.path.lstrip("/")
        # no traversal: the resolved path must stay under root
        full = os.path.realpath(os.path.join(self.root, rel))
        rootr = os.path.realpath(self.root)
        if not (full == rootr or full.startswith(rootr + os.sep)) \
                or not os.path.isfile(full):
            self.send_error(404, "no such file")
            return
        q = parse_qs(u.query)
        with open(full, "rb") as f:
            body = f.read()
        if "nseg" in q:
            nseg = max(int(q["nseg"][0]), 1)
            seg = int(q.get("segment", ["0"])[0]) % nseg
            lines = body.splitlines(keepends=True)
            # a final line without its newline must not merge into the
            # next stripe when the client concatenates segment fetches
            body = b"".join(
                ln if ln.endswith((b"\n", b"\r")) else ln + b"\n"
                for i, ln in enumerate(lines) if i % nseg == seg)
        self.send_response(200)
        self.send_header("Content-Type", "text/plain")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def serve(root: str, port: int = 0, host: str = "127.0.0.1"):
    """Start the server on a daemon thread; returns (server, port)."""
    handler = type("H", (_Handler,), {"root": root})
    srv = ThreadingHTTPServer((host, port), handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv, srv.server_address[1]


def main(root: str, port: int, host: str = "0.0.0.0") -> None:
    handler = type("H", (_Handler,), {"root": root})
    srv = ThreadingHTTPServer((host, port), handler)
    print(f"cbfdist serving {root} on {host}:{srv.server_address[1]}",
          flush=True)
    srv.serve_forever()
