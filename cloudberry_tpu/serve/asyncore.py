"""Event-loop serving front end — thousands of connections, a few threads.

The thread-per-connection transport spends an OS thread (stack, context
switches, accept-time spawn) per client even though almost every
connection is idle at any instant; at warehouse concurrency that is the
first wall. This module replaces it as the DEFAULT transport (the old
path stays behind ``config.serve.threaded``):

- ``io_threads`` event loops (selectors over non-blocking sockets)
  own every connection's framing: reads accumulate into a per-connection
  buffer, complete newline-JSON lines queue as pending requests, writes
  drain a per-connection output buffer under EVENT_WRITE interest;
- parsed requests execute on a small bounded WORKER POOL through the
  same request core the threaded path uses (Server._process_line) — one
  request at a time per connection, so the wire protocol's strict
  request→response order holds even for pipelining clients;
- dispatcher-bound reads complete ASYNCHRONOUSLY
  (Dispatcher.submit_nowait): the worker enqueues and returns, and the
  response is rendered/written when the coalesced batch lands — a
  thousand queued point lookups cost queue slots, not blocked threads;
- flow control at every layer: the accept-path connection cap
  (SERVER_BUSY, serve/server.py), per-connection pipelining caps (a
  client that streams requests without reading responses leaves the
  read set until its backlog drains), and the dispatcher/tenancy
  backpressure taxonomy (SchedQueueFull / TenantQueueFull).

Drain and lifecycle semantics are the Server's, unchanged: every
accepted request holds the in-flight window until its response bytes are
queued, so ``Server.stop(drain_s)`` keeps its never-silently-dropped
contract, and a dropped connection still rolls its open wire transaction
back (the backend-exit abort) once its in-flight request completes.
"""

from __future__ import annotations

import itertools
import json
import queue
import selectors
import socket
import threading
from collections import deque
from typing import Optional

_RECV_CHUNK = 1 << 16


class _WorkerPool:
    """Minimal daemon-thread pool: a wedged statement can never block
    interpreter exit (concurrent.futures workers are non-daemon), and
    the watchdog converts genuine hangs to timeouts anyway."""

    def __init__(self, n: int, name: str = "cbtpu-serve"):
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._threads = [
            threading.Thread(target=self._run, daemon=True,
                             name=f"{name}-w{i}")
            for i in range(max(1, n))]
        for t in self._threads:
            t.start()

    def submit(self, fn, *args) -> None:
        self._q.put((fn, args))

    def stop(self, timeout_s: float = 5.0) -> None:
        for _ in self._threads:
            self._q.put(None)
        for t in self._threads:
            t.join(timeout=timeout_s)

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            fn, args = item
            try:
                fn(*args)
            except Exception:
                pass  # the request core already converts errors to wire


class _Conn:
    """One client connection's state. Framing buffers (rbuf) and
    selector interest belong to the owning loop thread; ``wbuf``,
    ``pending``, and ``busy`` are shared with worker threads under
    ``lock``."""

    __slots__ = ("sock", "addr", "loop", "rbuf", "wbuf", "lock",
                 "pending", "busy", "authed", "session",
                 "close_after_flush", "closed", "paused", "ended",
                 "registered", "scanned")

    def __init__(self, sock, addr, loop):
        self.sock = sock
        self.addr = addr
        self.loop = loop
        self.rbuf = bytearray()
        self.wbuf = bytearray()
        self.lock = threading.Lock()
        self.pending: deque = deque()
        self.busy = False
        self.authed = False
        self.session = None
        self.close_after_flush = False
        self.closed = False
        self.paused = False
        self.ended = False
        self.registered = False
        self.scanned = 0  # rbuf prefix already searched for newlines


class _IOLoop:
    """One selector thread. Cross-thread work (enabling write interest,
    resuming reads, closing) arrives as tasks via ``call`` + a self-pipe
    wake, so the selector is only ever touched by its own thread."""

    def __init__(self, fe: "AsyncFrontEnd", name: str):
        self.fe = fe
        self.name = name
        self.sel = selectors.DefaultSelector()
        r, w = socket.socketpair()
        r.setblocking(False)
        w.setblocking(False)
        self._wake_r, self._wake_w = r, w
        self.sel.register(r, selectors.EVENT_READ, ("wake", None))
        self._tasks: deque = deque()
        self._tlock = threading.Lock()
        self._stopping = False
        self.conns: set = set()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------ thread control

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=self.name)
        self._thread.start()

    def stop(self) -> None:
        with self._tlock:
            self._stopping = True
        self.wake()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def call(self, fn) -> None:
        with self._tlock:
            self._tasks.append(fn)
        self.wake()

    def wake(self) -> None:
        try:
            self._wake_w.send(b"\0")
        except OSError:
            pass

    # -------------------------------------------------------------- loop

    def _run(self) -> None:
        while True:
            try:
                events = self.sel.select(timeout=0.5)
            except OSError:
                events = []
            for key, mask in events:
                kind, obj = key.data
                if kind == "wake":
                    try:
                        while self._wake_r.recv(4096):
                            pass
                    except OSError:
                        pass
                elif kind == "accept":
                    self.fe._accept()
                elif kind == "conn":
                    if mask & selectors.EVENT_READ:
                        self._read(obj)
                    if mask & selectors.EVENT_WRITE and not obj.closed:
                        self._flush(obj)
            while True:
                with self._tlock:
                    if not self._tasks:
                        break
                    fn = self._tasks.popleft()
                try:
                    fn()
                except Exception:
                    pass
            with self._tlock:
                if self._stopping:
                    break
        self._shutdown()

    def _shutdown(self) -> None:
        """Final flush: drain queued response bytes with a short blocking
        budget per connection, then close — responses written before the
        transport stopped are delivered, not dropped."""
        for conn in list(self.conns):
            try:
                self.sel.unregister(conn.sock)
            except (KeyError, ValueError, OSError):
                pass
            with conn.lock:
                data = bytes(conn.wbuf)
                conn.wbuf.clear()
                conn.closed = True
            if data:
                try:
                    conn.sock.settimeout(0.5)
                    conn.sock.sendall(data)
                except OSError:
                    pass
            try:
                conn.sock.close()
            except OSError:
                pass
            self.fe._conn_gone(conn)
        self.conns.clear()
        try:
            self.sel.close()
        except OSError:
            pass

    # ------------------------------------------------------- conn plumbing

    def register_conn(self, conn: _Conn) -> None:
        self.conns.add(conn)
        self.sel.register(conn.sock, selectors.EVENT_READ, ("conn", conn))
        conn.registered = True

    def _update_interest(self, conn: _Conn) -> None:
        """Re-derive this connection's selector interest from its state:
        READ unless paused, WRITE while output is buffered; a fully idle
        paused connection leaves the selector entirely (a writable
        socket is ALWAYS ready — keeping it registered would spin)."""
        if conn.closed:
            return
        mask = 0
        if not conn.paused:
            mask |= selectors.EVENT_READ
        with conn.lock:
            if conn.wbuf:
                mask |= selectors.EVENT_WRITE
        try:
            if mask == 0:
                if conn.registered:
                    self.sel.unregister(conn.sock)
                    conn.registered = False
            elif conn.registered:
                self.sel.modify(conn.sock, mask, ("conn", conn))
            else:
                self.sel.register(conn.sock, mask, ("conn", conn))
                conn.registered = True
        except (KeyError, ValueError, OSError):
            pass

    def _read(self, conn: _Conn) -> None:
        try:
            data = conn.sock.recv(_RECV_CHUNK)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self.close_conn(conn)
            return
        if not data:
            self.close_conn(conn)
            return
        conn.rbuf += data
        new = False
        while True:
            # resume the newline search where the last one stopped — a
            # full rescan per recv would make large lines quadratic
            i = conn.rbuf.find(b"\n", conn.scanned)
            if i < 0:
                conn.scanned = len(conn.rbuf)
                if conn.scanned > self.fe.max_line_bytes:
                    # framing-buffer bound: a newline-free byte stream
                    # must not grow rbuf forever — one fatal error
                    # line, then close
                    conn.rbuf.clear()
                    conn.scanned = 0
                    self.fe._complete_oversized(conn)
                break
            line = bytes(conn.rbuf[:i]).strip()
            del conn.rbuf[:i + 1]
            conn.scanned = 0
            if line:
                with conn.lock:
                    conn.pending.append(line)
                new = True
        with conn.lock:
            backlog = len(conn.pending)
        if backlog > self.fe.pipeline_depth and not conn.paused:
            # pipelining cap: stop reading a client that streams requests
            # without consuming responses; resumed when the backlog drains
            conn.paused = True
            self._update_interest(conn)
        if new:
            self.fe._pump(conn)

    def enable_write(self, conn: _Conn) -> None:
        if conn.closed:
            return
        self._flush(conn)

    def maybe_resume(self, conn: _Conn) -> None:
        if conn.closed or not conn.paused:
            return
        with conn.lock:
            backlog = len(conn.pending)
        if backlog * 2 <= self.fe.pipeline_depth:
            conn.paused = False
            self._update_interest(conn)

    def _flush(self, conn: _Conn) -> None:
        err = False
        with conn.lock:
            while conn.wbuf:
                try:
                    n = conn.sock.send(conn.wbuf)
                except (BlockingIOError, InterruptedError):
                    break
                except OSError:
                    err = True
                    break
                if n <= 0:
                    break
                del conn.wbuf[:n]
            empty = not conn.wbuf
        if err:
            self.close_conn(conn)
            return
        if empty and conn.close_after_flush:
            self.close_conn(conn)
            return
        self._update_interest(conn)

    def close_conn(self, conn: _Conn) -> None:
        if conn.closed:
            return
        conn.closed = True
        try:
            self.sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        self.conns.discard(conn)
        self.fe._conn_gone(conn)


class AsyncFrontEnd:
    """The event-loop transport: accept + framing on ``io_threads``
    selector loops, execution on a bounded worker pool, one in-order
    request at a time per connection."""

    def __init__(self, server, host: str, port: int):
        self.server = server
        cfg = server._config.serve
        self.pipeline_depth = max(1, cfg.pipeline_depth)
        self.max_line_bytes = max(1 << 16, cfg.max_line_bytes)
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind((host, port))
        ls.listen(max(16, cfg.listen_backlog))
        ls.setblocking(False)
        self._lsock = ls
        self.host, self.port = ls.getsockname()[:2]
        self._loops = [_IOLoop(self, f"cbtpu-io{i}")
                       for i in range(max(1, cfg.io_threads))]
        self._next = itertools.count()
        workers = cfg.workers or max(
            4, server._config.resource.max_concurrency)
        self._pool_size = workers
        self._pool: Optional[_WorkerPool] = None
        self._stopped = threading.Event()

    # ------------------------------------------------------------- control

    def start(self) -> None:
        if self._pool is not None:
            return  # idempotent: start() + serve_forever() compose
        self._pool = _WorkerPool(self._pool_size)
        for lp in self._loops:
            lp.start()
        lp0 = self._loops[0]
        lp0.call(lambda: lp0.sel.register(
            self._lsock, selectors.EVENT_READ, ("accept", None)))

    def serve_forever(self) -> None:
        self.start()
        self._stopped.wait()

    def stop(self) -> None:
        try:
            self._loops[0].call(
                lambda: self._loops[0].sel.unregister(self._lsock))
        except Exception:
            pass
        for lp in self._loops:
            lp.stop()
        try:
            self._lsock.close()
        except OSError:
            pass
        if self._pool is not None:
            self._pool.stop()
        self._stopped.set()

    # -------------------------------------------------------------- accept

    def _accept(self) -> None:
        while True:
            try:
                sock, addr = self._lsock.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            if not self.server._try_admit_conn():
                # the accept-path cap: one retryable SERVER_BUSY line
                # (best-effort, NON-blocking — a stalled peer must not
                # freeze this loop's established connections), then
                # close — never an unbounded fd/thread pile-up
                try:
                    sock.setblocking(False)
                    sock.send(self.server._busy_line())
                except OSError:
                    pass
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            sock.setblocking(False)
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            lp = self._loops[next(self._next) % len(self._loops)]
            conn = _Conn(sock, addr[0], lp)
            conn.authed = self.server.auth_token is None
            # bind BOTH names: `lp` is reassigned on the next accept of
            # this burst, and a late-binding closure would register the
            # connection on a foreign loop's selector
            lp.call(lambda c=conn, l=lp: l.register_conn(c))

    # ----------------------------------------------------------- execution

    def _pump(self, conn: _Conn) -> None:
        """Start the next pending request unless one is in flight —
        the per-connection ordering guarantee. Callable from loop and
        worker threads. A connection marked fatal (close_after_flush)
        stops here: pipelined lines behind a fatal response must not
        execute (the threaded handler returns on fatal the same way)."""
        with conn.lock:
            if conn.busy or conn.closed or conn.close_after_flush \
                    or not conn.pending:
                return
            line = conn.pending.popleft()
            conn.busy = True
        self._pool.submit(self._work, conn, line)

    def _work(self, conn: _Conn, line: bytes) -> None:
        srv = self.server
        # in-flight window covers compute AND response enqueue: drain
        # waits until every accepted request has its answer queued
        srv._request_begin()
        try:
            if conn.session is None:
                # lazy backend creation: accept stays cheap; the first
                # request pays the (store-mode) catalog registration
                conn.session = srv._connection_session()
            resp, conn.authed = srv._process_line(
                line, conn.session, conn.authed, conn.addr,
                async_cb=lambda r: self._complete(conn, r))
        except Exception as e:
            resp = srv._error_resp(e)
        if resp is None:
            return  # async completion owns the response AND _request_end
        self._complete(conn, resp)

    def _complete_oversized(self, conn: _Conn) -> None:
        """Refuse a request line past serve.max_line_bytes: write one
        fatal error response and close after flush (loop thread)."""
        data = json.dumps({
            "ok": False, "etype": "ValueError", "retryable": False,
            "fatal": True,
            "error": "request line exceeds serve.max_line_bytes "
                     f"({self.max_line_bytes} bytes)"}).encode() + b"\n"
        with conn.lock:
            conn.wbuf += data
            conn.close_after_flush = True
        conn.loop.enable_write(conn)

    def _complete(self, conn: _Conn, resp: dict) -> None:
        """Queue one response's bytes, release the in-flight window, and
        pump the next pipelined request. Runs on worker threads and on
        the dispatcher worker (async completions)."""
        try:
            data = json.dumps(resp).encode() + b"\n"
        except (TypeError, ValueError) as e:
            data = json.dumps(self.server._error_resp(e)).encode() + b"\n"
        with conn.lock:
            conn.wbuf += data
            if resp.get("fatal"):
                conn.close_after_flush = True
        lp = conn.loop
        lp.call(lambda c=conn: lp.enable_write(c))
        self.server._request_end()
        with conn.lock:
            conn.busy = False
            closed = conn.closed
        if closed:
            self._end_backend(conn)
        else:
            lp.call(lambda c=conn: lp.maybe_resume(c))
            self._pump(conn)

    # ------------------------------------------------------------ teardown

    def _conn_gone(self, conn: _Conn) -> None:
        """Socket closed (client drop, error, shutdown): release the
        connection slot and, once no request is mid-flight, run the
        backend exit (open wire transactions roll back)."""
        self.server._conn_closed()
        with conn.lock:
            busy = conn.busy
        if not busy:
            self._end_backend(conn)
        # else: _complete sees conn.closed and runs the backend exit

    def _end_backend(self, conn: _Conn) -> None:
        with conn.lock:
            if conn.ended:
                return
            conn.ended = True
            sess = conn.session
        if sess is not None:
            try:
                self.server._end_connection(sess)
            except Exception:
                pass
