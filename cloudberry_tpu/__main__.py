from cloudberry_tpu.mgmt.cli import main

raise SystemExit(main())
