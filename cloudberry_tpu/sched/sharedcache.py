"""Process-wide shared cache tier — compiled programs across sessions.

Until now every Session owned private LRUs for the three expensive
reusable artifacts: generic plans (sched/paramplan.py — skeleton →
compiled program with literals as device inputs), capacity-rung
executables (session._rung_cache — one SPMD program per motion-rung
signature), and join indexes (exec/joinindex.py — host-mirrored
sorted-build scaffolding). A server running per-connection backends over
a durable store therefore recompiled every skeleton once PER TENANT even
though the programs are identical.

This module promotes those caches to an engine-wide tier organized as
invalidation SCOPES:

- sessions over the same durable store root share ONE scope — tenant B
  re-binds tenant A's compiled skeleton with zero recompiles;
- storeless sessions get a private scope (their table contents have no
  cross-session identity), which preserves the exact pre-tier behavior.

The invalidation contract is the existing signature discipline, not a
new protocol:

- every shared key embeds content-stable TABLE VERSION tokens
  (``table_key``): a store-backed table outside a transaction is pinned
  by its store version (any commit bumps it); anything else — in-RAM
  tables, mid-transaction state, the ``$dual`` constant relation — falls
  back to a process-unique table uid + local version, making those
  entries private-by-construction even inside a shared scope;
- the config OBJECT IDENTITY is the config epoch (generic plans already
  check ``config is session.config``): any with_overrides/degrade_mesh
  swap replaces the frozen tree wholesale and orphans every entry built
  under it;
- the UDF registry version stays in every plan epoch (process-wide
  state compiled into programs).

Structural guards that per-session caches got from ``catalog.ddl_version``
are covered differently per cache: generic plans carry a full structural
signature (paramplan._Walker captures everything the trace bakes), so
cross-session reuse needs no ddl counter; rung executables have no such
signature, so their shared keys stay scoped to one session's catalog
generation whenever the catalog holds views (view redefinition can change
the plan under an unchanged query text).
"""

from __future__ import annotations

import itertools
import threading
import weakref


class CacheScope:
    """One invalidation domain's caches. ``kind`` is 'store' (shared by
    every session over the same storage root) or 'session' (private)."""

    def __init__(self, kind: str, token):
        self.kind = kind
        self.token = token
        # generic-plan cache: skeleton -> [GenericPlan, ...] (paramplan)
        self.generic: dict = {}
        self.generic_lock = threading.Lock()
        # capacity-rung executables (session._rung_executable)
        self.rung: dict = {}
        self.rung_lock = threading.Lock()
        # join indexes (exec/joinindex.py)
        self.joinindex: dict = {}
        self.joinindex_lock = threading.Lock()
        # HBM-resident scan buffer pool (exec/bufferpool.py), created
        # lazily by bufferpool.pool_for — it owns its own leaf lock and
        # byte budget; anchored here so sessions over one store root
        # share residency the way they share compiled programs
        self.bufferpool = None
        # learned-stats store (plan/feedback.py), created lazily by
        # feedback.store_for — same anchoring rationale: sketches learned
        # by one session serve every session over the same store root
        self.feedback = None

    def clear(self) -> None:
        with self.generic_lock:
            self.generic.clear()
        with self.rung_lock:
            self.rung.clear()
        with self.joinindex_lock:
            self.joinindex.clear()
        pool = self.bufferpool
        if pool is not None:
            pool.clear()

    def snapshot(self) -> dict:
        out = {
            "kind": self.kind,
            "generic_skeletons": len(self.generic),
            "rung_entries": len(self.rung),
            "join_index_entries": len(self.joinindex),
        }
        pool = self.bufferpool
        if pool is not None:
            out["bufferpool"] = pool.snapshot()
        fb = self.feedback
        if fb is not None:
            out["feedback"] = fb.snapshot()
        return out


_tier_lock = threading.Lock()
_store_scopes: dict[str, CacheScope] = {}
# process-lifetime bound on retained store scopes (LRU): evicting one
# only forfeits cached programs for sessions opened LATER against that
# root — existing sessions keep their scope object, and correctness
# never depends on scope identity (keys are self-describing)
_STORE_SCOPES_MAX = 16
_uid_counter = itertools.count(1)


def scope_for(session) -> CacheScope:
    """The session's cache scope, created on first use. Store-backed
    sessions with ``config.sched.shared_cache`` share the per-root scope;
    everything else is private. Sessions cache the result
    (``session._cache_scope``) — Session.__init__ calls this once."""
    scope = getattr(session, "_cache_scope", None)
    if scope is not None:
        return scope
    if session.store is not None and session.config.sched.shared_cache:
        root = str(session.config.storage.root)
        with _tier_lock:
            scope = _store_scopes.pop(root, None)
            if scope is None:
                scope = CacheScope("store", root)
            _store_scopes[root] = scope  # LRU touch
            while len(_store_scopes) > _STORE_SCOPES_MAX:
                _store_scopes.pop(next(iter(_store_scopes)))
    else:
        scope = CacheScope("session", id(session))
    session._cache_scope = scope
    return scope


def _uid(obj) -> int:
    """Process-unique, never-reused id for a table object (or any
    object), stamped lazily — the private-key component that makes
    object-bound entries collision-free inside a shared scope (plain
    ``id()`` is reused after GC)."""
    u = getattr(obj, "_cache_uid", None)
    if u is None:
        with _tier_lock:
            u = getattr(obj, "_cache_uid", None)
            if u is None:
                u = next(_uid_counter)
                try:
                    obj._cache_uid = u
                except AttributeError:  # __slots__ or frozen: fall back
                    return id(obj)
    return u


def session_uid(session) -> int:
    return _uid(session)


_config_uids: dict[int, tuple] = {}  # id(cfg) -> (uid, weakref)


def config_uid(cfg) -> int:
    """Process-unique token for a Config OBJECT (frozen dataclasses
    reject attribute stamping, and a bare id() could be reused after
    GC): the config-epoch component for shared cache keys — programs
    bake config knobs (packed wire, pallas, ...), so entries built
    under different Config objects must never collide."""
    with _tier_lock:
        ent = _config_uids.get(id(cfg))
        if ent is not None and ent[1]() is cfg:
            return ent[0]
        u = next(_uid_counter)
        _config_uids[id(cfg)] = (u, weakref.ref(cfg))
        return u


def table_key(session, name: str):
    """Content-stable identity token for one table, suitable as a shared
    cache-key component. Raises KeyError for unknown tables (mirroring
    Session._table_versions so callers keep their existing handling)."""
    t = session.catalog.tables.get(name)
    if t is None:
        raise KeyError(name)
    scope = scope_for(session)
    if scope.kind == "session":
        # private scope: the pre-tier key (per-session dict ⇒ names
        # suffice; versions bump on every set_data/ANALYZE)
        return (name, getattr(t, "_version", 0),
                getattr(t, "_stats_version", 0))
    sv = getattr(t, "_store_version", None)
    if sv is not None and getattr(session, "_txn_snapshot", None) is None:
        # store-backed outside a transaction: the store version IS the
        # content (manifests are immutable; any commit — data, stats,
        # recreate — publishes a new version)
        return (name, "sv", sv)
    # in-RAM table / mid-transaction state: bind to this table OBJECT so
    # the entry is private even in a shared scope
    return (name, "uid", _uid(t), getattr(t, "_version", 0),
            getattr(t, "_stats_version", 0))


def table_versions(session, names):
    """Tuple of table_key tokens for a sorted name list (the shared-tier
    replacement for Session._table_versions in cache guards)."""
    return tuple(table_key(session, n) for n in names)


def topology_token(session) -> int:
    """The session's current topology-epoch id (parallel/topology.py) —
    carried by EVERY shared-tier key so a program compiled under an
    earlier epoch can never serve after a cutover, even when every
    other identity component aliases (same nseg after a failover/recover
    round trip, a reused config uid, an unchanged table version)."""
    from cloudberry_tpu.parallel.topology import topology_token as _tt

    return _tt(session)


def plan_epoch(session) -> tuple:
    """The non-table part of a generic plan's validity: the process-wide
    UDF registry version always, plus the TOPOLOGY EPOCH TOKEN (a
    cutover orphans every earlier epoch's programs); the catalog ddl
    counter only for private scopes (shared scopes rely on the full
    structural signature — ddl counters are per-catalog and would just
    block sharing)."""
    from cloudberry_tpu.exec.udf import registry_version

    scope = scope_for(session)
    if scope.kind == "session":
        return ("local", topology_token(session),
                session.catalog.ddl_version, registry_version())
    return ("store", topology_token(session), registry_version())


def rung_scope_token(session) -> tuple:
    """Key prefix for rung-executable entries. Rung programs have no
    structural signature beyond (query text, versions, motion rungs), so
    cross-session sharing is only sound when the plan is a pure function
    of store content AND config: any catalog with session-local views
    keeps its entries scoped to its own ddl generation, and the shared
    branch carries the config uid (programs bake packed-wire/pallas/...
    knobs — the config-epoch guard the sibling caches get from object
    identity)."""
    scope = scope_for(session)
    if scope.kind == "store" and not session.catalog.views:
        return ("shared", topology_token(session),
                config_uid(session.config))
    return ("cat", topology_token(session), session_uid(session),
            session.catalog.ddl_version)


def tier_snapshot(session) -> dict:
    """Observability for serve/meta.py: this session's scope."""
    scope = scope_for(session)
    out = scope.snapshot()
    out["shared"] = scope.kind == "store"
    return out
