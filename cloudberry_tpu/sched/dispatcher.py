"""Continuous micro-batch dispatcher — the gang-dispatch analog.

A bounded async request queue in front of a serving Session. Handler
threads ``submit()`` statements and block on their result; ONE worker
thread drains the queue each tick, groups requests by statement skeleton
(sched/paramplan.normalize), and executes each group:

- same-skeleton groups flush as ONE stacked (vmapped) launch through the
  group's generic plan (paramplan.run_batch) — per-request host work is a
  tokenize-only fast rebind (point lookups) or a sub-millisecond re-plan,
  and the XLA launch cost amortizes across the batch;
- everything else (non-parameterizable statements, writes, shape drift
  mid-batch) falls back to ordinary sequential ``session.sql``.

Flow control mirrors the reference's interconnect discipline: the queue is
BOUNDED (backpressure — a full queue rejects enqueues after a short wait,
SchedQueueFull), every request carries a deadline (expired requests fail
WITHOUT executing, SchedDeadline), and executions feed the session's
existing admission gate (exec/resource.py) — the dispatcher adds
coalescing, never a second admission authority.

FAULT_POINTs at the three seams: ``sched_enqueue`` (request admission to
the queue), ``sched_coalesce`` (group formation), ``sched_flush`` (the
batched launch, armed inside paramplan.run_batch).
"""

from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from cloudberry_tpu.sched import paramplan


class SchedQueueFull(RuntimeError):
    """Backpressure: the bounded request queue stayed full past the
    enqueue grace period."""


class SchedDeadline(RuntimeError):
    """The request's deadline expired before (or while) it executed."""


@dataclass
class _Request:
    sql: str
    deadline: float                  # monotonic absolute
    # enqueue timestamp (perf_counter): the dispatch-queue-wait span /
    # stage histogram measures pick-time minus this (obs/trace.py)
    t_enq: float = field(default_factory=time.perf_counter)
    done: threading.Event = field(default_factory=threading.Event)
    result: Any = None
    error: Optional[BaseException] = None
    # async completion (event-loop serving, serve/asyncore.py): called
    # exactly once with this request after result/error are set, from
    # whichever thread finished it
    on_done: Optional[Any] = None
    # tenancy bookkeeping: the scheduler that picked this request and
    # the TenantGroup charged for it (stamped at pick time)
    _sched: Optional[Any] = None
    _tenant_group: Optional[Any] = None
    _finish_lock: threading.Lock = field(default_factory=threading.Lock)
    _finished: bool = False

    def finish(self, result=None, error=None):
        with self._finish_lock:
            # atomic test-and-set: stop()'s sweep and the enqueue/stop
            # race may both reach a request — on_done must fire ONCE
            if self._finished:
                return
            self._finished = True
        self.result = result
        self.error = error
        self.done.set()
        g, self._tenant_group = self._tenant_group, None
        if g is not None and self._sched is not None:
            self._sched.finish(g)
        if self.on_done is not None:
            try:
                self.on_done(self)
            except Exception:
                pass  # a dead connection must not poison the worker


class Dispatcher:
    """One worker thread coalescing a session's read statements.

    ``exec_scope`` (optional): a zero-argument callable returning a
    context manager held around every execution — the server passes its
    shared-session read-lock scope so dispatched reads keep excluding
    concurrent catalog writers exactly like direct dispatch does.

    ``tenancy`` (optional): a sched/tenancy.TenantScheduler. With it,
    requests land in per-tenant bounded queues and each tick picks the
    batch in deficit-weighted-round-robin order with starvation-free
    aging — fair throughput under saturation instead of FIFO.
    """

    def __init__(self, session, exec_scope=None, tenancy=None):
        self.session = session
        cfg = session.config.sched
        self.max_batch = max(1, cfg.max_batch)
        self.max_queue = max(1, cfg.max_queue)
        self.tick_s = max(0.0, cfg.tick_s)
        self.deadline_s = cfg.deadline_s
        self._exec_scope = exec_scope or contextlib.nullcontext
        self.tenancy = tenancy
        self._q: list[_Request] = []
        self._cond = threading.Condition()
        self._stop = False
        self._busy = False          # worker mid-batch (drain observability)
        self._thread: Optional[threading.Thread] = None
        self.stats = {
            "enqueued": 0, "rejected": 0, "expired": 0, "cancelled": 0,
            "batches": 0, "batched_requests": 0, "singles": 0,
            "seq_fallbacks": 0, "occupancy_sum": 0.0, "max_depth": 0,
        }
        # the serving layer reads queue/batch observability through the
        # session (serve/meta.py "sched")
        session._dispatcher = self

    # ------------------------------------------------------------ control

    def start(self) -> "Dispatcher":
        if self._thread is None:
            with self._cond:
                # published under the lock: a submitter blocked on a
                # stopped queue must never miss the restart flip
                self._stop = False
            self._thread = threading.Thread(target=self._loop,
                                            daemon=True,
                                            name="cbtpu-dispatcher")
            self._thread.start()
        return self

    def stop(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        # nothing may block forever on a dead worker: whatever drain()
        # could not finish fails with the RETRYABLE drain error — an
        # accepted request is answered or failed, never silently dropped
        from cloudberry_tpu.lifecycle import ServerDraining

        for _ in range(2):  # second sweep closes the enqueue/stop race
            with self._cond:
                pending, self._q = self._q, []
            if self.tenancy is not None:
                pending += self.tenancy.pending()
            if not pending:
                break
            for r in pending:
                r.finish(error=ServerDraining(
                    "dispatcher stopped while this request was queued; "
                    "retry against the serving primary"))

    def _bump(self, name: str, n=1) -> None:
        """Worker-side stats updates take the lock too: handler threads
        bump enqueued/rejected under _cond, and snapshot() copies under
        it — a bare += here would be a racy read-modify-write. Counters
        mirror onto the engine metrics registry (``disp_<name>``) so the
        Prometheus exposition sees dispatcher traffic without a snapshot
        call; the stats dict stays authoritative for snapshot()."""
        with self._cond:
            self.stats[name] += n
        self.session.stmt_log.bump(f"disp_{name}", n)

    def _mirror(self, name: str, n: int = 1) -> None:
        """Registry mirror for counters whose stats-dict update happens
        inline under _cond (enqueued/rejected/batches/...): the metric
        plane must see queue traffic and backpressure, not just the
        worker-side names _bump covers. The registry lock is a leaf
        below _cond in the declared order, so calling under _cond is
        safe."""
        self.session.stmt_log.bump(f"disp_{name}", n)

    def queue_depth(self) -> int:
        with self._cond:
            depth = len(self._q)
        if self.tenancy is not None:
            depth += self.tenancy.depth()
        return depth

    def drain(self, timeout_s: float) -> bool:
        """Wait until the queue is empty AND the worker is idle — every
        accepted request has been answered (the smart-shutdown wait).
        Returns False when work remains at the timeout (the caller then
        cancels stragglers; nothing is ever silently dropped — stop()
        fails whatever is still queued)."""
        end = time.monotonic() + max(0.0, timeout_s)
        with self._cond:
            while self._pending_depth() or self._busy:
                left = end - time.monotonic()
                if left <= 0:
                    return False
                self._cond.wait(timeout=min(left, 0.1))
        return True

    def _pending_depth(self) -> int:
        """Queued requests across the global and tenant queues (callers
        hold self._cond; the tenancy lock nests safely below it)."""
        depth = len(self._q)
        if self.tenancy is not None:
            depth += self.tenancy.depth()
        return depth

    # ------------------------------------------------------------- submit

    def _enqueue(self, req: _Request, tenant: Optional[str],
                 wait_s: float) -> None:
        """Admit one request (global or tenant queue), with the grace
        wait and the retryable refusals. Raises SchedQueueFull /
        TenantQueueFull / ServerDraining."""
        from cloudberry_tpu.utils.faultinject import fault_point

        fault_point("sched_enqueue")
        from cloudberry_tpu.lifecycle import ServerDraining

        if self.tenancy is not None:
            with self._cond:
                if self._stop:
                    raise ServerDraining("dispatcher stopped")
            req._sched = self.tenancy
            try:
                self.tenancy.enqueue(tenant, req, wait_s=wait_s)
            except Exception:
                with self._cond:
                    self.stats["rejected"] += 1
                self._mirror("rejected")
                raise
            self._mirror("enqueued")
            with self._cond:
                self.stats["enqueued"] += 1
                self.stats["max_depth"] = max(self.stats["max_depth"],
                                              self._pending_depth())
                stopped = self._stop
                self._cond.notify_all()
            if stopped:
                # raced a concurrent stop(): fail visibly (idempotent
                # finish — stop()'s own sweep may also reach it)
                req.finish(error=ServerDraining(
                    "dispatcher stopped while this request was queued; "
                    "retry against the serving primary"))
            return
        with self._cond:
            end = time.monotonic() + wait_s
            while len(self._q) >= self.max_queue and not self._stop:
                left = end - time.monotonic()
                if left <= 0:
                    self.stats["rejected"] += 1
                    self._mirror("rejected")
                    raise SchedQueueFull(
                        f"dispatcher queue full ({self.max_queue} "
                        "requests waiting); retry or raise "
                        "config.sched.max_queue")
                self._cond.wait(timeout=left)
            if self._stop:
                raise ServerDraining("dispatcher stopped")
            self._q.append(req)
            self.stats["enqueued"] += 1
            self.stats["max_depth"] = max(self.stats["max_depth"],
                                          len(self._q))
            self._cond.notify_all()
        self._mirror("enqueued")

    def submit(self, sql: str, deadline_s: Optional[float] = None,
               enqueue_wait_s: float = 0.25,
               tenant: Optional[str] = None):
        """Run one statement through the dispatcher; blocks until its
        result is ready. Raises SchedQueueFull / TenantQueueFull
        (backpressure) or SchedDeadline; other execution errors re-raise
        as-is."""
        budget = self.deadline_s if deadline_s is None else deadline_s
        req = _Request(sql, time.monotonic() + budget)
        self._enqueue(req, tenant, enqueue_wait_s)
        req.done.wait(timeout=budget + 60.0)
        if not req.done.is_set():
            raise SchedDeadline(f"request did not finish within "
                                f"{budget + 60.0:.0f}s")
        if req.error is not None:
            raise req.error
        return req.result

    def submit_nowait(self, sql: str, deadline_s: Optional[float] = None,
                      tenant: Optional[str] = None,
                      on_done=None) -> _Request:
        """Non-blocking submission for the event-loop front end: admit
        (refusing IMMEDIATELY on a full queue — the caller's client
        retries on the retryable taxonomy) and return; ``on_done(req)``
        fires once when the request finishes, from the finishing
        thread."""
        budget = self.deadline_s if deadline_s is None else deadline_s
        req = _Request(sql, time.monotonic() + budget, on_done=on_done)
        self._enqueue(req, tenant, wait_s=0.0)
        return req

    # ------------------------------------------------------------- worker

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._pending_depth() and not self._stop:
                    self._cond.wait(timeout=0.5)
                if self._stop:
                    return
            # coalescing window: give same-skeleton company a tick to
            # arrive (continuous batching — the queue keeps filling while
            # the previous batch executes, so a loaded server rarely
            # actually sleeps here)
            if self.tick_s:
                with self._cond:
                    deadline = time.monotonic() + self.tick_s
                    while self._pending_depth() < self.max_batch \
                            and not self._stop:
                        left = deadline - time.monotonic()
                        if left <= 0:
                            break
                        self._cond.wait(timeout=left)
            if self.tenancy is not None:
                # fair pick: deficit-weighted round robin with aging —
                # WHOSE requests flush this tick is the tenancy policy,
                # the skeleton grouping below stays workload-driven.
                # _busy flips BEFORE the pick: pick() drains the tenant
                # queues, and drain() must never observe depth==0 with
                # an unprocessed batch in hand
                with self._cond:
                    self._busy = True
                batch = self.tenancy.pick(self.max_batch)
                with self._cond:
                    self._busy = bool(batch)
                    self._cond.notify_all()
                if not batch:
                    # queued tenants all at max_concurrency (direct-path
                    # statements hold their slots): back off briefly
                    time.sleep(min(0.02, self.tick_s or 0.02))
                    continue
            else:
                with self._cond:
                    batch, self._q = self._q, []
                    self._busy = bool(batch)
                    self._cond.notify_all()  # wake blocked submitters
            if batch:
                try:
                    self._process(batch)
                except BaseException as e:  # never kill the worker
                    for r in batch:
                        if not r.done.is_set():
                            r.finish(error=e)
                finally:
                    with self._cond:
                        self._busy = False
                        self._cond.notify_all()  # wake drain waiters

    def _groups(self, batch: list[_Request]):
        """Group same-skeleton requests, preserving arrival order within
        a group; non-parameterizable statements ride alone."""
        groups: dict = {}
        order: list = []
        for r in batch:
            norm = paramplan.normalize(r.sql)
            key = (norm[0],) if norm is not None and norm[1] \
                else ("solo", id(r))
            if key not in groups:
                groups[key] = []
                order.append(key)
            groups[key].append(r)
        return [groups[k] for k in order]

    def _process(self, batch: list[_Request]) -> None:
        from cloudberry_tpu.utils.faultinject import fault_point

        fault_point("sched_coalesce")
        for group in self._groups(batch):
            live: list[_Request] = []
            now = time.monotonic()
            for r in group:
                if now > r.deadline:
                    self._bump("expired")
                    r.finish(error=SchedDeadline(
                        "deadline expired before dispatch"))
                else:
                    live.append(r)
            if not live:
                continue
            while live:
                chunk, live = live[:self.max_batch], live[self.max_batch:]
                self._run_group(chunk)

    def _flight(self, req: _Request, handle, status: str,
                error=None, result=None) -> None:
        """Flight-recorder seam for the batched path (obs/flightrec.py):
        batched statements finish here, not in session.sql, so the
        slow/error capture contract must fire here too. The wall is the
        handle's own clock — pick-to-finish, the window the member's
        deadline governs."""
        from cloudberry_tpu.obs import flightrec as OF

        OF.maybe_capture(self.session, req.sql, status,
                         time.monotonic() - handle.started, handle,
                         error=error, result=result)

    def _run_group(self, group: list[_Request]) -> None:
        from cloudberry_tpu import lifecycle

        log = self.session.stmt_log
        if len(group) > 1:
            # every batched request gets its own lifecycle handle in the
            # activity view (cancellable by id, watchdog-visible); the
            # stacked launch runs under a composite scope polling all of
            # them at the flush/tile seams. config.statement_timeout_s
            # tightens each deadline here because run_batch bypasses
            # session.sql — the two dispatcher paths must enforce the
            # same limit for the same statement
            timeout = self.session.config.statement_timeout_s
            t_dl = (time.monotonic() + timeout) if timeout else None

            def _dl(r):
                return r.deadline if t_dl is None \
                    else min(r.deadline, t_dl)

            sids = [log.begin(r.sql) for r in group]
            handles = [lifecycle.StatementHandle(sid, deadline=_dl(r))
                       for sid, r in zip(sids, group)]
            # topology epoch at batch formation (parallel/topology.py):
            # a cutover/failover landing mid-launch is detected below
            # and the batch re-routes sequentially instead of failing
            # every member with a raw shape/device error
            from cloudberry_tpu.parallel.topology import topology_token

            topo_tok = topology_token(self.session)
            now = time.perf_counter()
            from cloudberry_tpu.obs import metrics as OM

            from cloudberry_tpu.obs.progress import Progress

            for sid, h, r in zip(sids, handles, group):
                log.attach(sid, h)
                # batched statements bypass session.sql, so their traces
                # start here; the queue wait each member just finished is
                # its first span (recorded on the member's own trace).
                # Each member gets its own Progress too — stacked point
                # reads have no tile loop, but the 0→1 completion keeps
                # meta "progress" rows uniform across dispatch paths
                h.trace = log.start_trace(sid, r.sql)
                if log.obs_enabled:
                    h.progress = Progress()
                if h.trace is not None:
                    # ends exactly at the trace's root start, so the
                    # wait renders as the root's sibling, never a
                    # partial overlap
                    h.trace.add("dispatch-queue-wait", r.t_enq,
                                max(h.trace.t0 - r.t_enq, 0.0))
                OM.observe_stage(log, "queue_wait", now - r.t_enq)
            c0 = log.counter("compiles")
            g0 = log.counter("generic_hits")
            try:
                with self._exec_scope(), lifecycle.statement_scope(
                        lifecycle.CompositeHandle(handles)):
                    out = paramplan.run_batch(self.session,
                                              [r.sql for r in group])
            except lifecycle.StatementError:
                # a member's cancel/timeout aborted the stacked launch:
                # that member fails with ITS verdict; innocent batchmates
                # re-route through the sequential path below
                survivors: list[_Request] = []
                for r, sid, h in zip(group, sids, handles):
                    err = None
                    try:
                        h.check()
                    except lifecycle.StatementError as e:
                        err = e
                    if err is not None:
                        self._bump("cancelled")
                        log.finish(sid, "error",
                                   error=f"{type(err).__name__}: {err}")
                        self._flight(r, h, "error", error=err)
                        r.finish(error=err)
                    else:
                        log.finish(sid, "requeued")
                        survivors.append(r)
                if survivors:
                    # straight to sequential dispatch: this is a cancel
                    # abort, not a generic-plan miss — it must not count
                    # as (or re-log) a seq_fallback
                    self._run_sequential(survivors)
                return
            except BaseException as e:
                from cloudberry_tpu.parallel.health import recoverable
                from cloudberry_tpu.parallel.topology import \
                    TopologyRaceError

                if recoverable(e) or isinstance(e, TopologyRaceError) \
                        or topology_token(self.session) != topo_tok:
                    # device loss, or a topology flip raced the stacked
                    # launch: batched statements are READS, so re-route
                    # them through session.sql, whose retry machinery
                    # replans at the current epoch — the singles path
                    # already survives the same flip, and a batch must
                    # not drop every member where one statement would
                    # have recovered
                    self._bump("batch_reroutes")
                    for sid in sids:
                        log.finish(sid, "requeued")
                    self._run_sequential(group)
                    return
                for r, sid, h in zip(group, sids, handles):
                    log.finish(sid, "error",
                               error=f"{type(e).__name__}: {e}")
                    self._flight(r, h, "error", error=e)
                    r.finish(error=e)
                return
            if out is not None:
                with self._cond:
                    self.stats["batches"] += 1
                    self.stats["batched_requests"] += len(group)
                    self.stats["occupancy_sum"] += \
                        len(group) / paramplan._next_pow2(len(group))
                self._mirror("batches")
                self._mirror("batched_requests", len(group))
                # a flush that built a generic plan or a new rung DID
                # compile — attribute the delta to the batch head so the
                # per-statement compiles= field never under-reports.
                # generic_hits attribute the same way: every non-head
                # member is exactly one reuse (fast or re-planned), the
                # head gets the remainder (0 when it built the plan) —
                # per-statement sums stay equal to the engine counter
                compiled = log.counter("compiles") - c0
                ghead = max(log.counter("generic_hits") - g0
                            - (len(group) - 1), 0)
                for i, (r, sid, h, batch) in enumerate(
                        zip(group, sids, handles, out)):
                    log.finish(sid, "ok", rows=batch.num_rows(),
                               batch=len(group),
                               compiles=compiled if i == 0 else 0,
                               generic_hits=ghead if i == 0 else 1)
                    self._flight(r, h, "ok", result=batch)
                    r.finish(result=batch)
                return
            self._bump("seq_fallbacks")
            for sid in sids:
                log.finish(sid, "requeued")  # re-logged by session.sql
        self._run_sequential(group)

    def _run_sequential(self, group: list[_Request]) -> None:
        """Ordinary dispatch, one statement at a time."""
        from cloudberry_tpu.obs import metrics as OM

        for r in group:
            if time.monotonic() > r.deadline:
                self._bump("expired")
                r.finish(error=SchedDeadline(
                    "deadline expired before dispatch"))
                continue
            self._bump("singles")
            OM.observe_stage(self.session.stmt_log, "queue_wait",
                             time.perf_counter() - r.t_enq)
            try:
                with self._exec_scope():
                    # the request's deadline governs EXECUTION too (the
                    # session checks it at its cancel seams), not just
                    # time-in-queue
                    r.finish(result=self.session.sql(
                        r.sql, _deadline=r.deadline))
            except BaseException as e:
                r.finish(error=e)

    def snapshot(self) -> dict:
        """Observability snapshot for serve/meta.py."""
        with self._cond:
            depth = len(self._q)
            st = dict(self.stats)
        occ = st.pop("occupancy_sum")
        st["avg_occupancy"] = round(occ / st["batches"], 4) \
            if st["batches"] else 0.0
        if self.tenancy is not None:
            depth += self.tenancy.depth()
            st["tenants"] = self.tenancy.snapshot()
            st["fairness_index"] = round(self.tenancy.fairness_index(), 4)
        st["queue_depth"] = depth
        st["max_batch"] = self.max_batch
        st["max_queue"] = self.max_queue
        return st
