"""Parameterized generic plans — the plan_cache.c analog.

``Session._stmt_cache`` keys on exact SQL text, so ``WHERE k = 42`` and
``WHERE k = 99`` each pay a full parse→plan→XLA-compile even though they
need the same program. This module makes same-shape statements share one
compiled executable:

1. ``normalize`` lexes the statement and hoists constant literals into a
   parameter vector, producing a SKELETON string (the cache key) — the
   query-fingerprint normalization of plan_cache.c's generic plans.
2. On first execution of a skeleton, the freshly bound plan's
   filter/project literals are rewritten to ``expr.Param`` slots and the
   program is compiled with a ``$params`` input; the literal VALUES travel
   as device inputs.
3. On a later execution with different literals, the statement is re-bound
   (host-only, sub-millisecond) and its plan's STRUCTURAL SIGNATURE is
   compared with the cached generic plan's; on a match the new literal
   values (and point-lookup row slices / direct-dispatch segment) bind
   into the existing program — ZERO XLA compiles.

Plans that fold literals into plan STRUCTURE — nextval (plan-time sequence
allocation, ``_no_stmt_cache``), literal-dependent partition pruning
(``_store_parts``), a point lookup whose match count changed, a
direct-dispatch row-count change — are non-generic by construction: the
signature (or the ``_no_stmt_cache`` gate) refuses the rebind and the
statement keeps today's compile-per-text path.

The signature deliberately captures everything the TRACE bakes in: node
shapes and capacities, baked literal values outside param sites, DictLookup
table contents (string-predicate lookup tables are literal-derived),
dictionary identity for collation rank tables (guarded by table versions),
and shared-subtree (PShare) topology.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

from cloudberry_tpu.plan import expr as ex
from cloudberry_tpu.plan import nodes as N
from cloudberry_tpu.sql.lexer import LexError, tokenize
from cloudberry_tpu.types import DType, SqlType


class UnsupportedPlan(Exception):
    """The plan contains a shape the generic-plan walker does not model —
    the statement silently keeps the non-generic path."""


# ------------------------------------------------------------- skeletons


_PARAM_HEADS = ("select", "with", "(")
# literals after these keywords are STRUCTURAL (plan shape / bind-time
# folds), never parameters: LIMIT/OFFSET become static node fields and
# INTERVAL quantities fold into date arithmetic at bind time
_KEEP_AFTER = ("limit", "offset", "interval")


def normalize(sql: str):
    """(skeleton, literal texts) for a parameterizable statement, else
    None. The skeleton is the token stream with number/string literals
    replaced by kind-tagged placeholders — same-shape statements collide
    on it regardless of their literal values."""
    head = sql.lstrip()[:1]
    if not head:
        return None
    first = sql.split(None, 1)[0].lower() if head != "(" else "("
    if first not in _PARAM_HEADS:
        return None
    try:
        toks = tokenize(sql)
    except LexError:
        return None
    parts: list[str] = []
    params: list[str] = []
    prev = ""
    for t in toks:
        if t.kind == "number" and prev not in _KEEP_AFTER:
            params.append(t.text)
            parts.append("?n")
        elif t.kind == "string" and prev not in _KEEP_AFTER:
            params.append(t.text)
            parts.append("?s")
        elif t.kind == "string":
            parts.append(f"'{t.text}'")
        elif t.kind != "eof":
            parts.append(t.text)
        prev = t.text if t.kind == "ident" else ""
    return " ".join(parts), tuple(params)


# ------------------------------------------------------- plan signatures


def _tsig(t: Optional[SqlType]):
    if t is None:
        return None
    return (t.base.value, t.scale)


def _pyval(v) -> Any:
    """Baked literal value as a hashable python scalar."""
    if isinstance(v, str):
        return v
    try:
        return np.asarray(v).item()
    except (TypeError, ValueError):
        return repr(v)


def _param_scalar(e: ex.Literal) -> bool:
    """Literal eligible to travel as a device input: a numeric/bool/date
    scalar (strings stay baked — their plan effect is DictLookup tables,
    whose contents the signature hashes)."""
    if isinstance(e.value, str):
        return False
    try:
        np.asarray(e.value, dtype=e.dtype.np_dtype)
    except (TypeError, ValueError, OverflowError):
        return False
    return np.ndim(e.value) == 0


class _Walker:
    """One canonical walk shared by signature building, parameter-slot
    numbering, binding extraction, and the literal→Param rewrite: every
    consumer MUST see nodes, expression sites, and literals in the same
    order, or rebinding would feed values into the wrong slots."""

    def __init__(self, session, rewrite: bool = False):
        self.rewrite = rewrite
        self.slots: list[SqlType] = []
        self.bindings: dict[str, np.ndarray] = {}
        self.keyed: list[N.PScan] = []
        self._nrw = 0  # scan row-count parameter slots ($nrw<i>)
        self._memo: dict[int, int] = {}
        # table-owned dictionaries are version-pinned (any content change
        # bumps the table version) — only literal-derived dictionaries
        # need content hashing in the signature
        self._table_dicts = {
            id(d)
            for t in session.catalog.tables.values()
            for d in getattr(t, "dicts", {}).values()}

    # ------------------------------------------------------- expressions

    def esig(self, e: Optional[ex.Expr], paramable: bool):
        """(signature, possibly-rewritten expr) for one expression."""
        if e is None:
            return None, None
        if isinstance(e, ex.Literal):
            if paramable and _param_scalar(e):
                slot = len(self.slots)
                self.slots.append(e.dtype)
                key = f"$prm{slot}"
                self.bindings[key] = np.asarray(e.value,
                                                dtype=e.dtype.np_dtype)
                # the Param KEEPS the literal: the baked fallback for a
                # non-generic recompile (growth retry) and the binding
                # source when a rewritten plan is re-analyzed
                new = ex.Param(slot, e.dtype, e.value) if self.rewrite \
                    else e
                return ("P", _tsig(e.dtype)), new
            return ("L", _tsig(e.dtype), _pyval(e.value)), e
        if isinstance(e, ex.Param):
            # re-analysis of an already-rewritten plan (the expansion-growth
            # retry re-enters the generic gate with the same plan object):
            # the Param's kept build-time value IS the binding
            if not paramable or e.value is None:
                raise UnsupportedPlan("Param at a non-parameter site")
            slot = len(self.slots)
            self.slots.append(e.dtype)
            key = f"$prm{slot}"
            self.bindings[key] = np.asarray(e.value,
                                            dtype=e.dtype.np_dtype)
            new = ex.Param(slot, e.dtype, e.value) if self.rewrite else e
            return ("P", _tsig(e.dtype)), new
        if isinstance(e, ex.ColumnRef):
            return ("C", e.name, _tsig(e.dtype)), e
        if isinstance(e, ex.BinOp):
            ls, ln = self.esig(e.left, paramable)
            rs, rn = self.esig(e.right, paramable)
            new = ex.BinOp(e.op, ln, rn, e.dtype) if self.rewrite else e
            return ("B", e.op, _tsig(e.dtype), ls, rs), new
        if isinstance(e, ex.UnaryOp):
            s, n = self.esig(e.operand, paramable)
            new = ex.UnaryOp(e.op, n, e.dtype) if self.rewrite else e
            return ("U", e.op, _tsig(e.dtype), s), new
        if isinstance(e, ex.Cast):
            s, n = self.esig(e.operand, paramable)
            new = ex.Cast(n, e.dtype) if self.rewrite else e
            return ("T", _tsig(e.operand.dtype), _tsig(e.dtype), s), new
        if isinstance(e, ex.Func):
            # scale_down's k literal is consumed at COMPILE time
            # (expr_compile reads e.args[1].value) — args stay baked
            sub_param = paramable and e.name != "scale_down"
            sigs, news = [], []
            for a in e.args:
                s, n = self.esig(a, sub_param)
                sigs.append(s)
                news.append(n)
            new = ex.Func(e.name, tuple(news), e.dtype) if self.rewrite \
                else e
            return ("F", e.name, _tsig(e.dtype), tuple(sigs)), new
        if isinstance(e, ex.CaseWhen):
            sigs, news = [], []
            for c, v in e.whens:
                cs, cn = self.esig(c, paramable)
                vs, vn = self.esig(v, paramable)
                sigs.append((cs, vs))
                news.append((cn, vn))
            os_, on = self.esig(e.otherwise, paramable)
            new = ex.CaseWhen(tuple(news), on, e.dtype) if self.rewrite \
                else e
            return ("W", _tsig(e.dtype), tuple(sigs), os_), new
        if isinstance(e, ex.DictLookup):
            s, n = self.esig(e.column, False)
            tab = np.asarray(e.table)
            tsig = ("DL", s, str(tab.dtype), tab.shape,
                    hash(tab.tobytes()), self._dictsig(
                        getattr(e, "_out_dict", None)))
            if self.rewrite and n is not e.column:
                out = ex.DictLookup(n, e.table, e.dtype)
                d = getattr(e, "_out_dict", None)
                if d is not None:
                    object.__setattr__(out, "_out_dict", d)
                return tsig, out
            return tsig, e
        if isinstance(e, ex.IsValid):
            return ("V", tuple(e.mask_names), e.negate), e
        if isinstance(e, ex.SubqueryScalar):
            # the subplan lowers inside the same program — recurse; its
            # filter/project literals are param sites like any other
            psig = self.nsig(e.plan)
            return ("SQ", e.mode, _tsig(e.dtype), psig), e
        raise UnsupportedPlan(f"expression {type(e).__name__}")

    def _dictsig(self, d):
        if d is None:
            return None
        if id(d) in self._table_dicts:
            return ("tdict", len(d))
        return ("dict", len(d), hash(tuple(d.values)))

    def _fieldsig(self, node: N.PlanNode):
        return tuple(
            (f.name, _tsig(f.type), f.masks, self._dictsig(f.sdict),
             f._is_null_col)
            for f in node.fields)

    # ------------------------------------------------------------- nodes

    def _site(self, node, attr: str, paramable: bool):
        """Signature one expression attribute; rewrite in place when
        building the generic plan."""
        s, n = self.esig(getattr(node, attr), paramable)
        if self.rewrite and n is not None:
            setattr(node, attr, n)
        return s

    def nsig(self, node: N.PlanNode):
        key = id(node)
        if key in self._memo:
            # shared subtree (PShare / runtime-filter build): reference by
            # first-visit index — topology is part of the program
            return ("ref", self._memo[key])
        self._memo[key] = len(self._memo)
        t = type(node).__name__
        if isinstance(node, N.PScan):
            if hasattr(node, "_point_rows"):
                extra = ("pt", len(node._point_rows))
                self.keyed.append(node)
                nrows = node.num_rows  # the slice length IS the shape
            elif hasattr(node, "_store_parts"):
                extra = ("store",
                         tuple(p["file"] for p in node._store_parts))
                self.keyed.append(node)
                nrows = node.num_rows
            else:
                # whole-table/shard scan: the row count is DATA, not
                # shape — bind it as a parameter so one program serves
                # every direct-dispatch segment (per-segment counts
                # differ; the padded capacity does not)
                extra = None
                nrows = "$param"
                key = f"$nrw{self._nrw}"
                self._nrw += 1
                self.bindings[key] = np.asarray(node.num_rows
                                                if node.num_rows >= 0
                                                else node.capacity,
                                                dtype=np.int64)
                if self.rewrite:
                    node._nrows_key = key
            return (t, node.table_name,
                    tuple(sorted(node.column_map.items())),
                    tuple(sorted(node.mask_map.items())),
                    node.capacity, nrows, extra,
                    self._fieldsig(node))
        if isinstance(node, N.PFilter):
            return (t, self._site(node, "predicate", True),
                    self.nsig(node.child))
        if isinstance(node, N.PProject):
            sigs = []
            for i, (name, e) in enumerate(list(node.exprs)):
                s, n = self.esig(e, True)
                if self.rewrite:
                    node.exprs[i] = (name, n)
                sigs.append((name, s))
            return (t, tuple(sigs), self._fieldsig(node),
                    self.nsig(node.child))
        if isinstance(node, N.PJoin):
            bk = tuple(self.esig(k, False)[0] for k in node.build_keys)
            pk = tuple(self.esig(k, False)[0] for k in node.probe_keys)
            # the join-index slot is structural: a program compiled WITH
            # the cached-sorted-build input cannot serve a plan without
            # it (and vice versa) — the spec key carries table/columns/
            # bits/layout so signature-equal plans want the same input
            jix = getattr(node, "_jix", None)
            return (t, node.kind, tuple(node.build_payload),
                    node.match_name, node.probe_match_name,
                    node.unique_build, node.out_capacity, node.null_aware,
                    node.pack_bits, jix.key if jix is not None else None,
                    bk, pk,
                    self._site(node, "residual", False),
                    self._site(node, "build_key_valid", False),
                    self._site(node, "probe_key_valid", False),
                    self.nsig(node.build), self.nsig(node.probe))
        if isinstance(node, N.PAgg):
            keys = tuple((name, self.esig(e, False)[0])
                         for name, e in node.group_keys)
            aggs = tuple(
                (name, c.func, c.distinct,
                 self.esig(c.arg, False)[0],
                 self.esig(c.filter, False)[0])
                for name, c in node.aggs)
            return (t, node.mode, node.capacity, keys, aggs,
                    self._fieldsig(node), self.nsig(node.child))
        if isinstance(node, N.PSort):
            keys = tuple((self.esig(e, False)[0], asc)
                         for e, asc in node.keys)
            return (t, keys, self._fieldsig(node), self.nsig(node.child))
        if isinstance(node, N.PLimit):
            return (t, node.limit, node.offset, self.nsig(node.child))
        if isinstance(node, N.PWindow):
            pk = tuple(self.esig(e, False)[0] for e in node.partition_keys)
            ok = tuple((self.esig(e, False)[0], asc)
                       for e, asc in node.order_keys)
            calls = tuple((name, func, self.esig(arg, False)[0])
                          for name, func, arg in node.calls)
            valids = tuple(self.esig(v, False)[0]
                           for v in (node.valids or ()))
            params = tuple(
                None if p is None else tuple(
                    (k, self.esig(v, False)[0]
                     if isinstance(v, ex.Expr) else v)
                    for k, v in sorted(p.items()))
                for p in (node.params or ()))
            return (t, pk, ok, calls, valids, params, node.frame,
                    self._fieldsig(node), self.nsig(node.child))
        if isinstance(node, N.PShare):
            return (t, self.nsig(node.child))
        if isinstance(node, N.PConcat):
            return (t, tuple(self.nsig(c) for c in node.inputs),
                    self._fieldsig(node))
        if isinstance(node, N.PRuntimeFilter):
            bk = tuple(self.esig(k, False)[0] for k in node.build_keys)
            pk = tuple(self.esig(k, False)[0] for k in node.probe_keys)
            # digest slots (mode + bloom geometry) are structural: the
            # traced collective and bitmap shapes differ per mode
            return (t, node.pack_bits, node.mode, node.bloom_bits,
                    node.bloom_k, bk, pk, self.nsig(node.build),
                    self.nsig(node.child))
        if isinstance(node, N.PMotion):
            hk = tuple(self.esig(k, False)[0] for k in node.hash_keys)
            return (t, node.kind, node.out_capacity, node.bucket_cap,
                    node.pre_compact, hk, self._fieldsig(node),
                    node.host_bucket_cap, node.hier_hosts,
                    node.host_combine, self.nsig(node.child))
        raise UnsupportedPlan(f"node {t}")


def analyze(session, plan: N.PlanNode, rewrite: bool = False):
    """(signature, bindings, keyed scans, slot types) for a bound plan.
    ``rewrite=True`` (generic-plan build only) additionally replaces every
    parameter-site literal with its ``expr.Param`` slot IN PLACE."""
    w = _Walker(session, rewrite=rewrite)
    root = ("root", w.nsig(plan),
            getattr(plan, "_direct_segment", None) is not None,
            w._fieldsig(plan))
    return root, w.bindings, w.keyed, w.slots


# ------------------------------------------------------ the generic plan


def _next_pow2(n: int) -> int:
    return 1 << max(0, (int(n) - 1).bit_length())


def _text_converter(t: SqlType):
    """Literal token text → physical value, matching the binder's typed
    conversions (the fast-rebind contract is validated at build time:
    converter(build text) must equal the plan's bound literal)."""
    from cloudberry_tpu.plan.planner import _exact_decimal
    from cloudberry_tpu.types import date_to_days

    if t.base in (DType.INT32, DType.INT64):
        return lambda s: int(s)
    if t.base == DType.DECIMAL:
        return lambda s, k=t.scale: _exact_decimal(s, k)
    if t.base == DType.FLOAT64:
        return lambda s: float(s)
    if t.base == DType.DATE:
        return lambda s: date_to_days(s)
    return None


@dataclass
class FastRebind:
    """Tokenize-only rebinding for the canonical point-lookup shape
    (``WHERE k = ?`` on an indexed column): skip parse/bind/plan entirely
    — convert the literal text, sidecar-search the rows, slice the scan
    input, feed the value as the one parameter. The dispatcher's batch
    path leans on this to make per-request host work ~microseconds."""

    table: str
    phys: str
    sqltype: SqlType
    expect_rows: int
    input_key: str
    param_key: Optional[str]
    hashed_direct: bool          # multi-seg: route via the dist-key hash
    dist_dtype: Optional[np.dtype]

    def bind(self, session, text: str):
        """(inputs, bindings) for one literal text, or None → caller
        falls back to the full re-plan rebind."""
        from cloudberry_tpu.plan import pointlookup as PL

        conv = _text_converter(self.sqltype)
        try:
            v = conv(text)
        except (ValueError, TypeError, OverflowError):
            return None
        seg = None
        if self.hashed_direct:
            from cloudberry_tpu.utils import hashing

            nseg = session.config.n_segments
            h = hashing.hash_columns_np(
                [np.asarray([v], dtype=self.dist_dtype)])
            seg = int(hashing.jump_consistent_hash_np(h, nseg)[0])
        rows = PL._lookup(session, self.table, self.phys, seg, v)
        if rows is None or len(rows) != self.expect_rows:
            return None
        from cloudberry_tpu.exec import executor as X

        inputs = {self.input_key: X.point_scan_slice(
            self.table, rows, session, seg)}
        bindings = {}
        if self.param_key is not None:
            bindings[self.param_key] = np.asarray(
                v, dtype=self.sqltype.np_dtype)
        return inputs, bindings


def _redistributes(plan):
    """Redistribute motions in walk order, deduped by identity (shared
    subtrees re-walk) — the correspondence channel for copying observed
    bucket stats from the traced plan onto a signature-equal rebind."""
    from cloudberry_tpu.exec import executor as X

    seen: set[int] = set()
    out = []
    for n in X.all_nodes(plan):
        if isinstance(n, N.PMotion) and n.kind == "redistribute" \
                and id(n) not in seen:
            seen.add(id(n))
            out.append(n)
    return out


class GenericPlan:
    """One compiled program shared by every statement matching a
    (skeleton, signature) pair — rebinding feeds new literals/slices."""

    def __init__(self, session, skeleton: str, plan: N.PlanNode,
                 names, sig, bindings, keyed, slots):
        from cloudberry_tpu.exec import executor as X
        from cloudberry_tpu.exec.resource import estimate_plan_memory
        from cloudberry_tpu.sched import sharedcache

        self.skeleton = skeleton
        self.sig = sig
        self.config = session.config
        if session.config.debug.verify_plans:
            # planck gate on the GENERIC-PLAN FORM: the rewritten plan
            # (literals now $params slots, scan row counts now $nrw
            # inputs) must verify clean AND both slot families must
            # agree with the signature — a desynced slot would bind a
            # literal into the wrong predicate (or a row count into
            # the wrong scan) on every future rebind
            from cloudberry_tpu.plan.verify import check_plan

            check_plan(plan, session, "paramplan",
                       declared_slots=list(slots),
                       declared_nrw=sum(1 for k in bindings
                                        if k.startswith("$nrw")))
        # shared-tier guards (sched/sharedcache.py): content-stable table
        # version tokens + the plan epoch — store-scope entries match
        # across sessions, everything else stays private by construction
        self.versions = sharedcache.table_versions(session, names)
        self.ddlv = sharedcache.plan_epoch(session)
        self.plan = plan
        self.param_keys = sorted(bindings, key=lambda k: (k[:4],
                                                          int(k[4:])))
        self.keyed_keys = [s._input_key for s in keyed]
        self.table_names = sorted({s.table_name
                                   for s in X.scans_of(plan)
                                   if not X.keyed_scan(s)})
        # cached sorted-build join indexes this program reads next to its
        # tables (exec/joinindex.py) — rebinds re-feed them per table
        # version, the vmapped batch path rides them in_axes=None
        from cloudberry_tpu.exec.joinindex import jix_specs_of

        self.jix_keys = [s.key for s in jix_specs_of(plan)]
        self.est_bytes = estimate_plan_memory(plan).peak_bytes
        seg = getattr(plan, "_direct_segment", None)
        if session.config.n_segments > 1 and seg is None:
            self.kind = "dist"
        else:
            self.kind = "direct" if seg is not None else "single"
        if self.kind == "dist":
            from cloudberry_tpu.exec import dist_executor as DX

            self.fn = DX.compile_distributed(
                plan, session, param_keys=self.param_keys or None)
            self.exe = None
        else:
            self.exe = X.compile_plan(plan, session)
            self.fn = None
        # stacked-launch eligibility for the dispatcher (sched/dispatcher):
        # "sliced"  — every scan is a keyed point slice: stack ALL inputs;
        # "shared"  — no keyed scans, single-program: tables ride once
        #             (in_axes=None), only $params stacks.
        if self.kind in ("single", "direct") and self.keyed_keys \
                and not self.table_names:
            self.stack_mode = "sliced"
        elif self.kind == "single" and not self.keyed_keys \
                and self.param_keys:
            self.stack_mode = "shared"
        else:
            self.stack_mode = None
        self.fast: Optional[FastRebind] = None
        self._rungs: dict[int, Any] = {}
        self._rung_lock = __import__("threading").Lock()

    def matches(self, session, sig, versions, ddlv) -> bool:
        return (self.sig == sig and self.config is session.config
                and self.versions == versions and self.ddlv == ddlv)

    # --------------------------------------------------------- execution

    def bind_inputs(self, session, planB, keyedB, bindings) -> dict:
        """Assemble the program's inputs from a freshly bound plan:
        table columns (under the rebind's direct-dispatch segment), keyed
        scan slices REMAPPED to the compiled program's input keys, and the
        literal bindings as the ``$params`` entry."""
        from cloudberry_tpu.exec import executor as X

        seg = getattr(planB, "_direct_segment", None)
        tables = X.prepare_tables(self.table_names, session, segment=seg)
        if self.jix_keys:
            from cloudberry_tpu.exec.joinindex import join_index_inputs

            tables.update(join_index_inputs(self.plan, session, seg))
        for key, s in zip(self.keyed_keys, keyedB):
            if hasattr(s, "_point_rows"):
                tables[key] = X.point_scan_slice(
                    s.table_name, s._point_rows, session, seg)
            else:
                tables[key] = X._load_store_scan(s, session)
        if bindings:
            tables["$params"] = dict(bindings)
        return tables

    def run(self, session, planB, keyedB, bindings):
        """Execute the cached program with one rebind's values — never
        compiles."""
        import time as _t

        from cloudberry_tpu.exec import executor as X
        from cloudberry_tpu.obs import trace as OT

        session.stmt_log.bump("param_binds")
        # the rebind gets a SPAN only — the launch STAGE histogram
        # (recorded by the session around the whole runner) already
        # contains this host work, and the serve_bench time shares must
        # partition wall time, not count the bind twice
        t_bind = _t.perf_counter()
        if self.kind == "dist":
            from cloudberry_tpu.exec import dist_executor as DX

            inputs, _ = DX.prepare_dist_inputs(planB, session)
            if bindings:
                inputs["$params"] = dict(bindings)
            OT.mark("param-bind", t_bind)
            with OT.span("launch", mode="dist-generic"), \
                    OT.device_annotation("launch-dist"):
                cols, sel, checks, stats = self.fn(inputs)
            # the stats keys embed the TRACED plan's node ids — pin the
            # observed bucket demand there, then copy onto the rebind's
            # motions (signature-equal plans walk identically), so a skew
            # overflow still promotes straight to the fitting rung
            DX.record_motion_stats(self.plan, stats, session=session)
            for a, b in zip(_redistributes(self.plan),
                            _redistributes(planB)):
                ob = getattr(a, "_observed_bucket", None)
                if ob is not None:
                    b._observed_bucket = ob
            X.raise_checks(checks)
            DX.record_jf_counters(stats, session.stmt_log)
            from cloudberry_tpu.plan.feedback import fold_plan

            fold_plan(session, self.plan)
            host_cols = {k: DX._local_row(v) for k, v in cols.items()}
            return X.make_batch(self.plan, host_cols, DX._local_row(sel))
        inputs = self.bind_inputs(session, planB, keyedB, bindings)
        OT.mark("param-bind", t_bind)
        return X.run_executable(self.exe, inputs)

    # ----------------------------------------------------- stacked launch

    def rung_fn(self, session, rung: int):
        """The vmapped executable for a batch of ``rung`` rebinds —
        compiled once per power-of-two rung (the dispatcher pads batches
        up to the rung, so recompiles are bounded by log2(max_batch))."""
        import jax

        with self._rung_lock:
            fn = self._rungs.get(rung)
        if fn is not None:
            return fn
        from cloudberry_tpu.exec import executor as X

        X.count_compile(session)
        session.stmt_log.bump("batch_rung_compiles")
        if self.stack_mode == "sliced":
            axes: Any = 0
        else:
            axes = {n: None for n in self.table_names}
            # join indexes ride once per batch, like the tables
            axes.update({k: None for k in self.jix_keys})
            axes["$params"] = 0
        fn = jax.jit(jax.vmap(self.exe.raw_fn, in_axes=(axes,)))
        with self._rung_lock:
            self._rungs[rung] = fn
        return fn


# ----------------------------------------------------- session-side cache


_GENERIC_CACHE_MAX = 32


def _try_fast(session, gp: GenericPlan, plan, tok_params, bindings,
              keyed, slots) -> Optional[FastRebind]:
    """Attach the tokenize-only rebind template when the statement is the
    canonical single-parameter point lookup."""
    if len(tok_params) != 1 or len(slots) > 1 or len(keyed) != 1:
        return None
    if gp.kind == "dist" or gp.table_names:
        return None
    s = keyed[0]
    if not hasattr(s, "_point_rows"):
        return None
    out_to_phys = {out: phys for phys, out in s.column_map.items()}
    phys = out_to_phys.get(getattr(s, "_point_col", None))
    if phys is None:
        return None
    t = session.catalog.table(s.table_name)
    sqltype = t.schema.field(phys).type
    conv = _text_converter(sqltype)
    if conv is None:
        return None
    prm_keys = [k for k in gp.param_keys if k.startswith("$prm")]
    if len(prm_keys) != len(gp.param_keys):
        return None  # row-count params imply non-keyed scans — not fast
    param_key = prm_keys[0] if prm_keys else None
    try:
        v = conv(tok_params[0])
    except (ValueError, TypeError, OverflowError):
        return None
    # the converter must reproduce BOTH the bound literal (the $params
    # value) and the sidecar probe value, or fast rebinding would diverge
    # from the binder's typed folds — validate against the build's values
    if param_key is not None:
        bound = bindings[param_key]
        if slots[0] != sqltype or not np.asarray(v, bound.dtype) == bound:
            return None
    hashed_direct = False
    dist_dtype = None
    if session.config.n_segments > 1:
        if getattr(plan, "_direct_segment", None) is None:
            return None
        if t.policy.kind == "hashed":
            if list(t.policy.keys) != [phys]:
                return None
            hashed_direct = True
            dist_dtype = t.schema.field(phys).type.np_dtype
        elif t.policy.kind != "replicated":
            return None
    return FastRebind(s.table_name, phys, sqltype, s.num_rows,
                      gp.keyed_keys[0], param_key, hashed_direct,
                      dist_dtype)


def _eligible(session, query, plan) -> bool:
    if not session.config.sched.generic_plans:
        return False
    if getattr(plan, "_no_stmt_cache", False):
        return False
    return True


@dataclass
class Prep:
    """One statement's rebinding package: the shared program plus this
    execution's freshly bound plan and its literal values."""
    gp: GenericPlan
    plan: N.PlanNode
    keyed: list
    bindings: dict
    built: bool = False

    def run(self, session):
        return self.gp.run(session, self.plan, self.keyed, self.bindings)


def lookup_or_build(session, query: str, plan) -> Optional[Prep]:
    """The generic-plan gate for one freshly bound plan: normalize, match
    the (skeleton, signature) cache, build on miss. None → the statement
    keeps the non-generic path."""
    from cloudberry_tpu.exec import executor as X

    if not _eligible(session, query, plan):
        return None
    norm = normalize(query)
    if norm is None or not norm[1]:
        return None
    skeleton, tok_params = norm
    names = sorted({s.table_name for s in X.scans_of(plan)})
    if session._any_external(names):
        return None
    from cloudberry_tpu.sched import sharedcache

    try:
        versions = sharedcache.table_versions(session, names)
    except KeyError:
        return None
    ddlv = sharedcache.plan_epoch(session)
    try:
        sig, bindings, keyed, slots = analyze(session, plan)
    except UnsupportedPlan:
        return None
    lock = session._generic_lock
    cache = session._generic_cache
    with lock:
        bucket = cache.pop(skeleton, None)
        if bucket is not None:
            cache[skeleton] = bucket  # LRU touch
            for gp in bucket:
                if gp.matches(session, sig, versions, ddlv):
                    session.stmt_log.bump("generic_hits")
                    return Prep(gp, plan, keyed, bindings)
    # build: re-walk with rewrite=True so the compiled program reads its
    # literals from $params (slot order identical by the walker contract)
    sig2, bindings2, keyed2, slots2 = analyze(session, plan, rewrite=True)
    assert sig2 == sig and list(bindings2) == list(bindings)
    import time as _time

    from cloudberry_tpu.obs import metrics as OM
    from cloudberry_tpu.obs import trace as OT

    t_build = _time.perf_counter()
    with OT.span("compile", skeleton=skeleton[:80]):
        gp = GenericPlan(session, skeleton, plan, names, sig, bindings2,
                         keyed2, slots2)
    OM.observe_stage(session.stmt_log, "compile",
                     _time.perf_counter() - t_build)
    gp.fast = _try_fast(session, gp, plan, tok_params, bindings2, keyed2,
                        slots2)
    session.stmt_log.bump("generic_builds")
    with lock:
        bucket = cache.setdefault(skeleton, [])
        bucket.append(gp)
        del bucket[:-session.config.sched.max_variants]
        while len(cache) > _GENERIC_CACHE_MAX:
            cache.pop(next(iter(cache)))
    return Prep(gp, plan, keyed2, bindings2, built=True)


def generic_runner(session, query: str, plan):
    """Session hook (session._execute_and_cache): a zero-argument runner
    over the shared compiled program, or None for non-generic
    statements."""
    prep = lookup_or_build(session, query, plan)
    if prep is None:
        return None
    return lambda: prep.run(session)


# -------------------------------------------------------- batch execution


def prepare_one(session, query: str) -> Optional[Prep]:
    """Full host-side preparation of one statement for the dispatcher:
    parse → bind/plan → generic lookup/build. None → not batchable."""
    from cloudberry_tpu.plan.planner import plan_statement
    from cloudberry_tpu.sql.parser import parse_sql

    session._sync_store()
    try:
        stmt = parse_sql(query)
        result = plan_statement(stmt, session, {})
    except Exception:
        return None
    if result.is_ddl:
        return None
    return lookup_or_build(session, query, result.plan)


def run_batch(session, sqls: list[str]):
    """Execute same-skeleton statements as ONE stacked launch: per-request
    host rebinding (tokenize-only when the fast template applies, else a
    host re-plan), inputs stacked to the next power-of-two rung, one
    vmapped program launch, results split per request.

    Returns a list of ColumnBatch (one per statement) or None when the
    group is not stackable — the dispatcher then falls back to sequential
    dispatch. Never compiles except once per (skeleton, signature, rung).
    """
    import jax

    from cloudberry_tpu.exec import executor as X
    from cloudberry_tpu.exec.resource import ResourceError
    from cloudberry_tpu.utils.faultinject import fault_point

    if len(sqls) < 2 or not session.config.sched.generic_plans:
        return None
    prep0 = prepare_one(session, sqls[0])
    if prep0 is None or prep0.gp.stack_mode is None:
        return None
    gp = prep0.gp
    shared = gp.stack_mode == "shared"
    if shared:
        # tables ride ONCE (vmap in_axes=None) — per request only the
        # literal bindings vary
        from cloudberry_tpu.exec import executor as X

        base = X.prepare_tables(gp.table_names, session, segment=None)
        if gp.jix_keys:
            from cloudberry_tpu.exec.joinindex import join_index_inputs

            base.update(join_index_inputs(gp.plan, session, None))
        per: list[dict] = [dict(prep0.bindings)]
    else:
        per = [gp.bind_inputs(session, prep0.plan, prep0.keyed,
                              prep0.bindings)]
    for q in sqls[1:]:
        bound = None
        if gp.fast is not None:
            norm = normalize(q)
            if norm is None or norm[0] != gp.skeleton:
                return None
            fb = gp.fast.bind(session, norm[1][0])
            if fb is not None:
                tabs, binds = fb
                if binds:
                    tabs["$params"] = binds
                bound = tabs
                session.stmt_log.bump("fast_rebinds")
                # a fast rebind IS a generic-plan reuse (the tokenize-
                # only subset): the hit counter must agree with the
                # prepare_one path so per-statement attribution
                # (dispatcher batch finishes) sums to the engine total
                session.stmt_log.bump("generic_hits")
        if bound is None:
            p = prepare_one(session, q)
            if p is None or p.gp is not gp:
                return None  # shape drifted mid-batch — sequential path
            bound = dict(p.bindings) if shared \
                else gp.bind_inputs(session, p.plan, p.keyed, p.bindings)
        per.append(bound)
    k = len(per)
    rung = _next_pow2(k)
    per += [per[-1]] * (rung - k)
    if shared:
        stacked = dict(base)
        stacked["$params"] = {
            key: np.stack([b[key] for b in per])
            for key in gp.param_keys}
    else:
        # host-side stacking: leaves are numpy (point_scan_slice), so the
        # whole batch crosses to the device as ONE transfer per leaf at
        # dispatch instead of one put per request per column
        stacked = jax.tree_util.tree_map(
            lambda *xs: np.stack([np.asarray(x) for x in xs]), *per)
    fn = gp.rung_fn(session, rung)
    cost = gp.est_bytes * (rung if gp.stack_mode == "shared" else 1)
    try:
        with session._gate, session._admitted(cost):
            fault_point("sched_flush")
            # cancel seam at the batched launch: a cancelled/expired
            # member aborts the flush (StatementError is NOT part of the
            # fallback catch below — the dispatcher re-routes survivors)
            from cloudberry_tpu.lifecycle import check_cancel

            check_cancel()
            session.stmt_log.bump("dispatches")
            cols, sel, checks = fn(stacked)
            X.raise_checks(checks)
    except (ResourceError, X.ExecError):
        # vmapped checks OR across lanes: ONE request's runtime check
        # (subquery cardinality, expansion overflow, ...) must not error
        # its batchmates — fall back to sequential dispatch, where each
        # statement gets its own verdict and the grow-and-retry loop
        return None
    session.stmt_log.bump("batched_statements", k)
    out = []
    host_cols = {name: np.asarray(v) for name, v in cols.items()}
    host_sel = np.asarray(sel)
    for i in range(k):
        out.append(X.make_batch(
            gp.plan, {name: v[i] for name, v in host_cols.items()},
            host_sel[i]))
    return out
