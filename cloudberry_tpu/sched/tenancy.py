"""Per-tenant fair scheduling — resource groups with CPU-share teeth.

The admission layer (exec/resource.py) bounds HOW MANY statements run;
it says nothing about WHOSE. Under warehouse concurrency that means one
chatty tenant starves the rest — exactly the "partial — no CPU-share
isolation" gap of the resource-group analog. This module adds the
scheduling half, the way "Accelerating Presto with GPUs" feeds many
cheap coordinator connections into a small accelerator-side execution
pool with priority-aware batching:

- tenants are declared named groups (weight, max concurrency, queue
  depth — config.tenancy / exec/resource.TenantGroup); requests carry a
  tenant name, unknown names fall into an auto-created default-shaped
  group;
- each dispatcher tick picks requests in DEFICIT-WEIGHTED-ROUND-ROBIN
  order: every round a non-empty tenant's deficit grows by
  weight x quantum, and it dequeues while the deficit lasts — under
  saturation, dispatch throughput is proportional to weight;
- STARVATION-FREE AGING: a request waiting past ``aging_s`` is picked
  ahead of deficit order (oldest first), so a weight-1 tenant's p99
  stays bounded no matter how heavy its neighbors — priority aging, not
  priority inversion (per-tenant max_concurrency still holds: an
  operator's explicit cap is never overridden);
- per-tenant admission/backpressure: a full tenant queue refuses with
  the RETRYABLE TenantQueueFull instead of queueing unboundedly — the
  same flow-control discipline as the dispatcher's global queue, scoped
  per tenant.

The scheduler is deliberately free of execution knowledge: it schedules
opaque items (the dispatcher's _Request objects) and exposes
``enqueue`` / ``pick`` / ``finish`` plus a ``slot`` context manager for
the server's direct (non-dispatcher) paths.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from typing import Any, Optional

from cloudberry_tpu.exec.resource import TenantGroup, TenantQueueFull

DEFAULT_TENANT = "default"


class TenantScheduler:
    """DWRR + aging over per-tenant bounded queues.

    Items are opaque; the scheduler tracks (item, enqueue_t) pairs and
    per-group accounting. Every mutable field of a TenantGroup is
    guarded by ``self._lock``.
    """

    def __init__(self, config):
        """``config`` is a config.TenancyConfig."""
        self.quantum = max(1, int(config.quantum))
        self.aging_s = float(config.aging_s)
        self.slot_wait_s = float(config.slot_wait_s)
        self._default_weight = max(1, int(config.default_weight))
        self._default_max_queue = max(1, int(config.default_max_queue))
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._groups: dict[str, TenantGroup] = {}
        self._queues: dict[str, deque] = {}
        self._order: list[str] = []       # round-robin rotation order
        self._rr = 0                      # rotation cursor
        for spec in getattr(config, "tenants", ()) or ():
            self._add_group(TenantGroup(
                name=str(spec.name).lower(),
                weight=max(1, int(spec.weight)),
                max_concurrency=max(0, int(spec.max_concurrency)),
                max_queue=max(1, int(spec.max_queue))))

    # ------------------------------------------------------------- groups

    def _add_group(self, g: TenantGroup) -> TenantGroup:
        self._groups[g.name] = g
        self._queues[g.name] = deque()
        self._order.append(g.name)
        return g

    def group(self, tenant: Optional[str]) -> TenantGroup:
        """The tenant's group, auto-created with the default shape for
        undeclared names (callers under the lock use _group_locked)."""
        with self._lock:
            return self._group_locked(tenant)

    def _group_locked(self, tenant: Optional[str]) -> TenantGroup:
        name = (tenant or DEFAULT_TENANT).lower()
        g = self._groups.get(name)
        if g is None:
            g = self._add_group(TenantGroup(
                name=name, weight=self._default_weight,
                max_queue=self._default_max_queue))
        return g

    # ------------------------------------------------------------ enqueue

    def enqueue(self, tenant: Optional[str], item: Any,
                wait_s: Optional[float] = None) -> TenantGroup:
        """Admit one request to its tenant's bounded queue. Waits up to
        ``wait_s`` (default: config slot_wait_s; 0 = refuse immediately)
        for space, then raises the retryable TenantQueueFull."""
        wait = self.slot_wait_s if wait_s is None else wait_s
        end = time.monotonic() + wait
        with self._lock:
            g = self._group_locked(tenant)
            q = self._queues[g.name]
            while len(q) >= g.max_queue:
                left = end - time.monotonic()
                if left <= 0:
                    g.rejected += 1
                    raise TenantQueueFull(
                        f"tenant {g.name!r}: request queue full "
                        f"({g.max_queue} waiting); retry, or raise the "
                        "tenant's max_queue")
                self._cond.wait(timeout=left)
            q.append((item, time.monotonic()))
            g.queued = len(q)
            g.max_depth = max(g.max_depth, len(q) + g.waiting)
            self._cond.notify_all()
            return g

    # --------------------------------------------------------------- pick

    def _pickable(self, g: TenantGroup) -> bool:
        return bool(self._queues[g.name]) and (
            g.max_concurrency <= 0 or g.running < g.max_concurrency)

    def _take(self, g: TenantGroup, now: float, aged: bool) -> Any:
        item, t0 = self._queues[g.name].popleft()
        g.queued = len(self._queues[g.name])
        g.running += 1
        g.picks += 1
        g.last_pick_t = now
        try:
            # the dispatcher's _Request.finish reads this to release the
            # concurrency slot; opaque items without the field just skip
            item._tenant_group = g
        except AttributeError:
            pass
        if aged:
            g.aged += 1
        w = (now - t0) * 1000.0
        g.wait_sum_ms += w
        g.wait_max_ms = max(g.wait_max_ms, w)
        self._cond.notify_all()  # space freed: wake blocked enqueuers
        return item

    def pick(self, max_n: int, now: Optional[float] = None) -> list:
        """Up to ``max_n`` requests in scheduling order: over-age heads
        first (oldest first — the starvation bound), then DWRR rounds.
        Deficits persist across calls; a tenant whose queue empties
        forfeits its leftover deficit (classic DWRR, so an idle tenant
        cannot hoard credit and burst past its share later)."""
        now = time.monotonic() if now is None else now
        out: list = []
        with self._lock:
            # aging pass — the STARVATION bound, not a FIFO override: a
            # tenant qualifies only when its head is over-age AND the
            # scheduler has not picked from it within aging_s (a tenant
            # being served every round is loaded, not starved — under
            # deep saturation every head is over-age, and oldest-first
            # alone would collapse the weights into global FIFO). Taking
            # one request updates last_pick_t, so each starving tenant
            # gets at most one aged pick per call; the rest is DWRR.
            while len(out) < max_n:
                oldest = None
                for name in self._order:
                    g = self._groups[name]
                    if not self._pickable(g):
                        continue
                    t0 = self._queues[name][0][1]
                    if now - t0 > self.aging_s \
                            and now - g.last_pick_t > self.aging_s \
                            and (oldest is None or t0 < oldest[1]):
                        oldest = (g, t0)
                if oldest is None:
                    break
                out.append(self._take(oldest[0], now, aged=True))
            # DWRR rounds over the rotation order
            while len(out) < max_n:
                progressed = False
                n = len(self._order)
                for i in range(n):
                    name = self._order[(self._rr + i) % n]
                    g = self._groups[name]
                    if not self._queues[name]:
                        g.deficit = 0.0  # empty queue forfeits credit
                        continue
                    if not self._pickable(g):
                        # concurrency-blocked: no credit accrual — a
                        # tenant parked at its cap must not bank deficit
                        # and burst past its weight share once freed
                        continue
                    # cap the bank at one pick budget: credit models
                    # "servable but the batch filled", never a hoard
                    g.deficit = min(g.deficit + g.weight * self.quantum,
                                    float(max(max_n,
                                              g.weight * self.quantum)))
                    while g.deficit >= 1.0 and self._pickable(g) \
                            and len(out) < max_n:
                        g.deficit -= 1.0
                        out.append(self._take(g, now, aged=False))
                        progressed = True
                    if len(out) >= max_n:
                        break
                self._rr = (self._rr + 1) % max(1, n)
                if not progressed:
                    break
        return out

    def finish(self, g: TenantGroup) -> None:
        """One picked/admitted request completed (ok or error)."""
        with self._lock:
            g.running -= 1
            g.served += 1
            self._cond.notify_all()

    # ------------------------------------------------ direct-path gating

    def slot(self, tenant: Optional[str],
             wait_s: Optional[float] = None):
        """Concurrency gate for statements that bypass the dispatcher
        (writes, non-parameterizable reads): waits briefly for a
        max_concurrency slot, then refuses with TenantQueueFull. The
        queue-depth bound covers waiters too — a tenant cannot park
        unbounded worker threads here."""
        wait = self.slot_wait_s if wait_s is None else wait_s

        @contextlib.contextmanager
        def _slot():
            end = time.monotonic() + wait
            with self._lock:
                g = self._group_locked(tenant)
                g.waiting += 1
                g.max_depth = max(g.max_depth, g.queued + g.waiting)
                try:
                    if g.waiting + g.queued > g.max_queue:
                        g.rejected += 1
                        raise TenantQueueFull(
                            f"tenant {g.name!r}: {g.max_queue} requests "
                            "already waiting; retry shortly")
                    while g.max_concurrency > 0 \
                            and g.running >= g.max_concurrency:
                        left = end - time.monotonic()
                        if left <= 0:
                            g.rejected += 1
                            raise TenantQueueFull(
                                f"tenant {g.name!r}: no concurrency slot "
                                f"({g.running} of {g.max_concurrency} "
                                "running); retry shortly")
                        self._cond.wait(timeout=left)
                finally:
                    g.waiting -= 1
                g.running += 1
                g.picks += 1
            try:
                yield
            finally:
                self.finish(g)

        return _slot()

    # ------------------------------------------------------ observability

    def depth(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._queues.values())

    def pending(self) -> list:
        """Drain every queue (dispatcher stop: fail pending visibly)."""
        out = []
        with self._lock:
            for name, q in self._queues.items():
                g = self._groups[name]
                while q:
                    out.append(q.popleft()[0])
                g.queued = 0
            self._cond.notify_all()
        return out

    def snapshot(self) -> dict:
        with self._lock:
            out = {}
            for name in self._order:
                g = self._groups[name]
                served = max(g.picks, 1)
                out[name] = {
                    "weight": g.weight,
                    "max_concurrency": g.max_concurrency,
                    "max_queue": g.max_queue,
                    "queued": g.queued,
                    "waiting": g.waiting,
                    "running": g.running,
                    "picks": g.picks,
                    "served": g.served,
                    "rejected": g.rejected,
                    "aged": g.aged,
                    "max_depth": g.max_depth,
                    "wait_avg_ms": round(g.wait_sum_ms / served, 3),
                    "wait_max_ms": round(g.wait_max_ms, 3),
                }
            return out

    def fairness_index(self) -> float:
        """Jain's fairness index over weight-normalized picks: 1.0 =
        every tenant got throughput exactly proportional to its weight
        (only tenants that were ever picked participate)."""
        with self._lock:
            xs = [g.picks / g.weight for g in self._groups.values()
                  if g.picks > 0]
        if not xs:
            return 1.0
        return (sum(xs) ** 2) / (len(xs) * sum(x * x for x in xs))
