"""Statement scheduler — parameterized generic plans + the continuous
micro-batch dispatcher (the plan_cache.c / gang-dispatch analog).

Two layers:

- ``paramplan``: literal parameterization. Same-shape statements share ONE
  compiled XLA program keyed on the normalized statement skeleton, with
  literals fed as device inputs (``$params``) instead of baked constants —
  the generic-plan side of PostgreSQL's plan_cache.c, where the dominant
  cost amortized is XLA compilation rather than planning.
- ``dispatcher``: a bounded async request queue in front of a serving
  Session that coalesces same-skeleton statements per tick into one
  stacked (vmapped) launch — the continuous-batching shape of an
  inference stack, applied to SQL dispatch.

Two more layers ride alongside (ISSUE-7, the multi-tenant serving core):

- ``sharedcache``: the process-wide cache tier — sessions over one
  durable store share generic-plan / rung / join-index scopes, so a
  second tenant's identical-skeleton statements compile nothing;
- ``tenancy``: per-tenant fair scheduling — named resource groups picked
  in deficit-weighted-round-robin order inside the dispatcher tick, with
  starvation-free aging and per-tenant backpressure (TenantQueueFull).
"""

from cloudberry_tpu.sched.paramplan import normalize  # noqa: F401
from cloudberry_tpu.sched.dispatcher import (  # noqa: F401
    Dispatcher, SchedDeadline, SchedQueueFull)
from cloudberry_tpu.sched.tenancy import TenantScheduler  # noqa: F401
