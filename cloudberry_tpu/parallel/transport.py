"""Swappable motion transports — the ic_modules.c vtable analog.

The reference selects an interconnect implementation through a vtable
(contrib/interconnect/ic_modules.c:26-160: UDP / TCP / proxy share one
motion API). Under the one-XLA-program model every transport must still
be XLA collectives — but WHICH collective formulation is a real choice
on TPU hardware:

- ``xla``: the compiler's native ``all_gather`` / ``all_to_all`` /
  ``psum`` — XLA picks the algorithm (default).
- ``ring``: ``ppermute``-composed collectives. all_gather and psum are
  true rings — N−1 nearest-neighbor shift-and-accumulate steps, the
  systolic pattern that rides ICI links on torus topologies (and the
  building block of ring attention). all_to_all uses one distance-k
  ppermute per round (minimal data motion; the hardware routes each
  rotation), not strictly neighbor hops. Either way it is a second
  independent implementation that cross-checks the first (tests assert
  bit-identical results against XLA's).

Both implement one interface, chosen by ``interconnect.backend``; the
interconnect bench (tools/ic_bench.py) measures either, so the
backends can be compared on real hardware without the executor.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


class XlaCollectives:
    """XLA's native collectives (the compiler schedules the algorithm)."""

    name = "xla"

    def all_gather(self, x, axis):
        return jax.lax.all_gather(x, axis, axis=0, tiled=True)

    def all_to_all(self, x, axis):
        """x: (nseg, ...) per-destination blocks -> (nseg, ...) received."""
        return jax.lax.all_to_all(x, axis, split_axis=0, concat_axis=0,
                                  tiled=False)

    def psum(self, x, axis):
        return jax.lax.psum(x, axis)

    def pmax(self, x, axis):
        return jax.lax.pmax(x, axis)


class RingCollectives:
    """ppermute-composed collectives (see module docstring: all_gather
    and psum are true neighbor rings; all_to_all rotates by k).

    ``chunks`` > 1 splits each all_to_all block along its ROW axis into
    that many independent contiguous slices — one ppermute per
    (hop, chunk) — so the compiler can overlap hop-k's rotation of one
    chunk with the placement of the previous chunk (the software-
    pipelined ring; profitable on real ICI links for the large packed
    motion buffers, a wash for the small ones). The row axis is the
    bucket capacity, a power-of-two rung, so any pow2 chunk count
    divides it; an indivisible count falls back to whole-block hops.
    Chunking never changes results: the slices are disjoint and
    reassembled in order."""

    name = "ring"

    def __init__(self, n: int, chunks: int = 1):
        self.n = n
        self.chunks = max(int(chunks), 1)

    def _shift(self, x, axis, by: int = 1):
        perm = [(i, (i + by) % self.n) for i in range(self.n)]
        return jax.lax.ppermute(x, axis, perm)

    def all_gather(self, x, axis):
        # accumulate blocks while rotating: after k hops this segment
        # holds the block of segment (i - k) mod n; place each into its
        # global slot so the result matches all_gather(tiled=True)
        idx = jax.lax.axis_index(axis)
        n = self.n
        rows = x.shape[0]
        out = jnp.zeros((n * rows,) + x.shape[1:], dtype=x.dtype)
        cur = x
        for k in range(n):
            src = (idx - k) % n
            out = jax.lax.dynamic_update_slice_in_dim(
                out, cur, src * rows, axis=0)
            if k + 1 < n:
                cur = self._shift(cur, axis)
        return out

    def all_to_all(self, x, axis):
        # x[(dest, ...)]: send block d to segment d. Rotate k hops so
        # each segment receives the block addressed to it from the
        # segment k behind it on the ring.
        idx = jax.lax.axis_index(axis)
        n = self.n
        out = jnp.zeros_like(x)
        # a block is (rows, ...) once the destination axis is selected;
        # chunk along the contiguous row axis (bucket capacity — a pow2
        # rung under the capacity ladder, so pow2 chunk counts divide it)
        nch = self.chunks if (x.ndim > 1
                              and x.shape[1] % self.chunks == 0) else 1
        for k in range(n):
            # after shifting by k, this segment sees the block that
            # segment (idx - k) addressed to destination idx... select
            # our destination slot BEFORE shifting to move one block
            src = (idx - k) % n
            block = jnp.take(x, (idx + k) % n, axis=0)  # dest = idx + k
            if k == 0:
                moved = block
            elif nch > 1:
                # chunked hop: independent per-chunk ppermutes let the
                # scheduler start chunk c+1's rotation while chunk c is
                # being placed — a software pipeline over the slices
                parts = jnp.split(block, nch, axis=0)
                moved = jnp.concatenate(
                    [self._shift(p, axis, by=k) for p in parts], axis=0)
            else:
                moved = self._shift(block, axis, by=k)
            out = out.at[src].set(moved)
        return out

    def psum(self, x, axis):
        acc = x
        cur = x
        for _ in range(self.n - 1):
            cur = self._shift(cur, axis)
            acc = acc + cur
        return acc

    def pmax(self, x, axis):
        acc = x
        cur = x
        for _ in range(self.n - 1):
            cur = self._shift(cur, axis)
            acc = jnp.maximum(acc, cur)
        return acc


class HierarchicalCollectives:
    """Topology-aware two-level collectives (the ISSUE-14 tentpole):
    every exchange splits into an intra-host hop over ICI and ONE
    aggregated inter-host hop over DCN (the data-movement thesis of
    Theseus, PAPERS.md — move bytes on the cheap links, aggregate
    before the expensive ones).

    Built entirely from ``ppermute`` compositions (the one collective
    every backend supports identically) over a single ``seg`` axis:
    "intra-host" permutations rotate within a host's contiguous segment
    block, "inter-host" permutations rotate between hosts along a lane.
    Requires a UNIFORM CONTIGUOUS HostTopology (host h owns segments
    [h*S, (h+1)*S)) — jax.devices() orders by process index, so real
    clusters satisfy it; ragged/degraded layouts stay on flat motion.

    - ``all_gather`` (gather/broadcast motions, runtime-filter key and
      digest gathers): host-root tree — intra-host ring gather, DCN
      ring between the hosts' lane-0 segments only, intra-host
      broadcast. DCN carries each host's COMBINED block once per remote
      host instead of every segment's block to every remote segment.
      Applied to unsigned-integer payloads (the packed wire, u64 keys,
      u32 digests — where the zero-fill broadcast trick is exact);
      other dtypes delegate to the flat inner transport.
    - ``hier_all_to_all`` (hash redistribute): re-buckets rows by
      DESTINATION HOST between the hops — packed-wire buffers
      throughout, the re-bucket is kernels.wire_rebucket, no unpack —
      so DCN ships one host-pair block at the ``host_cap`` rung instead
      of nseg per-segment-pair blocks at the pair rung. Two route words
      (destination segment, source slot) ride the wire across the hops
      and place every received row at EXACTLY the slot the flat
      all_to_all would have used, so the returned buffer is
      bit-identical to ``inner.all_to_all`` — downstream programs
      cannot tell the transports apart.
    - ``host_ring_exchange``: per-lane inter-host ring of HOST-COMBINED
      vectors (the runtime-filter digest fold: DCN carries one digest
      per host, not one per segment).
    - ``psum`` / ``pmax`` delegate flat: they carry control-plane
      scalars (checks, stats), not data volume.

    ``launches`` counts ppermute launches at trace time (ic_bench's
    two-level launch accounting)."""

    name = "hier"
    is_hierarchical = True

    def __init__(self, topo, inner=None):
        if inner is None:
            inner = XlaCollectives()
        self.inner = inner
        self.hier_topo = topo
        self.n = topo.n_segments
        self.H = topo.n_hosts
        self.S = topo.n_segments // topo.n_hosts
        if not topo.uniform_contiguous() or self.H < 2:
            raise ValueError(
                "HierarchicalCollectives needs a uniform contiguous "
                f"multi-host topology; got {topo.as_dict()}")
        self.launches = 0

    # ------------------------------------------------------ primitives

    def _pp(self, x, axis, perm):
        self.launches += 1
        return jax.lax.ppermute(x, axis, perm)

    def _intra_shift(self, x, axis, by: int = 1):
        """Rotate within each host's segment block (ICI hop)."""
        H, S = self.H, self.S
        perm = [(h * S + t, h * S + (t + by) % S)
                for h in range(H) for t in range(S)]
        return self._pp(x, axis, perm)

    def _lane_shift(self, x, axis, by: int = 1):
        """Rotate between hosts along every lane (DCN hop)."""
        H, S = self.H, self.S
        perm = [(h * S + t, ((h + by) % H) * S + t)
                for h in range(H) for t in range(S)]
        return self._pp(x, axis, perm)

    def _idx(self, axis):
        idx = jax.lax.axis_index(axis)
        return idx // self.S, idx % self.S

    # --------------------------------------------------- intra helpers

    def intra_all_gather(self, x, axis):
        """(rows, ...) -> (S*rows, ...): each segment gathers its
        HOST's blocks in local order (ICI ring, S-1 ppermutes)."""
        S = self.S
        if S == 1:
            return x
        _, t = self._idx(axis)
        rows = x.shape[0]
        out = jnp.zeros((S * rows,) + x.shape[1:], dtype=x.dtype)
        cur = x
        for k in range(S):
            src = (t - k) % S
            out = jax.lax.dynamic_update_slice_in_dim(
                out, cur, src * rows, axis=0)
            if k + 1 < S:
                cur = self._intra_shift(cur, axis)
        return out

    def _intra_psum(self, x, axis):
        """Sum over each host's segments (ICI ring) — exact for the
        unsigned payloads the tree broadcast uses."""
        acc = x
        cur = x
        for _ in range(self.S - 1):
            cur = self._intra_shift(cur, axis)
            acc = acc + cur
        return acc

    def _intra_all_to_all(self, x, axis):
        """(S, C, ...) per-local-destination blocks -> (S, C, ...)
        received, within each host (the ICI all_to_all; same rotate
        scheme as RingCollectives.all_to_all, group-local)."""
        S = self.S
        if S == 1:
            return x
        _, t = self._idx(axis)
        out = jnp.zeros_like(x)
        for k in range(S):
            src = (t - k) % S
            block = jnp.take(x, (t + k) % S, axis=0)
            moved = block if k == 0 else self._intra_shift(block, axis,
                                                           by=k)
            out = out.at[src].set(moved)
        return out

    # ------------------------------------------------------- interface

    def psum(self, x, axis):
        return self.inner.psum(x, axis)

    def pmax(self, x, axis):
        return self.inner.pmax(x, axis)

    def all_to_all(self, x, axis):
        """Flat fallback (callers without host stamps / non-wire
        payloads); the two-level exchange is ``hier_all_to_all``."""
        return self.inner.all_to_all(x, axis)

    def all_gather(self, x, axis):
        """Host-root tree all_gather, bit-identical to the flat tiled
        all_gather: result rows land in global segment order. Unsigned
        payloads only (the intra-host broadcast rides an exact zero-fill
        psum); everything else delegates flat."""
        if self.S == 1 or not jnp.issubdtype(x.dtype,
                                             jnp.unsignedinteger):
            return self.inner.all_gather(x, axis)
        h, t = self._idx(axis)
        hb = self.intra_all_gather(x, axis)          # (S*rows, ...)
        rows_h = hb.shape[0]
        H, S = self.H, self.S
        full = jnp.zeros((H * rows_h,) + hb.shape[1:], dtype=hb.dtype)
        cur = hb
        lane0 = [(g * S, ((g + 1) % H) * S) for g in range(H)]
        for k in range(H):
            src = (h - k) % H
            full = jax.lax.dynamic_update_slice_in_dim(
                full, cur, src * rows_h, axis=0)
            if k + 1 < H:
                cur = self._pp(cur, axis, lane0)     # DCN: lane 0 only
        # intra-host broadcast of lane 0's assembled result (non-lane-0
        # accumulations above saw zeros from the lane-0-only ring)
        return self._intra_psum(
            jnp.where(t == 0, full, jnp.zeros((), dtype=full.dtype)),
            axis)

    def host_ring_exchange(self, x, axis):
        """(D,) per-segment HOST-COMBINED vector -> (H, D) host vectors
        in host order, via an all-lane inter-host ring. The digest
        host-combine transport: DCN carries one combined vector per
        host per lane instead of one per segment pair."""
        h, _ = self._idx(axis)
        H = self.H
        out = jnp.zeros((H,) + x.shape, dtype=x.dtype)
        cur = x
        for k in range(H):
            src = (h - k) % H
            out = out.at[src].set(cur)
            if k + 1 < H:
                cur = self._lane_shift(cur, axis)
        return out

    # --------------------------------------------- two-level a2a (hash)

    def hier_all_to_all(self, x, axis, host_cap: int):
        """Two-level hash redistribute over packed wire blocks.

        ``x``: (nseg, B, W) uint32 per-destination-SEGMENT blocks — the
        flat all_to_all's exact input (word 0 bit 0 = row validity).
        Returns ``(recv, host_demand)``: recv (nseg, B, W) BIT-IDENTICAL
        to ``inner.all_to_all(x, axis)`` (two route words carry each
        row's destination segment and source slot through the hops, so
        final placement reproduces the flat layout exactly), and
        host_demand (H,) int32 — rows THIS source host addressed to each
        destination host, the ``host_cap`` overflow/stats feed (each
        segment reports its lane's hosts; the others read 0).

        Hops: (1) ICI all_to_all routing rows to the lane that owns
        their destination host (a STATIC permutation of wire rows —
        destination and rank are slot-determined); between the hops the
        lane re-buckets its host's combined rows by destination host
        (kernels.wire_rebucket — dynamic, validity-driven, no unpack)
        into host-pair blocks at the ``host_cap`` rung; (2) one DCN
        exchange of the combined host-pair blocks (H-1 ppermutes, one
        block per host pair — THE aggregated inter-host exchange); (3)
        ICI all_to_all scattering received rows to their destination
        segment, then slot placement. Hop-1/hop-3 capacities are the
        PROVEN bound ceil(H/S)*S*B (every per-segment-pair bucket is
        already capped at B by the caller's rank discipline), so only
        the host rung needs an overflow check."""
        from cloudberry_tpu.exec import kernels as K

        n, H, S = self.n, self.H, self.S
        B, W = int(x.shape[1]), int(x.shape[2])
        k_hosts = -(-H // S)                     # hosts per lane (ceil)
        h, t = self._idx(axis)

        flat = x.reshape(n * B, W)
        slot = jnp.arange(n * B, dtype=jnp.uint32)
        destw = (slot // jnp.uint32(B)).astype(jnp.uint32)
        idx = jax.lax.axis_index(axis).astype(jnp.uint32)
        origw = idx * jnp.uint32(B) + slot % jnp.uint32(B)
        rbuf = jnp.concatenate([flat, destw[:, None], origw[:, None]],
                               axis=1)           # (n*B, W+2)

        # hop 1: static lane permutation (dest host g -> lane g % S,
        # host slot j = g // S, then dest-local s, then rank) + ICI a2a
        C1 = two_level_lane_rows(n, H, B)
        gidx = np.zeros((S, C1), dtype=np.int32)
        padm = np.zeros((S, C1), dtype=bool)
        for lane in range(S):
            pos = 0
            for j in range(k_hosts):
                g = lane + j * S
                for s in range(S):
                    if g < H:
                        base = (g * S + s) * B
                        gidx[lane, pos:pos + B] = np.arange(base,
                                                            base + B)
                    else:
                        padm[lane, pos:pos + B] = True
                    pos += B
        y = rbuf[jnp.asarray(gidx)]              # (S, C1, W+2)
        y = jnp.where(jnp.asarray(padm)[:, :, None],
                      jnp.zeros((), dtype=y.dtype), y)
        z = self._intra_all_to_all(y, axis)      # peers' lane-t blocks
        zf = z.reshape(S * C1, W + 2)

        # host combine: re-bucket the host's combined rows by dest host
        valid = (zf[:, 0] & jnp.uint32(1)).astype(jnp.bool_)
        g_host = (zf[:, W] // jnp.uint32(S)).astype(jnp.int32)
        j_slot = g_host // S                     # slot within this lane
        buf2, counts_j = K.wire_rebucket(zf, j_slot, valid, k_hosts,
                                         host_cap)
        host_demand = jnp.zeros((H,), dtype=jnp.int32)
        lane_hosts = t + jnp.arange(k_hosts, dtype=jnp.int32) * S
        host_demand = host_demand.at[
            jnp.where(lane_hosts < H, lane_hosts, H)].set(
            counts_j, mode="drop")

        # hop 2: ONE aggregated inter-host exchange (H-1 ppermutes,
        # each moving every host's combined block for offset d)
        out_dcn = jnp.zeros((H, host_cap, W + 2), dtype=x.dtype)
        own = jnp.take(buf2, jnp.clip(h // S, 0, k_hosts - 1), axis=0)
        own = jnp.where(h % S == t, own, jnp.zeros((), dtype=own.dtype))
        out_dcn = out_dcn.at[h].set(own)
        for d in range(1, H):
            g = (h + d) % H
            blk = jnp.take(buf2, jnp.clip(g // S, 0, k_hosts - 1),
                           axis=0)
            blk = jnp.where(g % S == t, blk,
                            jnp.zeros((), dtype=blk.dtype))
            perm = [(hh * S + ((hh + d) % H) % S,
                     ((hh + d) % H) * S + hh % S) for hh in range(H)]
            recv = self._pp(blk, axis, perm)
            out_dcn = out_dcn.at[(h - d) % H].set(recv)

        # hop 3: ICI scatter to the destination segment
        f3 = out_dcn.reshape(H * host_cap, W + 2)
        valid3 = (f3[:, 0] & jnp.uint32(1)).astype(jnp.bool_)
        s_local = (f3[:, W] % jnp.uint32(S)).astype(jnp.int32)
        C3 = two_level_lane_rows(n, H, B)        # proven bound, no check
        buf3, _ = K.wire_rebucket(f3, s_local, valid3, S, C3)
        recv3 = self._intra_all_to_all(buf3, axis)
        ff = recv3.reshape(S * C3, W + 2)

        # final placement: the flat layout's exact slot (src*B + rank)
        validf = (ff[:, 0] & jnp.uint32(1)).astype(jnp.bool_)
        origf = ff[:, W + 1].astype(jnp.int32)
        slotf = jnp.where(validf, origf, n * B)
        out = jnp.zeros((n * B, W), dtype=x.dtype)
        out = out.at[slotf].set(ff[:, :W], mode="drop")
        return out.reshape(n, B, W), host_demand


def two_level_lane_rows(nseg: int, n_hosts: int,
                        bucket_cap: int) -> int:
    """Rows one hop-1/hop-3 lane buffer holds: the PROVEN bound
    ceil(H/S)·S·B (every per-segment-pair bucket is capped at B by the
    caller's rank discipline). The ONE place the lane algebra lives —
    the transport sizes its staging with it, obs/capacity itemizes it,
    and the benches' byte models derive from it, so the hop structure
    cannot drift between the implementation and its accounting."""
    S = nseg // n_hosts
    return -(-n_hosts // S) * S * bucket_cap


def two_level_wire_model(nseg: int, n_hosts: int, bucket_cap: int,
                         host_bucket_cap: int, row_bytes: int) -> dict:
    """Analytic per-redistribute byte split for the TWO-LEVEL exchange:
    DCN carries one aggregated block per ordered host pair at the host
    rung; the hop-1/hop-3 lane staging (send + receive each) rides ICI.
    Every row carries the two u32 route words on both hops."""
    S = nseg // n_hosts
    rb2 = row_bytes + 8                  # + dest/slot route words
    lane = two_level_lane_rows(nseg, n_hosts, bucket_cap)
    return {
        "dcn_bytes": n_hosts * (n_hosts - 1) * host_bucket_cap * rb2,
        "ici_bytes": 2 * nseg * (S - 1) * lane * rb2,
    }


def flat_wire_model(nseg: int, n_hosts: int, bucket_cap: int,
                    row_bytes: int) -> dict:
    """FLAT all_to_all byte split under the same host grouping: every
    cross-host (source segment → destination segment) block crosses DCN
    padded to the pair rung; same-host blocks ride ICI."""
    S = nseg // n_hosts
    return {
        "dcn_bytes": nseg * (nseg - S) * bucket_cap * row_bytes,
        "ici_bytes": nseg * (S - 1) * bucket_cap * row_bytes,
    }


def hier_topology(cfg, n_segments: int, device_ids=None):
    """The two-level selection gate: the HostTopology motion should
    split over, or None for flat. Flat when the feature is off, the
    transport is not the packed xla path, the cluster is one host, or
    the layout is not uniform-contiguous (degraded survivor meshes).
    ``auto`` vs ``on`` differ only in the per-motion size gate the
    DISTRIBUTOR applies when stamping host rungs
    (interconnect.hier_min_block_bytes) — topology legality is
    identical. Epoch-aware by construction: host_topology re-derives
    from the live device list every call, and compiled two-level
    programs are keyed by topology epoch in the shared cache tier."""
    ic = cfg.interconnect
    mode = getattr(ic, "hierarchical", "off")
    if mode not in ("auto", "on"):
        return None
    if ic.backend != "xla" or not ic.packed_wire:
        return None
    from cloudberry_tpu.parallel.mesh import host_topology

    try:
        topo = host_topology(n_segments, device_ids)
    except Exception:
        return None     # stale/odd restriction: mesh build will report
    if topo.n_hosts < 2 or n_segments % topo.n_hosts != 0 \
            or not topo.uniform_contiguous():
        return None
    return topo


def make_transport(backend: str, n_segments: int, chunks: int = 1,
                   topo=None):
    """``topo`` (a HostTopology from hier_topology) selects the
    two-level transport; None keeps the flat vtable choice."""
    if topo is not None:
        return HierarchicalCollectives(topo)
    if backend == "xla":
        return XlaCollectives()
    if backend == "ring":
        return RingCollectives(n_segments, chunks=chunks)
    raise ValueError(f"unknown interconnect backend {backend!r} "
                     "(known: xla, ring)")
