"""Swappable motion transports — the ic_modules.c vtable analog.

The reference selects an interconnect implementation through a vtable
(contrib/interconnect/ic_modules.c:26-160: UDP / TCP / proxy share one
motion API). Under the one-XLA-program model every transport must still
be XLA collectives — but WHICH collective formulation is a real choice
on TPU hardware:

- ``xla``: the compiler's native ``all_gather`` / ``all_to_all`` /
  ``psum`` — XLA picks the algorithm (default).
- ``ring``: ``ppermute``-composed collectives. all_gather and psum are
  true rings — N−1 nearest-neighbor shift-and-accumulate steps, the
  systolic pattern that rides ICI links on torus topologies (and the
  building block of ring attention). all_to_all uses one distance-k
  ppermute per round (minimal data motion; the hardware routes each
  rotation), not strictly neighbor hops. Either way it is a second
  independent implementation that cross-checks the first (tests assert
  bit-identical results against XLA's).

Both implement one interface, chosen by ``interconnect.backend``; the
interconnect bench (tools/ic_bench.py) measures either, so the
backends can be compared on real hardware without the executor.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class XlaCollectives:
    """XLA's native collectives (the compiler schedules the algorithm)."""

    name = "xla"

    def all_gather(self, x, axis):
        return jax.lax.all_gather(x, axis, axis=0, tiled=True)

    def all_to_all(self, x, axis):
        """x: (nseg, ...) per-destination blocks -> (nseg, ...) received."""
        return jax.lax.all_to_all(x, axis, split_axis=0, concat_axis=0,
                                  tiled=False)

    def psum(self, x, axis):
        return jax.lax.psum(x, axis)

    def pmax(self, x, axis):
        return jax.lax.pmax(x, axis)


class RingCollectives:
    """ppermute-composed collectives (see module docstring: all_gather
    and psum are true neighbor rings; all_to_all rotates by k).

    ``chunks`` > 1 splits each all_to_all block along its ROW axis into
    that many independent contiguous slices — one ppermute per
    (hop, chunk) — so the compiler can overlap hop-k's rotation of one
    chunk with the placement of the previous chunk (the software-
    pipelined ring; profitable on real ICI links for the large packed
    motion buffers, a wash for the small ones). The row axis is the
    bucket capacity, a power-of-two rung, so any pow2 chunk count
    divides it; an indivisible count falls back to whole-block hops.
    Chunking never changes results: the slices are disjoint and
    reassembled in order."""

    name = "ring"

    def __init__(self, n: int, chunks: int = 1):
        self.n = n
        self.chunks = max(int(chunks), 1)

    def _shift(self, x, axis, by: int = 1):
        perm = [(i, (i + by) % self.n) for i in range(self.n)]
        return jax.lax.ppermute(x, axis, perm)

    def all_gather(self, x, axis):
        # accumulate blocks while rotating: after k hops this segment
        # holds the block of segment (i - k) mod n; place each into its
        # global slot so the result matches all_gather(tiled=True)
        idx = jax.lax.axis_index(axis)
        n = self.n
        rows = x.shape[0]
        out = jnp.zeros((n * rows,) + x.shape[1:], dtype=x.dtype)
        cur = x
        for k in range(n):
            src = (idx - k) % n
            out = jax.lax.dynamic_update_slice_in_dim(
                out, cur, src * rows, axis=0)
            if k + 1 < n:
                cur = self._shift(cur, axis)
        return out

    def all_to_all(self, x, axis):
        # x[(dest, ...)]: send block d to segment d. Rotate k hops so
        # each segment receives the block addressed to it from the
        # segment k behind it on the ring.
        idx = jax.lax.axis_index(axis)
        n = self.n
        out = jnp.zeros_like(x)
        # a block is (rows, ...) once the destination axis is selected;
        # chunk along the contiguous row axis (bucket capacity — a pow2
        # rung under the capacity ladder, so pow2 chunk counts divide it)
        nch = self.chunks if (x.ndim > 1
                              and x.shape[1] % self.chunks == 0) else 1
        for k in range(n):
            # after shifting by k, this segment sees the block that
            # segment (idx - k) addressed to destination idx... select
            # our destination slot BEFORE shifting to move one block
            src = (idx - k) % n
            block = jnp.take(x, (idx + k) % n, axis=0)  # dest = idx + k
            if k == 0:
                moved = block
            elif nch > 1:
                # chunked hop: independent per-chunk ppermutes let the
                # scheduler start chunk c+1's rotation while chunk c is
                # being placed — a software pipeline over the slices
                parts = jnp.split(block, nch, axis=0)
                moved = jnp.concatenate(
                    [self._shift(p, axis, by=k) for p in parts], axis=0)
            else:
                moved = self._shift(block, axis, by=k)
            out = out.at[src].set(moved)
        return out

    def psum(self, x, axis):
        acc = x
        cur = x
        for _ in range(self.n - 1):
            cur = self._shift(cur, axis)
            acc = acc + cur
        return acc

    def pmax(self, x, axis):
        acc = x
        cur = x
        for _ in range(self.n - 1):
            cur = self._shift(cur, axis)
            acc = jnp.maximum(acc, cur)
        return acc


def make_transport(backend: str, n_segments: int, chunks: int = 1):
    if backend == "xla":
        return XlaCollectives()
    if backend == "ring":
        return RingCollectives(n_segments, chunks=chunks)
    raise ValueError(f"unknown interconnect backend {backend!r} "
                     "(known: xla, ring)")
