"""Device mesh — the gp_segment_configuration analog.

The reference's cluster topology is a catalog of N segment postmasters
(cdbutil.c getCdbComponentInfo); here it is a jax.sharding.Mesh with one
``seg`` axis: mesh slot ↔ segment. Multi-host later extends this to a
(host, seg) mesh with DCN between hosts; the executor only ever names the
``seg`` axis, so that change is local to this module.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh


SEG_AXIS = "seg"


def segment_mesh(n_segments: int) -> Mesh:
    devices = jax.devices()
    if len(devices) < n_segments:
        raise RuntimeError(
            f"config asks for {n_segments} segments but only "
            f"{len(devices)} devices are visible; for tests set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n_segments}")
    import numpy as np

    return Mesh(np.asarray(devices[:n_segments]), (SEG_AXIS,))
