"""Device mesh — the gp_segment_configuration analog.

The reference's cluster topology is a catalog of N segment postmasters
(cdbutil.c getCdbComponentInfo) wired by a socket interconnect
(contrib/interconnect/udp/ic_udpifc.c); here it is a jax.sharding.Mesh
with one ``seg`` axis: mesh slot ↔ segment.

Multi-host (the DCN path): each host process calls ``init_distributed``
(the gpinitsystem / interconnect-setup analog) before creating a session.
After ``jax.distributed.initialize`` the device list is GLOBAL — the
segment mesh then spans hosts, and XLA routes intra-host collectives over
ICI and inter-host collectives over DCN (Gloo on CPU test clusters) with
no change anywhere else in the engine: the executor only ever names the
``seg`` axis. Segments stay stateless (data placement is recomputed from
shared/deterministic storage), so there is no per-segment WAL to ship —
a failed host re-runs statements against pinned snapshots.
"""

from __future__ import annotations

import os

import jax
from jax.sharding import Mesh

SEG_AXIS = "seg"


def init_distributed(coordinator: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None) -> None:
    """Join (or start) a multi-host cluster. Arguments default to the
    CBTPU_COORDINATOR / CBTPU_NUM_PROCS / CBTPU_PROC_ID environment —
    this engine's gp_segment_configuration bootstrap. Idempotent; a
    single-host run (no coordinator configured) is a no-op."""
    if getattr(init_distributed, "_done", False):
        return
    coordinator = coordinator or os.environ.get("CBTPU_COORDINATOR")
    if coordinator is None:
        return
    num_processes = int(num_processes
                       or os.environ.get("CBTPU_NUM_PROCS", "1"))
    process_id = int(process_id
                     if process_id is not None
                     else os.environ.get("CBTPU_PROC_ID", "0"))
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    init_distributed._done = True  # type: ignore[attr-defined]


def segment_mesh(n_segments: int, device_ids=None) -> Mesh:
    """Mesh over the first n_segments GLOBAL devices (all hosts).
    ``device_ids`` restricts to surviving devices (by index into
    jax.devices()) after a probe found losses — a real loss leaves a hole
    mid-list, so the degraded mesh must skip it, not just shrink."""
    devices = jax.devices()
    if device_ids is not None:
        devices = [devices[i] for i in device_ids if i < len(devices)]
    if len(devices) < n_segments:
        raise RuntimeError(
            f"config asks for {n_segments} segments but only "
            f"{len(devices)} devices are visible; for tests set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n_segments}")
    chosen = devices[:n_segments]
    if jax.process_count() > 1:
        # every host must own at least one mesh segment: a host outside
        # the mesh could neither feed its shards nor read results
        owners = {int(getattr(d, "process_index", 0)) for d in chosen}
        if owners != set(range(jax.process_count())):
            raise RuntimeError(
                f"n_segments={n_segments} covers only hosts "
                f"{sorted(owners)} of {jax.process_count()}; every host "
                "must own at least one segment (raise n_segments or "
                "shrink the cluster)")
    import numpy as np

    return Mesh(np.asarray(chosen), (SEG_AXIS,))


def mesh_topology(n_segments: int) -> dict:
    """Host → segment layout (the gp_segment_configuration view)."""
    devices = jax.devices()[:n_segments]
    hosts: dict[int, list[int]] = {}
    for i, d in enumerate(devices):
        hosts.setdefault(int(getattr(d, "process_index", 0)), []).append(i)
    return {
        "n_segments": n_segments,
        "n_hosts": max(len(hosts), 1),
        "this_host": jax.process_index(),
        "segments_by_host": hosts,
    }
