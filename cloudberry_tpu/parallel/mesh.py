"""Device mesh — the gp_segment_configuration analog.

The reference's cluster topology is a catalog of N segment postmasters
(cdbutil.c getCdbComponentInfo) wired by a socket interconnect
(contrib/interconnect/udp/ic_udpifc.c); here it is a jax.sharding.Mesh
with one ``seg`` axis: mesh slot ↔ segment.

Multi-host (the DCN path): each host process calls ``init_distributed``
(the gpinitsystem / interconnect-setup analog) before creating a session.
After ``jax.distributed.initialize`` the device list is GLOBAL — the
segment mesh then spans hosts, and XLA routes intra-host collectives over
ICI and inter-host collectives over DCN (Gloo on CPU test clusters) with
no change anywhere else in the engine: the executor only ever names the
``seg`` axis. Segments stay stateless (data placement is recomputed from
shared/deterministic storage), so there is no per-segment WAL to ship —
a failed host re-runs statements against pinned snapshots.

``HostTopology`` is the first-class host → segment layout (the promoted
``segments_by_host`` view): the two-level Motion path
(parallel/transport.py HierarchicalCollectives) consults it to split
every collective into an intra-host (ICI) and an inter-host (DCN) hop.
It is DERIVED state, never stored: ``host_topology`` recomputes it from
the live device list (plus any survivor restriction) on demand, so an
epoch flip — expand/shrink/failover via parallel/topology.py — re-derives
it the moment the new epoch's first plan compiles; the shared cache tier
already keys compiled programs by topology epoch, so a stale host layout
can never serve a post-cutover statement. ``CBTPU_FORCE_HOSTS=N``
partitions a single-process mesh into N simulated hosts (contiguous,
uniform) — the CPU test/bench stand-in for a real multi-host split.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass

import jax
from jax.sharding import Mesh

SEG_AXIS = "seg"

# host-topology derivation cache (device lists are stable between epoch
# flips; the key carries everything the derivation reads)
# graftlint: the lock is module-level like faultinject._lock and carries
# a witness rank (lint/config.py WITNESS_ORDER rank 4 — innermost leaf)
_topo_lock = threading.Lock()
_topo_cache: dict = {}


class DeviceRestrictionError(RuntimeError):
    """A ``device_ids`` restriction named devices the mesh cannot use.

    ``kind`` distinguishes the two failure stories:
    - ``"stale"``  — an id at or past the live device count: the id was
      plausibly valid once (before a shrink / device loss) and the caller
      is holding an out-of-date survivor list; re-probe and re-derive.
    - ``"invalid"`` — a negative or duplicate id: the restriction list
      itself is malformed, no probe will fix it.

    Before this error existed, ``segment_mesh`` silently SKIPPED
    out-of-range ids — a stale survivor list would quietly build a
    smaller mesh and every placement assumption downstream went wrong.
    """

    def __init__(self, kind: str, message: str):
        super().__init__(message)
        self.kind = kind


def init_distributed(coordinator: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None) -> None:
    """Join (or start) a multi-host cluster. Arguments default to the
    CBTPU_COORDINATOR / CBTPU_NUM_PROCS / CBTPU_PROC_ID environment —
    this engine's gp_segment_configuration bootstrap. Idempotent; a
    single-host run (no coordinator configured) is a no-op."""
    if getattr(init_distributed, "_done", False):
        return
    coordinator = coordinator or os.environ.get("CBTPU_COORDINATOR")
    if coordinator is None:
        return
    num_processes = int(num_processes
                       or os.environ.get("CBTPU_NUM_PROCS", "1"))
    process_id = int(process_id
                     if process_id is not None
                     else os.environ.get("CBTPU_PROC_ID", "0"))
    # XLA:CPU only implements cross-process collectives through a
    # pluggable backend (Gloo in jaxlib) — without this, any program
    # whose device assignment spans processes dies at dispatch with
    # "Multiprocess computations aren't implemented on the CPU
    # backend". Must be set before the CPU client spins up, which is
    # why it lives here (workers call init_distributed before any jax
    # op). TPU pods ignore it: their DCN collectives are native.
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):  # older/newer jax: best effort
        pass
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    init_distributed._done = True  # type: ignore[attr-defined]


def _check_device_ids(device_ids, n_devices: int) -> None:
    """The typed replacement for the old silent ``if i < len(devices)``
    skip: holes mid-list are an error the caller must see."""
    seen = set()
    for i in device_ids:
        if i < 0:
            raise DeviceRestrictionError(
                "invalid",
                f"device restriction contains negative id {i} — the "
                "restriction list is malformed, not stale")
        if i in seen:
            raise DeviceRestrictionError(
                "invalid",
                f"device restriction names id {i} twice — the "
                "restriction list is malformed, not stale")
        seen.add(i)
    stale = sorted(i for i in device_ids if i >= n_devices)
    if stale:
        raise DeviceRestrictionError(
            "stale",
            f"device restriction names id(s) {stale} but only "
            f"{n_devices} devices are visible — the ids are stale "
            "(devices lost / cluster shrunk since the restriction was "
            "derived); re-probe and rebuild the survivor list")


def segment_mesh(n_segments: int, device_ids=None) -> Mesh:
    """Mesh over the first n_segments GLOBAL devices (all hosts).
    ``device_ids`` restricts to surviving devices (by index into
    jax.devices()) after a probe found losses — a real loss leaves a hole
    mid-list, so the degraded mesh must skip it, not just shrink.
    A restriction naming devices that no longer exist raises the typed
    DeviceRestrictionError instead of silently building a smaller mesh."""
    devices = jax.devices()
    if device_ids is not None:
        _check_device_ids(device_ids, len(devices))
        devices = [devices[i] for i in device_ids]
    if len(devices) < n_segments:
        raise RuntimeError(
            f"config asks for {n_segments} segments but only "
            f"{len(devices)} devices are visible; for tests set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n_segments}")
    chosen = devices[:n_segments]
    if jax.process_count() > 1:
        # every host must own at least one mesh segment: a host outside
        # the mesh could neither feed its shards nor read results
        owners = {int(getattr(d, "process_index", 0)) for d in chosen}
        if owners != set(range(jax.process_count())):
            raise RuntimeError(
                f"n_segments={n_segments} covers only hosts "
                f"{sorted(owners)} of {jax.process_count()}; every host "
                "must own at least one segment (raise n_segments or "
                "shrink the cluster)")
    import numpy as np

    return Mesh(np.asarray(chosen), (SEG_AXIS,))


# --------------------------------------------------------- host topology


@dataclass(frozen=True)
class HostTopology:
    """First-class host → segment layout (the promoted segments_by_host
    view of ``mesh_topology``). Immutable and DERIVED: rebuild it via
    ``host_topology`` whenever the device set may have changed (epoch
    flips do — see module docstring)."""

    n_segments: int
    # host -> tuple of global segment indices it owns (ascending)
    segs_by_host: tuple
    # True when the grouping came from CBTPU_FORCE_HOSTS (simulated
    # hosts on one process) rather than real process indices
    forced: bool = False

    @property
    def n_hosts(self) -> int:
        return len(self.segs_by_host)

    @property
    def segs_per_host(self) -> int:
        """Segments per host when UNIFORM, else 0 (the two-level path
        requires uniformity; a ragged cluster stays on flat motion)."""
        sizes = {len(s) for s in self.segs_by_host}
        return len(self.segs_by_host[0]) if len(sizes) == 1 else 0

    def host_of(self, seg: int) -> int:
        for h, segs in enumerate(self.segs_by_host):
            if seg in segs:
                return h
        raise KeyError(seg)

    def uniform_contiguous(self) -> bool:
        """True when host h owns exactly segments [h*S, (h+1)*S) — the
        layout HierarchicalCollectives' static lane algebra relies on
        (jax.devices() orders by process index, so real clusters are
        contiguous by construction; a degraded survivor restriction can
        break it, and then motion stays flat)."""
        S = self.segs_per_host
        if S == 0:
            return False
        if S * self.n_hosts != self.n_segments:
            # the hosts don't COVER n_segments (fewer visible devices
            # than requested segments) — per-host contiguity would pass
            # while the lane algebra's S = nseg // n_hosts disagrees
            # with the real grouping; never let that stamp host caps
            return False
        for h, segs in enumerate(self.segs_by_host):
            if tuple(segs) != tuple(range(h * S, (h + 1) * S)):
                return False
        return True

    def as_dict(self) -> dict:
        return {
            "n_segments": self.n_segments,
            "n_hosts": self.n_hosts,
            "segs_per_host": self.segs_per_host,
            "uniform_contiguous": self.uniform_contiguous(),
            "forced": self.forced,
            "segments_by_host": {h: list(s)
                                 for h, s in enumerate(self.segs_by_host)},
        }


def host_topology(n_segments: int, device_ids=None) -> HostTopology:
    """Derive the HostTopology for the FIRST n_segments live devices
    (after the optional survivor restriction — the same selection
    ``segment_mesh`` makes, so mesh and topology can never disagree).

    ``CBTPU_FORCE_HOSTS=N`` overrides with N simulated contiguous hosts
    (single-process CPU meshes have one real host; the env knob is how
    tools/ic_bench.py and the tests exercise the DCN-shaped path without
    a cluster). The derivation is cached per (nseg, restriction, force)
    — device lists only change at epoch flips, which change the key."""
    force = os.environ.get("CBTPU_FORCE_HOSTS")
    key = (n_segments,
           tuple(device_ids) if device_ids is not None else None,
           force)
    with _topo_lock:
        hit = _topo_cache.get(key)
    if hit is not None:
        return hit
    if force:
        n_hosts = max(int(force), 1)
        if n_segments % n_hosts != 0:
            raise ValueError(
                f"CBTPU_FORCE_HOSTS={n_hosts} does not divide "
                f"n_segments={n_segments} (simulated hosts are uniform "
                "by construction)")
        S = n_segments // n_hosts
        topo = HostTopology(
            n_segments,
            tuple(tuple(range(h * S, (h + 1) * S))
                  for h in range(n_hosts)),
            forced=True)
    else:
        devices = jax.devices()
        if device_ids is not None:
            _check_device_ids(device_ids, len(devices))
            devices = [devices[i] for i in device_ids]
        hosts: dict[int, list[int]] = {}
        for i, d in enumerate(devices[:n_segments]):
            hosts.setdefault(int(getattr(d, "process_index", 0)),
                             []).append(i)
        topo = HostTopology(
            n_segments,
            tuple(tuple(sorted(hosts[h])) for h in sorted(hosts)))
    with _topo_lock:
        if len(_topo_cache) >= 32:
            _topo_cache.pop(next(iter(_topo_cache)))
        _topo_cache[key] = topo
    return topo


def mesh_topology(n_segments: int) -> dict:
    """Host → segment layout (the gp_segment_configuration view), now a
    rendering of HostTopology. NOTE: reports REAL process grouping plus
    ``this_host``; the forced simulation knob applies here too so the
    observability view matches what the motion layer will do."""
    topo = host_topology(n_segments)
    return {
        "n_segments": n_segments,
        "n_hosts": topo.n_hosts,
        "this_host": jax.process_index(),
        "segments_by_host": {h: list(s)
                             for h, s in enumerate(topo.segs_by_host)},
    }
